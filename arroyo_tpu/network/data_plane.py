"""Inter-worker data plane: framed Arrow IPC batches over TCP.

Analog of the reference's custom network manager
(/root/reference/arroyo-worker/src/network_manager.rs): edges that cross
worker processes are carried on one TCP socket per worker pair, with a frame
header addressing the edge by ``Quad`` (src operator, src subtask, dst
operator, dst subtask) (network_manager.rs:70-119), demuxed into per-edge
queues on the receiving side (:25-152).

Differences from the reference, by design:
* payloads are **Arrow IPC** record batches (columnar, zero-parse into numpy)
  instead of bincode'd single records — the batch is the unit of flow;
* this is the **DCN/host path only**: shuffles *within* a mesh slice ride ICI
  via XLA collectives (parallel/mesh_window.py); this plane connects hosts.

Frame layout (little-endian):
  u32 magic | u16 kind | u32 src_op_len | src_op | u32 src_idx
  | u32 dst_op_len | dst_op | u32 dst_idx | u64 payload_len | payload
kind: 0 = data (arrow), 1 = control message (msgpack watermark/barrier/...).
"""

from __future__ import annotations

import asyncio
import io
import logging
import struct
import time as _time
from dataclasses import dataclass
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

import msgpack
import numpy as np

from ..types import (
    Batch,
    CheckpointBarrier,
    Message,
    MessageKind,
    Watermark,
    WatermarkKind,
)

logger = logging.getLogger(__name__)

from ..obs import profiler  # noqa: E402
from ..obs.metrics import (BYTES_RECV, BYTES_SENT, FLUSH_LATENCY,  # noqa: E402
                           FRAME_BYTES)

MAGIC = 0xA770_10CB
KIND_DATA = 0
KIND_CONTROL = 1
# schema-less continuation frame: one Arrow record-batch message decoded
# against the schema delivered by the edge's last KIND_DATA frame.
# Frames on one TCP connection arrive in order, so the receiver's cached
# per-edge schema is always the one this batch was encoded under.
KIND_DATA_BATCH = 2
# latency-observatory stamp flag on the u16 frame kind: when set, an
# 8-byte little-endian ingest stamp (micros) rides between the frame
# header and the Arrow payload.  A side-channel prefix — NOT schema
# metadata — so a sampled batch never flips the per-edge schema cache
# and the KIND_DATA_BATCH continuation fast path is undisturbed.
KIND_STAMP_FLAG = 0x100

Quad = Tuple[str, int, str, int]


def _arrow_parts(batch: Batch):
    """(schema-with-metadata, RecordBatch) for one Batch — the shared
    front half of the full-stream and continuation encoders."""
    import pyarrow as pa

    arrays = batch.arrow_arrays()
    meta = {b"key_cols": ",".join(batch.key_cols).encode()}
    if batch.key_hash is not None:
        meta[b"has_key_hash"] = b"1"
        arrays["__key_hash"] = pa.array(batch.key_hash, type=pa.uint64())
    rb = pa.record_batch(list(arrays.values()),
                         names=list(arrays.keys()))
    rb = rb.replace_schema_metadata(meta)
    return rb.schema, rb


def _stream_bytes(rb) -> bytes:
    """Full Arrow IPC stream (schema + one batch) — the KIND_DATA
    payload, written once per edge stream (and again on schema change)."""
    import pyarrow as pa

    buf = io.BytesIO()
    with pa.ipc.new_stream(buf, rb.schema) as w:
        w.write_batch(rb)
    return buf.getvalue()


def _encode_batch(batch: Batch) -> bytes:
    schema, rb = _arrow_parts(batch)
    return _stream_bytes(rb)


def _table_to_batch(table, meta) -> Batch:
    kh = None
    if (meta or {}).get(b"has_key_hash") == b"1":
        kh = table.column("__key_hash").combine_chunks().to_numpy(
            zero_copy_only=False).astype(np.uint64)
        table = table.drop_columns(["__key_hash"])
    batch = Batch.from_arrow(table)
    key_cols = (meta or {}).get(b"key_cols", b"").decode()
    batch.key_hash = kh
    batch.key_cols = tuple(key_cols.split(",")) if key_cols else ()
    return batch


def _decode_batch_full(data: bytes):
    """(Batch, schema) from a full KIND_DATA stream payload; the schema
    is what continuation frames on the same edge decode against."""
    import pyarrow as pa

    with pa.ipc.open_stream(io.BytesIO(data)) as r:
        table = r.read_all()
    schema = table.schema
    return _table_to_batch(table, schema.metadata), schema


def _decode_batch(data: bytes) -> Batch:
    return _decode_batch_full(data)[0]


def _decode_batch_continuation(data: bytes, schema) -> Batch:
    import pyarrow as pa

    rb = pa.ipc.read_record_batch(pa.py_buffer(data), schema)
    return _table_to_batch(pa.Table.from_batches([rb], schema=schema),
                           schema.metadata)


def encode_message(msg: Message) -> Tuple[int, bytes]:
    if msg.kind == MessageKind.RECORD:
        return KIND_DATA, _encode_batch(msg.batch)
    if msg.kind == MessageKind.WATERMARK:
        payload = {"k": "wm", "idle": msg.watermark.is_idle,
                   "t": int(msg.watermark.time)}
    elif msg.kind == MessageKind.BARRIER:
        b = msg.barrier
        payload = {"k": "barrier", "epoch": b.epoch, "min_epoch": b.min_epoch,
                   "ts": b.timestamp, "stop": b.then_stop}
    elif msg.kind == MessageKind.STOP:
        payload = {"k": "stop"}
    else:
        payload = {"k": "eod"}
    return KIND_CONTROL, msgpack.packb(payload)


def decode_message(kind: int, data: bytes) -> Message:
    if kind == KIND_DATA:
        return Message.record(_decode_batch(data))
    p = msgpack.unpackb(data)
    if p["k"] == "wm":
        wm = Watermark.idle() if p["idle"] else Watermark.event_time(p["t"])
        return Message.wm(wm)
    if p["k"] == "barrier":
        return Message.barrier_msg(CheckpointBarrier(
            p["epoch"], p["min_epoch"], p["ts"], p["stop"]))
    if p["k"] == "stop":
        return Message.stop()
    return Message.end_of_data()


def _write_frame(writer: asyncio.StreamWriter, quad: Quad, kind: int,
                 payload, stamp: Optional[int] = None) -> None:
    """``payload`` may be any bytes-like (bytes, memoryview over an
    Arrow buffer): header and payload go out as two writes so a large
    batch payload is never copied into a concatenated frame — the
    transport buffer is the only copy between Arrow memory and the
    socket.  ``stamp`` (latency-observatory ingest micros) sets the
    KIND_STAMP_FLAG bit and rides as 8 extra bytes between header and
    payload — outside ``plen`` and outside the Arrow stream."""
    src_op, src_idx, dst_op, dst_idx = quad
    so, do = src_op.encode(), dst_op.encode()
    if stamp is not None:
        kind |= KIND_STAMP_FLAG
    header = struct.pack(
        f"<IHI{len(so)}sII{len(do)}sIQ",
        MAGIC, kind, len(so), so, src_idx, len(do), do, dst_idx, len(payload))
    writer.write(header)
    if stamp is not None:
        writer.write(struct.pack("<q", stamp))
    writer.write(payload)


async def _read_frame(reader: asyncio.StreamReader
                      ) -> Optional[Tuple[Quad, int, bytes, Optional[int]]]:
    try:
        head = await reader.readexactly(10)
        magic, kind, so_len = struct.unpack("<IHI", head)
        if magic != MAGIC:
            raise ValueError(f"bad frame magic {magic:#x}")
        so = (await reader.readexactly(so_len)).decode()
        src_idx, do_len = struct.unpack("<II", await reader.readexactly(8))
        do = (await reader.readexactly(do_len)).decode()
        dst_idx, plen = struct.unpack("<IQ", await reader.readexactly(12))
        stamp: Optional[int] = None
        if kind & KIND_STAMP_FLAG:
            kind &= ~KIND_STAMP_FLAG
            stamp = struct.unpack("<q",
                                  await reader.readexactly(8))[0]
        payload = await reader.readexactly(plen)
        return (so, src_idx, do, dst_idx), kind, payload, stamp
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None


class NetworkManager:
    """Opens a listener for incoming edges and maintains one outgoing
    connection per remote worker (NetworkManager::{open_listener, connect,
    start}, network_manager.rs:221-307)."""

    def __init__(self, job_id: str = "") -> None:
        from ..analysis.sanitizer import maybe_sanitizer

        self.job_id = job_id
        # arroyosan: decode-side invariants (per-quad schema stability +
        # watermark monotonicity); None unless ARROYO_SANITIZE armed it
        self.sanitizer = maybe_sanitizer("data-plane")
        self.senders: Dict[Quad, asyncio.Queue] = {}
        self.server: Optional[asyncio.AbstractServer] = None
        self.port: Optional[int] = None
        self._out_writers: Dict[str, asyncio.StreamWriter] = {}
        self._in_writers: list = []  # accepted connections, closed on close()
        self._pending: Dict[Quad, list] = {}  # frames ahead of registration
        # receive side of the encode fast path: per-edge Arrow schema
        # from the last full (KIND_DATA) frame, which KIND_DATA_BATCH
        # continuations decode against
        self._edge_schemas: Dict[Quad, Any] = {}
        # labeled prometheus children resolved once per quad, off hot path
        self._metric_children: Dict[Tuple[str, str, int], Any] = {}

    def _labeled_child(self, factory, name: str, help_: str,
                       op_id: str, idx: int):
        """Labeled prometheus child per (metric, edge endpoint), with the
        reference's task labels (arroyo-types/src/lib.rs:736-737)."""
        key = (name, op_id, idx)
        child = self._metric_children.get(key)
        if child is None:
            child = factory(name, help_).labels(
                job_id=self.job_id, operator_id=op_id,
                subtask_idx=str(idx), operator_name=op_id)
            self._metric_children[key] = child
        return child

    def _bytes_counter(self, name: str, op_id: str, idx: int):
        from ..obs.metrics import _counter

        return self._labeled_child(
            _counter, name, "serialized bytes on the data plane", op_id, idx)

    def _frame_histogram(self, name: str, help_: str, op_id: str, idx: int):
        from ..obs.metrics import _histogram

        return self._labeled_child(_histogram, name, help_, op_id, idx)

    # -- receiving ---------------------------------------------------------

    def register_in_edge(self, quad: Quad, queue: asyncio.Queue) -> None:
        """Route incoming frames for ``quad`` to ``queue`` (Senders map,
        network_manager.rs:25-60).  Frames that raced ahead of registration
        were parked in ``_pending`` and are flushed here."""
        self.senders[quad] = queue
        for msg in self._pending.pop(quad, []):
            queue.put_nowait(msg)

    def _decode_frame(self, quad: Quad, kind: int, payload: bytes,
                      stamp: Optional[int] = None) -> Message:
        prof = profiler.active()
        if prof is None:
            return self._decode_frame_inner(quad, kind, payload, stamp)
        # receive-side Arrow decode: the egress/ingest host cost of a
        # cross-worker edge, charged to the DESTINATION operator
        frame = prof.begin(quad[2], "frame_decode")
        try:
            return self._decode_frame_inner(quad, kind, payload, stamp)
        finally:
            prof.end(frame)

    def _decode_frame_inner(self, quad: Quad, kind: int, payload: bytes,
                            stamp: Optional[int] = None) -> Message:
        san = self.sanitizer
        if kind == KIND_DATA:
            batch, schema = _decode_batch_full(payload)
            if san is not None and quad in self._edge_schemas:
                # a full frame mid-stream is legal only on a declared
                # schema change: re-seed the stability tracker so the
                # cached-schema continuation contract stays checkable
                san.reset_edge(quad)
            self._edge_schemas[quad] = schema
            if san is not None:
                san.on_record(quad, batch)
            batch.lat_stamp = stamp
            return Message.record(batch)
        if kind == KIND_DATA_BATCH:
            schema = self._edge_schemas.get(quad)
            if schema is None:
                # cannot happen on an ordered stream (the first data
                # frame per edge is always a full one) — fail loudly
                # rather than fabricate a schema
                raise ValueError(f"continuation frame for {quad} before "
                                 "any full frame delivered its schema")
            batch = _decode_batch_continuation(payload, schema)
            if san is not None:
                # continuation batches decode against the cached schema:
                # any layout drift here is wire corruption
                san.on_record(quad, batch)
            batch.lat_stamp = stamp
            return Message.record(batch)
        msg = decode_message(kind, payload)
        if san is not None and msg.kind == MessageKind.WATERMARK:
            san.on_watermark(quad, msg.watermark)
        return msg

    async def open_listener(self, host: str = "0.0.0.0", port: int = 0) -> int:
        async def on_conn(reader, writer):
            self._in_writers.append(writer)
            try:
                while True:
                    frame = await _read_frame(reader)
                    if frame is None:
                        break
                    quad, kind, payload, stamp = frame
                    self._bytes_counter(BYTES_RECV, quad[2], quad[3]).inc(
                        len(payload))
                    msg = self._decode_frame(quad, kind, payload, stamp)
                    q = self.senders.get(quad)
                    if q is None:
                        # receiver engine not built yet: park the frame
                        self._pending.setdefault(quad, []).append(msg)
                        continue
                    await q.put(msg)
            except AssertionError as e:
                # a decode-side sanitizer violation (SanitizerError is an
                # AssertionError) must not die as an unretrieved task
                # exception: log it loudly — it also stays visible on the
                # admin /sanitizer endpoint and in the violations counter
                # — and drop the connection so the peer sees the break
                logger.error("data-plane decode violation on %s: %s",
                             writer.get_extra_info("peername"), e)
            finally:
                writer.close()

        self.server = await asyncio.start_server(on_conn, host, port)
        self.port = self.server.sockets[0].getsockname()[1]
        return self.port

    # -- sending -----------------------------------------------------------

    async def connect(self, addr: str) -> None:
        if addr in self._out_writers:
            return
        host, port = addr.rsplit(":", 1)
        for attempt in range(30):
            try:
                _, writer = await asyncio.open_connection(host, int(port))
                break
            except OSError:
                await asyncio.sleep(0.2 * (attempt + 1))
        else:
            raise ConnectionError(f"cannot reach worker data plane at {addr}")
        self._out_writers[addr] = writer

    def remote_sender(self, addr: str, quad: Quad
                      ) -> Callable[[Message], Awaitable[None]]:
        """An OutQueue-compatible async send fn for a remote edge.

        Encode fast path: the Arrow IPC schema is written ONCE per edge
        stream — the first record frame (and any frame after a schema
        change) is a full stream, every other one a schema-less
        KIND_DATA_BATCH continuation the receiver decodes against its
        cached schema.  ``drain()`` is awaited only when the transport
        buffer crossed its high-water mark: ``StreamWriter.write`` is
        synchronous and hands bytes to the transport immediately, so
        draining under the mark was pure per-frame overhead (an await +
        lock round-trip) with no flow-control effect."""

        sent_counter = self._bytes_counter(BYTES_SENT, quad[0], quad[1])
        frame_bytes = self._frame_histogram(
            FRAME_BYTES, "serialized payload bytes per data-plane frame",
            quad[0], quad[1])
        flush_latency = self._frame_histogram(
            FLUSH_LATENCY, "socket drain seconds per high-water flush",
            quad[0], quad[1])
        state: Dict[str, Any] = {"schema": None}

        async def send(msg: Message) -> None:
            writer = self._out_writers[addr]
            prof = profiler.active()
            # Arrow encode + frame write: the data-plane half of the
            # emission-encode host cost, charged to the SOURCE operator
            enc = (prof.begin(quad[0], "frame_encode")
                   if prof is not None else None)
            try:
                stamp = None
                if msg.kind == MessageKind.RECORD:
                    stamp = msg.batch.lat_stamp
                    schema, rb = _arrow_parts(msg.batch)
                    prev = state["schema"]
                    if prev is not None and schema.equals(
                            prev, check_metadata=True):
                        kind = KIND_DATA_BATCH
                        # zero-copy egress: the Arrow buffer feeds the
                        # socket through a memoryview — no to_pybytes()
                        # copy of the whole batch per frame
                        payload = memoryview(rb.serialize())
                    else:
                        state["schema"] = schema
                        kind, payload = KIND_DATA, _stream_bytes(rb)
                else:
                    kind, payload = encode_message(msg)
                sent_counter.inc(len(payload))
                frame_bytes.observe(len(payload))
                # frames never interleave: _write_frame is one
                # synchronous writer.write call, so no lock is needed
                # for atomicity
                _write_frame(writer, quad, kind, payload, stamp)
            finally:
                # an encode failure must not leak the open frame: an
                # unclosed frame would absorb every later span on this
                # task as its "child" and corrupt attribution
                if enc is not None:
                    prof.end(enc)
            transport = writer.transport
            if transport is not None:
                high = transport.get_write_buffer_limits()[1]
                if transport.get_write_buffer_size() >= high:
                    t0 = _time.perf_counter()
                    wfr = (prof.begin(quad[0], "net_flush", wait=True)
                           if prof is not None else None)
                    try:
                        await writer.drain()
                    finally:
                        if wfr is not None:
                            prof.end(wfr)
                    # socket drain: the network half of backpressure
                    flush_latency.observe(_time.perf_counter() - t0)

        return send

    async def close(self) -> None:
        for w in self._out_writers.values():
            w.close()
        for w in self._in_writers:
            w.close()
        if self.server is not None:
            self.server.close()
            try:
                await asyncio.wait_for(self.server.wait_closed(), timeout=2)
            except asyncio.TimeoutError:
                pass
