"""Minimal asyncio HTTP/1.1 server with routing, JSON bodies, and SSE.

Plays the role axum plays for the reference's REST API
(/root/reference/arroyo-api/src/rest.rs:93-126) — no third-party web
framework is available in this image, and the surface we need (JSON CRUD
routes + one server-sent-events stream) is small enough to own.
"""

from __future__ import annotations

import asyncio
import json
import re
import traceback
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Awaitable, Callable, Dict, Optional
from urllib.parse import parse_qsl, urlsplit


class HttpError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


@dataclass
class Request:
    method: str
    path: str
    query: Dict[str, str]
    headers: Dict[str, str]
    body: bytes
    params: Dict[str, str] = field(default_factory=dict)

    def json(self) -> Any:
        if not self.body:
            return {}
        try:
            return json.loads(self.body)
        except json.JSONDecodeError as e:
            raise HttpError(400, f"invalid JSON body: {e}")


@dataclass
class Response:
    status: int = 200
    body: bytes = b""
    content_type: str = "application/json"
    headers: Dict[str, str] = field(default_factory=dict)

    @classmethod
    def json(cls, obj: Any, status: int = 200) -> "Response":
        return cls(status=status, body=json.dumps(obj).encode())


class SseResponse:
    """Handler return value that streams server-sent events."""

    def __init__(self, events: AsyncIterator[Dict[str, Any]]):
        self.events = events


Handler = Callable[[Request], Awaitable[Any]]

_STATUS_TEXT = {200: "OK", 201: "Created", 204: "No Content",
                400: "Bad Request", 404: "Not Found", 405: "Method Not "
                "Allowed", 409: "Conflict", 422: "Unprocessable Entity",
                500: "Internal Server Error"}


class Router:
    def __init__(self) -> None:
        # method -> list of (compiled path regex, handler)
        self.routes: Dict[str, list] = {}
        # (method, raw pattern, handler) in registration order — the
        # OpenAPI generator reads this
        self.patterns: list = []

    def route(self, method: str, pattern: str, handler: Handler) -> None:
        # '/v1/pipelines/{id}/jobs' -> named groups
        rx = re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern)
        self.routes.setdefault(method.upper(), []).append(
            (re.compile(f"^{rx}$"), handler))
        self.patterns.append((method.upper(), pattern, handler))

    def get(self, p: str):
        return lambda h: (self.route("GET", p, h), h)[1]

    def post(self, p: str):
        return lambda h: (self.route("POST", p, h), h)[1]

    def patch(self, p: str):
        return lambda h: (self.route("PATCH", p, h), h)[1]

    def put(self, p: str):
        return lambda h: (self.route("PUT", p, h), h)[1]

    def delete(self, p: str):
        return lambda h: (self.route("DELETE", p, h), h)[1]

    def match(self, method: str, path: str):
        for rx, handler in self.routes.get(method.upper(), []):
            m = rx.match(path)
            if m:
                return handler, m.groupdict()
        # distinguish 404 from 405 for better errors
        for routes in self.routes.values():
            for rx, _ in routes:
                if rx.match(path):
                    return None, {"__status__": "405"}
        return None, {"__status__": "404"}


class HttpServer:
    def __init__(self, router: Router):
        self.router = router
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._server = await asyncio.start_server(self._handle, host, port)
        return self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    line = await reader.readline()
                except ValueError:  # request line over the 64 KiB limit
                    self._write_response(writer, Response.json(
                        {"error": "request line too long"}, 400))
                    await writer.drain()
                    break
                if not line or line in (b"\r\n", b"\n"):
                    break
                parts = line.decode("latin1").strip().split(" ")
                if len(parts) < 2:
                    break
                method, target = parts[0], parts[1]
                headers: Dict[str, str] = {}
                bad_header = False
                while True:
                    try:
                        h = await reader.readline()
                    except ValueError:  # oversized header
                        bad_header = True
                        break
                    if not h or h in (b"\r\n", b"\n"):
                        break
                    k, _, v = h.decode("latin1").partition(":")
                    headers[k.strip().lower()] = v.strip()
                if bad_header:
                    self._write_response(writer, Response.json(
                        {"error": "header too long"}, 400))
                    await writer.drain()
                    break
                try:
                    length = int(headers.get("content-length", "0"))
                    if length < 0:
                        raise ValueError("negative content-length")
                except ValueError:
                    self._write_response(writer, Response.json(
                        {"error": "invalid Content-Length"}, 400))
                    await writer.drain()
                    break
                body = await reader.readexactly(length) if length else b""
                split = urlsplit(target)
                req = Request(method=method, path=split.path,
                              query=dict(parse_qsl(split.query)),
                              headers=headers, body=body)
                keep_alive = await self._dispatch(req, writer)
                if not keep_alive:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _dispatch(self, req: Request,
                        writer: asyncio.StreamWriter) -> bool:
        handler, params = self.router.match(req.method, req.path)
        if handler is None:
            status = int(params.get("__status__", "404"))
            self._write_response(writer, Response.json(
                {"error": _STATUS_TEXT[status]}, status))
            await writer.drain()
            return True
        req.params = params
        try:
            result = await handler(req)
        except HttpError as e:
            self._write_response(
                writer, Response.json({"error": e.message}, e.status))
            await writer.drain()
            return True
        except Exception:
            traceback.print_exc()
            self._write_response(writer, Response.json(
                {"error": "internal server error"}, 500))
            await writer.drain()
            return True

        if isinstance(result, SseResponse):
            await self._stream_sse(result, writer)
            return False  # SSE exhausts the connection
        if not isinstance(result, Response):
            result = Response.json(result)
        self._write_response(writer, result)
        await writer.drain()
        return True

    def _write_response(self, writer: asyncio.StreamWriter,
                        resp: Response) -> None:
        text = _STATUS_TEXT.get(resp.status, "Unknown")
        head = [f"HTTP/1.1 {resp.status} {text}",
                f"content-type: {resp.content_type}",
                f"content-length: {len(resp.body)}"]
        for k, v in resp.headers.items():
            head.append(f"{k}: {v}")
        head.append("\r\n")
        writer.write("\r\n".join(head).encode() + resp.body)

    async def _stream_sse(self, sse: SseResponse,
                          writer: asyncio.StreamWriter) -> None:
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"content-type: text/event-stream\r\n"
                     b"cache-control: no-cache\r\n"
                     b"connection: close\r\n\r\n")
        await writer.drain()
        try:
            async for event in sse.events:
                payload = json.dumps(event).encode()
                writer.write(b"data: " + payload + b"\n\n")
                await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass
