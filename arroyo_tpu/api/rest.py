"""REST API mirroring the reference's arroyo-api surface.

Route shape follows /root/reference/arroyo-api/src/rest.rs:93-126:
pipelines CRUD + validate (pipelines.rs:316-700), job listing with errors
and checkpoint details (jobs.rs:213-542), output tailing as server-sent
events over the controller's SubscribeToOutput stream (jobs.rs:465+,
rpc.proto:186), connection-table CRUD with connector schema validation
(connection_tables.rs), and the connector catalog (connectors.rs).

Postgres is replaced by sqlite (stdlib) — the API owns pipeline/job
metadata rows, the controller owns runtime state, exactly as in the
reference where the API writes rows the controller's db-poll picks up.
Here submission calls the controller directly (same process model as
LocalRunner deployments); the controller remains the single source of
truth for live job state.
"""

from __future__ import annotations

import asyncio
import json
import logging
import sqlite3
import time
import uuid
from typing import Any, AsyncIterator, Dict, Optional

from ..connectors.registry import list_connectors, validate_config
from ..controller.controller import ControllerServer
from ..controller.state_machine import JobState
from ..sql import Planner, SchemaProvider, SqlPlanError
from ..sql.compiler import SqlCompileError
from .http import HttpError, HttpServer, Request, Router, SseResponse

logger = logging.getLogger(__name__)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS pipelines (
    id TEXT PRIMARY KEY,
    name TEXT NOT NULL,
    query TEXT NOT NULL,
    parallelism INTEGER NOT NULL DEFAULT 1,
    created_at REAL NOT NULL,
    stopped INTEGER NOT NULL DEFAULT 0,
    graph TEXT
);
CREATE TABLE IF NOT EXISTS jobs (
    id TEXT PRIMARY KEY,
    pipeline_id TEXT NOT NULL REFERENCES pipelines(id),
    created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS job_log (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    job_id TEXT NOT NULL,
    ts REAL NOT NULL,
    level TEXT NOT NULL,
    message TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS metrics_history (
    job_id TEXT NOT NULL,
    operator_id TEXT NOT NULL,
    ts REAL NOT NULL,
    messages_sent REAL NOT NULL,
    backpressure REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS metrics_history_job
    ON metrics_history (job_id, operator_id, ts);
CREATE TABLE IF NOT EXISTS connection_profiles (
    id TEXT PRIMARY KEY,
    name TEXT UNIQUE NOT NULL,
    connector TEXT NOT NULL,
    config TEXT NOT NULL,
    created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS connection_tables (
    id TEXT PRIMARY KEY,
    name TEXT UNIQUE NOT NULL,
    connector TEXT NOT NULL,
    table_type TEXT NOT NULL,
    config TEXT NOT NULL,
    created_at REAL NOT NULL
);
"""


class ApiServer:
    """The arroyo-api equivalent: REST over a controller + sqlite."""

    def __init__(self, controller: ControllerServer,
                 db_path: str = ":memory:"):
        self.controller = controller
        self.db = sqlite3.connect(db_path)
        self.db.row_factory = sqlite3.Row
        self.db.executescript(_SCHEMA)
        try:  # pre-existing dbs from before the stored-DAG column
            self.db.execute("ALTER TABLE pipelines ADD COLUMN graph TEXT")
        except sqlite3.OperationalError:
            pass
        self.router = Router()
        self._register_routes()
        self.http = HttpServer(self.router)
        self.port: Optional[int] = None

    # metrics-history sampler cadence / retention (persistent per-job
    # history the console can reload — arroyo-api queries Prometheus with
    # rate() for this, metrics.rs:42-60; here the API owns the store)
    METRICS_SAMPLE_SECS = 2.0
    METRICS_RETENTION_SECS = 3600.0

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self.port = await self.http.start(host, port)
        self._sampler = asyncio.ensure_future(self._sample_metrics_loop())
        return self.port

    async def stop(self) -> None:
        sampler = getattr(self, "_sampler", None)
        if sampler is not None:
            sampler.cancel()
        await self.http.stop()
        self.db.close()

    # -- metrics history ----------------------------------------------------

    @staticmethod
    def _iter_job_samples(jid: str):
        """Yield the in-process prometheus samples belonging to one job
        (the single filtering definition the live endpoint AND the
        history sampler share — so they cannot drift)."""
        from ..obs import metrics as m

        for fam in m.REGISTRY.collect():
            if not fam.name.startswith("arroyo_worker_"):
                continue
            for s in fam.samples:
                if s.name.endswith("_created") \
                        or s.labels.get("job_id") != jid:
                    continue
                yield s

    def _scrape_job_metrics(self, jid: str) -> Dict[str, Dict[str, float]]:
        """{operator_id: {messages_sent, backpressure}} summary."""
        out: Dict[str, Dict[str, float]] = {}
        for s in self._iter_job_samples(jid):
            op = s.labels.get("operator_id", "")
            g = out.setdefault(op, {"messages_sent": 0.0,
                                    "qsize": 0.0, "qrem": 0.0})
            if s.name.startswith("arroyo_worker_messages_sent"):
                g["messages_sent"] += s.value
            elif s.name.startswith("arroyo_worker_tx_queue_size"):
                g["qsize"] += s.value
            elif s.name.startswith("arroyo_worker_tx_queue_rem"):
                g["qrem"] += s.value
        for g in out.values():
            g["backpressure"] = (1 - g["qrem"] / g["qsize"]
                                 if g["qsize"] > 0 else 0.0)
        return out

    async def _sample_metrics_loop(self) -> None:
        while True:
            try:
                await asyncio.sleep(self.METRICS_SAMPLE_SECS)
                now = time.time()
                for jid in list(self.controller.jobs):
                    for op, g in self._scrape_job_metrics(jid).items():
                        self.db.execute(
                            "INSERT INTO metrics_history VALUES "
                            "(?, ?, ?, ?, ?)",
                            (jid, op, now, g["messages_sent"],
                             g["backpressure"]))
                self.db.execute(
                    "DELETE FROM metrics_history WHERE ts < ?",
                    (now - self.METRICS_RETENTION_SECS,))
                self.db.commit()
            except asyncio.CancelledError:
                return
            except Exception:  # sampling must never kill the server
                logger.exception("metrics sampler")

    # -- planning ----------------------------------------------------------

    def _plan(self, query: str, parallelism: int):
        provider = SchemaProvider()
        self._install_connection_tables(provider)
        try:
            return Planner(provider).plan(query,
                                          query_parallelism=parallelism)
        except (SqlPlanError, SqlCompileError, ValueError, KeyError) as e:
            raise HttpError(400, f"SQL error: {e}")

    @staticmethod
    def _validate_plan(prog, reject: bool):
        """Plan-time validation (analysis.plan_validator + shardcheck):
        returns the structured plan report for the console's validation
        endpoint — diagnostics plus the sharding verifier's
        ``predicted_reshards``/``mesh_shards``; with ``reject`` a plan
        with error-severity diagnostics 400s before a job row or
        running pipeline ever exists."""
        from ..analysis.plan_validator import errors_of, plan_report

        rep = plan_report(prog)
        errs = errors_of(rep["diagnostics"])
        if reject and errs:
            raise HttpError(
                400, "plan validation failed: "
                     + "; ".join(d.render() for d in errs))
        return {"diagnostics": [d.to_json() for d in rep["diagnostics"]],
                "predicted_reshards": rep["predicted_reshards"],
                "mesh_shards": rep["mesh_shards"]}

    def _install_connection_tables(self, provider: SchemaProvider) -> None:
        """Saved connection tables become CREATE TABLEs the planner sees."""
        from ..sql.ast_nodes import CreateTable

        for row in self.db.execute("SELECT * FROM connection_tables"):
            cfg = json.loads(row["config"])
            with_opts = {"connector": row["connector"], **{
                k: str(v) for k, v in cfg.items() if v is not None}}
            provider.add_create_table(CreateTable(
                name=row["name"], columns=[], with_options=with_opts))

    # -- routes ------------------------------------------------------------

    def _register_routes(self) -> None:
        r = self.router

        @r.get("/api/v1/ping")
        async def ping(req: Request):
            return {"pong": True}

        @r.get("/api/v1/openapi.json")
        async def openapi(req: Request):
            """OpenAPI 3.0 description of this API, generated from the
            live route table (the reference serves a utoipa-generated
            spec the same way, arroyo-openapi)."""
            import re as _re

            paths: Dict[str, Dict] = {}
            for method, pattern, handler in r.patterns:
                if pattern in ("/", "/api/v1/openapi.json"):
                    continue
                entry = paths.setdefault(pattern, {})
                doc = (handler.__doc__ or "").strip().split("\n")[0]
                op = {
                    "summary": doc or handler.__name__,
                    "operationId": handler.__name__,
                    "responses": {"200": {"description": "success"}},
                }
                params = _re.findall(r"\{(\w+)\}", pattern)
                if params:
                    op["parameters"] = [{
                        "name": p, "in": "path", "required": True,
                        "schema": {"type": "string"},
                    } for p in params]
                if method in ("POST", "PATCH", "PUT"):
                    op["requestBody"] = {"content": {
                        "application/json": {"schema": {"type": "object"}}}}
                entry[method.lower()] = op
            return {
                "openapi": "3.0.3",
                "info": {"title": "arroyo_tpu REST API",
                         "version": "0.1.0",
                         "description":
                             "Pipeline/job management for the TPU-native "
                             "streaming engine (arroyo-api parity)"},
                "paths": paths,
            }

        @r.get("/")
        async def console(req: Request):
            from .console import CONSOLE_HTML
            from .http import Response

            return Response(body=CONSOLE_HTML.encode(),
                            content_type="text/html; charset=utf-8")

        # ---- pipelines (pipelines.rs:316-700) ----

        @r.post("/v1/pipelines/validate")
        async def validate_pipeline(req: Request):
            body = req.json()
            query = body.get("query")
            if not query:
                raise HttpError(400, "missing 'query'")
            prog = self._plan(query, int(body.get("parallelism", 1)))
            # validation endpoint: structured plan diagnostics (errors
            # AND warnings) so the console can render them inline
            # without attempting a create, plus shardcheck's plan
            # report — predicted_reshards is the number the smoke
            # drift gate holds against the live reshard counter
            rep = self._validate_plan(prog, reject=False)
            return {"graph": _graph_json(prog),
                    "diagnostics": rep["diagnostics"],
                    "predicted_reshards": rep["predicted_reshards"],
                    "mesh_shards": rep["mesh_shards"]}

        @r.post("/v1/pipelines")
        async def create_pipeline(req: Request):
            body = req.json()
            name, query = body.get("name"), body.get("query")
            if not name or not query:
                raise HttpError(400, "missing 'name' or 'query'")
            preview = bool(body.get("preview"))
            parallelism = 1 if preview else int(body.get("parallelism", 1))
            try:  # validate BEFORE the job exists: a bad ttl must not
                # leave an unreaped preview running behind a 500
                ttl_secs = (float(body.get("ttl_secs", 60))
                            if preview else None)
            except (TypeError, ValueError):
                raise HttpError(400, "ttl_secs must be a number")
            prog = self._plan(query, parallelism)
            self._validate_plan(prog, reject=True)
            if preview:
                # the reference's preview mode (pipelines.rs:191-198):
                # parallelism 1, every connector sink swapped for the
                # web/preview sink so results stream to the console's
                # output pane, and the job auto-stops after a TTL
                from ..graph.logical import ConnectorOpSpec, OpKind

                for node in prog.nodes():
                    op = node.operator
                    if (op.kind == OpKind.CONNECTOR_SINK
                            and op.spec.connector != "preview"):
                        op.spec = ConnectorOpSpec(
                            "preview",
                            {"controller_addr": self.controller.addr},
                            "preview sink")
            pipeline_id = f"pl_{uuid.uuid4().hex[:12]}"
            job_id = f"job_{uuid.uuid4().hex[:8]}"
            now = time.time()
            graph = _graph_json(prog)
            with self.db:
                self.db.execute(
                    "INSERT INTO pipelines (id, name, query, parallelism, "
                    "created_at, graph) VALUES (?,?,?,?,?,?)",
                    (pipeline_id, name, query, parallelism, now,
                     json.dumps(graph)))
                self.db.execute(
                    "INSERT INTO jobs (id, pipeline_id, created_at) "
                    "VALUES (?,?,?)", (job_id, pipeline_id, now))
            # ttl enforcement lives in the controller's supervisor (and
            # its durable store), so a restarted controller still reaps
            # resumed previews
            await self.controller.submit_job(prog, job_id=job_id,
                                             ttl_secs=ttl_secs)
            return {"id": pipeline_id, "name": name, "preview": preview,
                    "jobs": [{"id": job_id}],
                    "graph": graph}

        @r.get("/v1/pipelines")
        async def list_pipelines(req: Request):
            rows = self.db.execute(
                "SELECT * FROM pipelines ORDER BY created_at").fetchall()
            return {"data": [self._pipeline_json(row) for row in rows]}

        @r.get("/v1/pipelines/{id}")
        async def get_pipeline(req: Request):
            row = self._pipeline_row(req.params["id"])
            out = self._pipeline_json(row)
            # detail view carries the stored DAG (console overlay); the
            # list view stays lean
            try:
                out["graph"] = (json.loads(row["graph"])
                                if row["graph"] else None)
            except (KeyError, IndexError):
                out["graph"] = None
            return out

        @r.patch("/v1/pipelines/{id}")
        async def patch_pipeline(req: Request):
            row = self._pipeline_row(req.params["id"])
            body = req.json()
            stop = body.get("stop")
            p = None
            if "parallelism" in body:
                p = int(body["parallelism"])
                if not 1 <= p <= 1024:
                    raise HttpError(
                        400, "parallelism must be between 1 and 1024")
            rescaled = []
            for job in self._job_rows(row["id"]):
                jid = job["id"]
                if (stop in ("checkpoint", "graceful", "immediate")
                        and jid in self.controller.jobs):
                    await self.controller.stop_job(
                        jid, checkpoint=(stop == "checkpoint"))
                live = (jid in self.controller.jobs
                        and not self.controller.jobs[jid].fsm.state.terminal)
                if p is not None and live:
                    # terminal jobs stay registered for status queries but
                    # cannot transition — rescaling one was a 500
                    overrides = {
                        n.operator_id: p
                        for n in self.controller.jobs[jid].program.nodes()}
                    await self.controller.rescale_job(jid, overrides)
                    rescaled.append(jid)
            # metadata updates apply once, jobs or not
            with self.db:
                if stop in ("checkpoint", "graceful", "immediate"):
                    self.db.execute(
                        "UPDATE pipelines SET stopped = 1 WHERE id = ?",
                        (row["id"],))
                if p is not None:
                    self.db.execute(
                        "UPDATE pipelines SET parallelism = ? WHERE id = ?",
                        (p, row["id"]))
                    if rescaled:
                        # keep the stored graph honest: the console's DAG
                        # renders per-node parallelism from this column
                        jid = rescaled[-1]
                        self.db.execute(
                            "UPDATE pipelines SET graph = ? WHERE id = ?",
                            (json.dumps(_graph_json(
                                self.controller.jobs[jid].program)),
                             row["id"]))
            out = self._pipeline_json(self._pipeline_row(row["id"]))
            if "parallelism" in body:
                # the console must distinguish "job rescaled live" from
                # "no live job; only the stored default changed"
                out["rescaled_jobs"] = rescaled
            return out

        @r.delete("/v1/pipelines/{id}")
        async def delete_pipeline(req: Request):
            row = self._pipeline_row(req.params["id"])
            for job in self._job_rows(row["id"]):
                jid = job["id"]
                if jid in self.controller.jobs:
                    state = self.controller.job_state(jid)
                    if not state.terminal:
                        await self.controller.stop_job(jid,
                                                       checkpoint=False)
                        try:
                            await self.controller.wait_for_state(
                                jid, JobState.STOPPED, JobState.FINISHED,
                                timeout=15)
                        except TimeoutError:
                            raise HttpError(
                                409, "job did not stop in time; retry")
            with self.db:
                self.db.execute(
                    "DELETE FROM job_log WHERE job_id IN "
                    "(SELECT id FROM jobs WHERE pipeline_id = ?)",
                    (row["id"],))
                self.db.execute("DELETE FROM jobs WHERE pipeline_id = ?",
                                (row["id"],))
                self.db.execute("DELETE FROM pipelines WHERE id = ?",
                                (row["id"],))
            return {"deleted": row["id"]}

        @r.get("/v1/pipelines/{id}/jobs")
        async def pipeline_jobs(req: Request):
            row = self._pipeline_row(req.params["id"])
            return {"data": [self._job_json(j)
                             for j in self._job_rows(row["id"])]}

        # ---- jobs (jobs.rs:213-542) ----

        @r.get("/v1/jobs")
        async def list_jobs(req: Request):
            rows = self.db.execute(
                "SELECT * FROM jobs ORDER BY created_at").fetchall()
            return {"data": [self._job_json(j) for j in rows]}

        @r.get("/v1/jobs/{jid}/autoscaler")
        async def autoscaler_status(req: Request):
            """Autoscaler state for one job: policy knobs, counters, and
            the decision ledger (every evaluation's inputs digest plus
            the action taken or the veto that blocked it)."""
            jid = req.params["jid"]
            if jid not in self.controller.jobs:
                raise HttpError(404, "no such job")
            scaler = self.controller.autoscalers.get(jid)
            if scaler is None:
                # subsystem globally disabled (ARROYO_AUTOSCALE=0), or a
                # job admitted before the feature: report, don't 404 —
                # a throwaway (unregistered, never-started) autoscaler
                # keeps the payload shape identical to the live one
                from ..autoscale.supervisor import JobAutoscaler

                scaler = JobAutoscaler(self.controller, jid)
            return scaler.status()

        @r.put("/v1/jobs/{jid}/autoscaler")
        async def autoscaler_update(req: Request):
            """Enable/disable the job's autoscaler and/or merge policy
            knob updates ({"enabled": bool, "policy": {knob: value}})."""
            from ..config import config as _config

            from ..autoscale.supervisor import JobAutoscaler

            jid = req.params["jid"]
            if jid not in self.controller.jobs:
                raise HttpError(404, "no such job")
            body = req.json()
            scaler = self.controller.autoscalers.get(jid)
            if scaler is None and not _config().autoscale_enabled:
                raise HttpError(409, "autoscaling is globally disabled "
                                     "(ARROYO_AUTOSCALE=0)")
            # validate the WHOLE body before any side effect: a 422 must
            # not leave a freshly attached (possibly default-enabled and
            # persisted) loop behind
            new_cfg = None
            if "policy" in body:
                if not isinstance(body["policy"], dict):
                    raise HttpError(422, "'policy' must be an object")
                base = (scaler if scaler is not None
                        else JobAutoscaler(self.controller, jid))
                try:
                    new_cfg = base.policy.cfg.merged(body["policy"])
                except (KeyError, TypeError, ValueError) as e:
                    raise HttpError(422, f"invalid policy: {e}")
            if scaler is None:
                # single registration path: the controller's attach owns
                # the prev-loop-stop guard and default-on semantics
                self.controller._attach_autoscaler(jid)
                scaler = self.controller.autoscalers[jid]
            if new_cfg is not None:
                scaler.policy.cfg = new_cfg
            if "enabled" in body:
                scaler.set_enabled(bool(body["enabled"]))
            # durable controllers resume the autoscaler with the job
            self.controller.persist_autoscaler(jid)
            return scaler.status()

        @r.get("/v1/jobs/{jid}/latency")
        async def job_latency(req: Request):
            """End-to-end latency observatory view (obs/latency.py):
            per-sink e2e quantiles, per-operator watermark ages, the
            critical-path stage decomposition, the device-memory ledger
            and the SLO verdict — aggregated from worker heartbeats,
            with the in-process registry as the embedded/LocalRunner
            fallback.  Empty quantiles unless a worker runs with
            sampling armed (ARROYO_LATENCY_SAMPLE_N>0)."""
            jid = req.params["jid"]
            data = self.controller.job_latency(jid)
            source = "heartbeat"
            if data is None or (not data["sinks"]
                                and not data["watermark_age_ms"]):
                # embedded/LocalRunner fallback: shape the in-process
                # registry summary the same way
                from ..obs.latency import Slo, SloEvaluator
                from ..obs.metrics import job_operator_summary

                rows = self.controller.rollup_from_summary(
                    job_operator_summary(jid))
                local = self.controller.latency_shape(rows)
                if local["sinks"] or local["watermark_age_ms"] \
                        or data is None:
                    if (not local["sinks"]
                            and jid not in self.controller.jobs):
                        raise HttpError(404, "no such job")
                    job = self.controller.jobs.get(jid)
                    local["slo"] = (job.slo_eval.to_json() if job is not None
                                    else SloEvaluator(
                                        jid, Slo.from_config()).to_json())
                    data, source = local, "local_registry"
            data["source"] = source
            return data

        @r.get("/v1/jobs/{jid}/slo")
        async def slo_status(req: Request):
            """The job's declared latency SLO plus the evaluator's
            verdict: burn rate, violation counters and the recent
            violation events (decision-ledger style)."""
            jid = req.params["jid"]
            job = self.controller.jobs.get(jid)
            if job is None:
                raise HttpError(404, "no such job")
            return job.slo_eval.to_json()

        @r.put("/v1/jobs/{jid}/slo")
        async def slo_update(req: Request):
            """Replace the job's latency SLO live
            ({"p99_ms": float, "staleness_ms": float,
            "burn_window_secs": float} — 0 unsets a dimension).  The
            whole body validates before any side effect."""
            from ..obs.latency import Slo

            jid = req.params["jid"]
            job = self.controller.jobs.get(jid)
            if job is None:
                raise HttpError(404, "no such job")
            body = req.json()
            if not isinstance(body, dict):
                raise HttpError(422, "body must be an object")
            cur = job.slo
            vals = {}
            for key, default in (("p99_ms", cur.p99_ms),
                                 ("staleness_ms", cur.staleness_ms),
                                 ("burn_window_secs",
                                  cur.burn_window_secs)):
                v = body.get(key, default)
                if not isinstance(v, (int, float)) or v < 0:
                    raise HttpError(422,
                                    f"'{key}' must be a number >= 0")
                vals[key] = float(v)
            unknown = set(body) - {"p99_ms", "staleness_ms",
                                   "burn_window_secs"}
            if unknown:
                raise HttpError(422, f"unknown keys: {sorted(unknown)}")
            if vals["burn_window_secs"] == 0:
                vals["burn_window_secs"] = 60.0
            job.set_slo(Slo(**vals))
            return job.slo_eval.to_json()

        @r.get("/v1/pipelines/{pid}/jobs/{jid}/errors")
        async def job_errors(req: Request):
            rows = self.db.execute(
                "SELECT * FROM job_log WHERE job_id = ? AND level = "
                "'error' ORDER BY id", (req.params["jid"],)).fetchall()
            errors = [{"created_at": r["ts"], "message": r["message"]}
                      for r in rows]
            job = self.controller.jobs.get(req.params["jid"])
            if job is not None and job.failure:
                errors.append({"created_at": None, "message": job.failure})
            return {"data": errors}

        @r.get("/v1/pipelines/{pid}/jobs/{jid}/checkpoints")
        async def job_checkpoints(req: Request):
            job = self.controller.jobs.get(req.params["jid"])
            if job is None:
                raise HttpError(404, "no such job")
            data = []
            for epoch, tr in sorted(job.trackers.items()):
                data.append({
                    "epoch": epoch,
                    "backend": job.checkpoint_url,
                    "finished": tr.done,
                    "subtasks_completed": len(tr.completed),
                    "subtasks_total": tr.n_subtasks,
                })
            return {"data": data,
                    "last_successful_epoch": job.last_successful_epoch}

        @r.get("/v1/pipelines/{pid}/jobs/{jid}/checkpoints/{epoch}"
               "/operator_checkpoint_groups")
        async def checkpoint_details(req: Request):
            """Per-operator checkpoint detail for one epoch: file sizes
            written by each operator's subtasks (get_checkpoint_details,
            jobs.rs — the reference reads its DB rows; here the parquet
            layout itself is the record)."""
            job = self.controller.jobs.get(req.params["jid"])
            if job is None:
                raise HttpError(404, "no such job")
            try:
                epoch = int(req.params["epoch"])
            except ValueError:
                raise HttpError(400, "epoch must be an integer")
            import asyncio

            from ..state.backend import ParquetBackend

            backend = ParquetBackend.for_url(job.checkpoint_url)
            ckpt_dir = backend.checkpoint_dir(job.job_id, epoch) + "/"
            store = backend.storage

            def scan():
                groups: Dict[str, Dict[str, Any]] = {}
                finished = None
                try:
                    files = store.list(ckpt_dir)
                except Exception:
                    files = []
                for f in files:
                    rel = f[len(ckpt_dir):]
                    head = rel.split("/", 1)[0]
                    if head == "metadata.json":
                        try:
                            finished = bool(json.loads(
                                store.get(f)).get("complete"))
                        except Exception:
                            pass
                        continue
                    # directory names are operator-<id>: report the bare
                    # id so clients can correlate with the metrics groups
                    op = head[len("operator-"):] \
                        if head.startswith("operator-") else head
                    g = groups.setdefault(op, {"operator_id": op,
                                               "bytes": 0, "files": []})
                    try:
                        size = store.size(f)  # stat, not a full download
                    except Exception:
                        size = 0
                    g["bytes"] += size
                    g["files"].append({"path": rel, "bytes": size})
                return groups, finished

            # listing + stats can hit object storage: off the event loop
            groups, finished = await asyncio.get_event_loop() \
                .run_in_executor(None, scan)
            if finished is None:
                tr = job.trackers.get(epoch)
                finished = bool(tr.done) if tr else None
            return {"epoch": epoch, "finished": finished,
                    "data": sorted(groups.values(),
                                   key=lambda g: g["operator_id"])}

        @r.get("/v1/pipelines/{pid}/jobs/{jid}/operator_metric_groups")
        async def operator_metrics(req: Request):
            """Per-operator throughput metrics (metrics.rs:42-60 queries
            prometheus rate(arroyo_worker_*); here the registry is
            in-process, so the API scrapes it directly)."""
            jid = req.params["jid"]
            groups: Dict[str, Dict[str, Any]] = {}
            for s in self._iter_job_samples(jid):
                op = s.labels.get("operator_id", "")
                g = groups.setdefault(op, {"operator_id": op,
                                           "metrics": {}})
                key = f"{s.name}[{s.labels.get('subtask_idx', '0')}]"
                g["metrics"][key] = s.value
            return {"data": sorted(groups.values(),
                                   key=lambda g: g["operator_id"])}

        @r.get("/v1/pipelines/{pid}/jobs/{jid}/operator_rollups")
        async def operator_rollups(req: Request):
            """Controller-aggregated per-operator job rollups (records/s,
            event-time/watermark lag, batch latency, backpressure, kernel
            seconds) built from worker heartbeat snapshots — works across
            worker processes, unlike the in-process registry scrape."""
            jid = req.params["jid"]
            data = self.controller.job_rollup(jid)
            source = "heartbeat"
            if not data:
                # no heartbeat snapshot (job just started, or an embedded/
                # in-process run the controller never scheduled): fall back
                # to the local registry so the console never renders blank
                from ..obs.metrics import job_operator_summary

                ops = self.controller.rollup_from_summary(
                    job_operator_summary(jid))
                if not ops and jid not in self.controller.jobs:
                    raise HttpError(404, "no such job")
                data, source = ops, "local_registry"
            return {"data": data, "source": source}

        @r.get("/v1/pipelines/{pid}/jobs/{jid}/profile_rollups")
        async def profile_rollups(req: Request):
            """Phase-profile rollups (obs/profiler.py): per-operator
            measured phase/wait seconds, host vs device split, and the
            worker event-loop watchdog numbers — aggregated from the
            same heartbeat snapshots as operator_rollups.  Empty unless
            a worker runs with the profiler armed (ARROYO_PROFILE=1)."""
            jid = req.params["jid"]
            data = self.controller.job_profile_rollup(jid)
            source = "heartbeat"
            if not data["operators"] and not data["worker"]:
                # embedded/LocalRunner fallback: shape the in-process
                # registry + profiler summary the same way
                from ..obs.metrics import job_operator_summary

                rows = self.controller.rollup_from_summary(
                    job_operator_summary(jid))
                data = self.controller.profile_shape(rows)
                if (not data["operators"]
                        and jid not in self.controller.jobs):
                    raise HttpError(404, "no such job")
                source = "local_registry"
            data["source"] = source
            return data

        @r.get("/v1/pipelines/{pid}/jobs/{jid}/metrics_history")
        async def metrics_history(req: Request):
            """Persistent per-operator history (the API's sampler writes
            it to sqlite every METRICS_SAMPLE_SECS): the console reloads
            charts after a refresh instead of starting empty."""
            jid = req.params["jid"]
            series: Dict[str, list] = {}
            for row in self.db.execute(
                    "SELECT operator_id, ts, messages_sent, backpressure "
                    "FROM metrics_history WHERE job_id = ? ORDER BY ts",
                    (jid,)):
                series.setdefault(row["operator_id"], []).append(
                    [row["ts"], row["messages_sent"], row["backpressure"]])
            return {"data": [{"operator_id": op, "points": pts}
                             for op, pts in sorted(series.items())]}

        @r.get("/v1/pipelines/{pid}/jobs/{jid}/output")
        async def job_output(req: Request):
            jid = req.params["jid"]
            if jid not in self.controller.jobs:
                raise HttpError(404, "no such job")
            return SseResponse(self._tail_output(jid))

        # ---- connection profiles (connection_profiles.rs analog:
        # reusable connector credentials/config shared across tables) ----

        @r.post("/v1/connection_profiles")
        async def create_connection_profile(req: Request):
            body = req.json()
            for f in ("name", "connector", "config"):
                if f not in body:
                    raise HttpError(400, f"missing '{f}'")
            if not isinstance(body["config"], dict):
                raise HttpError(422, "'config' must be an object")
            pid = f"cp_{uuid.uuid4().hex[:12]}"
            try:
                with self.db:
                    self.db.execute(
                        "INSERT INTO connection_profiles (id, name, "
                        "connector, config, created_at) VALUES (?,?,?,?,?)",
                        (pid, body["name"], body["connector"],
                         json.dumps(body["config"]), time.time()))
            except sqlite3.IntegrityError:
                raise HttpError(409,
                                f"profile {body['name']!r} already exists")
            return {"id": pid, "name": body["name"],
                    "connector": body["connector"],
                    "config": body["config"]}

        @r.get("/v1/connection_profiles")
        async def list_connection_profiles(req: Request):
            rows = self.db.execute(
                "SELECT * FROM connection_profiles ORDER BY created_at"
            ).fetchall()
            return {"data": [{
                "id": row["id"], "name": row["name"],
                "connector": row["connector"],
                "config": json.loads(row["config"]),
            } for row in rows]}

        @r.post("/v1/connection_tables/schemas/test")
        async def test_schema(req: Request):
            """Validate a JSON schema document (test_schema analog:
            the reference checks the schema compiles to valid types)."""
            body = req.json()
            schema = body.get("schema")
            if not isinstance(schema, dict):
                return {"ok": False, "error": "missing 'schema' object"}
            try:
                from ..formats import columns_from_json_schema

                cols = columns_from_json_schema(schema)
                return {"ok": True, "columns": cols}
            except Exception as e:
                return {"ok": False, "error": str(e)}

        # ---- connectors & connection tables ----

        @r.get("/v1/connectors")
        async def connectors(req: Request):
            # config_schema plays the reference's table_config role
            # (connector-schemas/*/table.json served via the metadata
            # crate): the console renders creation forms from it
            return {"data": [{
                "id": m.name, "name": m.name,
                "source": m.supports_source, "sink": m.supports_sink,
                "description": m.description,
                "config_schema": (m.config_model.model_json_schema()
                                  if m.config_model else None),
            } for m in list_connectors()]}

        @r.post("/v1/connection_tables")
        async def create_connection_table(req: Request):
            body = req.json()
            for f in ("name", "connector", "config"):
                if f not in body:
                    raise HttpError(400, f"missing '{f}'")
            if not isinstance(body["config"], dict):
                raise HttpError(422, "'config' must be an object")
            cfg_in = dict(body["config"])
            if body.get("connection_profile_id"):
                row = self.db.execute(
                    "SELECT * FROM connection_profiles WHERE id = ?",
                    (body["connection_profile_id"],)).fetchone()
                if row is None:
                    raise HttpError(404, "no such connection profile")
                if row["connector"] != body["connector"]:
                    raise HttpError(409, "profile is for connector "
                                    f"{row['connector']!r}")
                cfg_in = {**json.loads(row["config"]), **cfg_in}
            try:
                cfg = validate_config(body["connector"], cfg_in)
            except KeyError:
                raise HttpError(400,
                                f"unknown connector {body['connector']!r}")
            except Exception as e:
                raise HttpError(422, f"invalid config: {e}")
            tid = f"ct_{uuid.uuid4().hex[:12]}"
            try:
                with self.db:
                    self.db.execute(
                        "INSERT INTO connection_tables (id, name, "
                        "connector, table_type, config, created_at) "
                        "VALUES (?,?,?,?,?,?)",
                        (tid, body["name"], body["connector"],
                         body.get("table_type", "source"),
                         json.dumps(cfg), time.time()))
            except sqlite3.IntegrityError:
                raise HttpError(409,
                                f"table {body['name']!r} already exists")
            return {"id": tid, "name": body["name"],
                    "connector": body["connector"], "config": cfg}

        @r.post("/v1/connection_tables/test")
        async def test_connection_table(req: Request):
            body = req.json()
            try:
                validate_config(body.get("connector", ""),
                                body.get("config", {}))
            except KeyError:
                return {"ok": False,
                        "error": f"unknown connector "
                                 f"{body.get('connector')!r}"}
            except Exception as e:
                return {"ok": False, "error": str(e)}
            return {"ok": True}

        @r.get("/v1/connection_tables")
        async def list_connection_tables(req: Request):
            rows = self.db.execute(
                "SELECT * FROM connection_tables ORDER BY created_at"
            ).fetchall()
            return {"data": [{
                "id": row["id"], "name": row["name"],
                "connector": row["connector"],
                "table_type": row["table_type"],
                "config": json.loads(row["config"]),
            } for row in rows]}

        @r.delete("/v1/connection_tables/{id}")
        async def delete_connection_table(req: Request):
            cur = self.db.execute(
                "DELETE FROM connection_tables WHERE id = ?",
                (req.params["id"],))
            self.db.commit()
            if cur.rowcount == 0:
                raise HttpError(404, "no such connection table")
            return {"deleted": req.params["id"]}

    # -- helpers -----------------------------------------------------------

    def _pipeline_row(self, pid: str) -> sqlite3.Row:
        row = self.db.execute("SELECT * FROM pipelines WHERE id = ?",
                              (pid,)).fetchone()
        if row is None:
            raise HttpError(404, f"no pipeline {pid!r}")
        return row

    def _job_rows(self, pid: str):
        return self.db.execute(
            "SELECT * FROM jobs WHERE pipeline_id = ? ORDER BY created_at",
            (pid,)).fetchall()

    def _pipeline_json(self, row: sqlite3.Row) -> Dict[str, Any]:
        return {"id": row["id"], "name": row["name"],
                "query": row["query"], "parallelism": row["parallelism"],
                "stopped": bool(row["stopped"]),
                "created_at": row["created_at"],
                "jobs": [self._job_json(j)
                         for j in self._job_rows(row["id"])]}

    def _job_json(self, row: sqlite3.Row) -> Dict[str, Any]:
        jid = row["id"]
        job = self.controller.jobs.get(jid)
        state = job.fsm.state.value if job else "Created"
        return {"id": jid, "pipeline_id": row["pipeline_id"],
                "state": state,
                "created_at": row["created_at"],
                "failure_message": job.failure if job else None,
                "checkpoint_epoch": (job.last_successful_epoch
                                     if job else None)}

    async def _tail_output(self, job_id: str) -> AsyncIterator[Dict]:
        """Bridge the controller's in-process output subscription to SSE
        (the reference proxies controller SubscribeToOutput the same way,
        jobs.rs:465+)."""
        import asyncio

        q: asyncio.Queue = asyncio.Queue()
        subs = self.controller.sink_subscribers.setdefault(job_id, [])
        subs.append(q)
        try:
            while True:
                try:
                    item = await asyncio.wait_for(q.get(), timeout=0.5)
                except asyncio.TimeoutError:
                    # a job that finished before (or without) a done event
                    # must still terminate the stream
                    job = self.controller.jobs.get(job_id)
                    if job is None or job.fsm.state.terminal:
                        yield {"job_id": job_id, "rows": [], "done": True}
                        return
                    continue
                yield _sink_event_json(item)
                if item.get("done"):
                    return
        finally:
            subs.remove(q)


def _sink_event_json(item: Dict[str, Any]) -> Dict[str, Any]:
    """SendSinkData payloads carry a wire-encoded Batch; SSE clients get
    plain JSON rows."""
    out = {"job_id": item.get("job_id"),
           "operator_id": item.get("operator_id"),
           "done": bool(item.get("done"))}
    data = item.get("batch")
    rows = []
    if data:
        from ..formats import batch_to_rows
        from ..network.data_plane import _decode_batch

        rows = batch_to_rows(_decode_batch(data))
    out["rows"] = rows
    return out


def _graph_json(prog) -> Dict[str, Any]:
    """Pipeline DAG for the console (PipelineGraph in the REST types).
    Members of a multi-operator chain carry the chain head's id so the
    console can render them as one grouped task."""
    from ..graph.chaining import chain_annotations

    chains = chain_annotations(prog)
    return {
        "nodes": [{"operator_id": n.operator_id,
                   "description": n.operator.name,
                   "parallelism": n.parallelism,
                   **({"chain": chains[n.operator_id]}
                      if n.operator_id in chains else {})}
                  for n in prog.nodes()],
        "edges": [{"src": u, "dst": v,
                   "edge_type": prog.edge(u, v).typ.value}
                  for u, v in prog.graph.edges],
    }


async def _serve() -> None:
    import logging
    import os

    from ..config import config
    from ..controller.controller import ControllerServer
    from ..obs.logging_setup import init_logging

    init_logging("api")
    controller = ControllerServer(
        host=os.environ.get("CONTROLLER_HOST", "0.0.0.0"))
    await controller.start(port=int(os.environ.get("CONTROLLER_PORT",
                                                   "9190")))
    api = ApiServer(controller,
                    db_path=os.environ.get("API_DB", ":memory:"))
    port = await api.start(host=os.environ.get("API_HOST", "0.0.0.0"),
                           port=int(os.environ.get("API_PORT", "8000")))
    logging.getLogger(__name__).info(
        "REST API on :%s (controller grpc at %s, checkpoints -> %s)",
        port, controller.addr, config().checkpoint_url)
    import asyncio

    await asyncio.Event().wait()


def main() -> None:
    """``python -m arroyo_tpu.api.rest``: REST API + controller in one
    process — the single-node deployment entrypoint (deploy/)."""
    import asyncio

    asyncio.run(_serve())


if __name__ == "__main__":
    main()
