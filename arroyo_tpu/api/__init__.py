"""REST API (arroyo-api analog): HTTP server, routes, sqlite metadata."""

from .http import HttpError, HttpServer, Request, Response, Router  # noqa: F401
from .rest import ApiServer  # noqa: F401
