"""Runtime-generated API client + structural OpenAPI validation.

The reference generates a Rust client from its utoipa spec and drives
black-box integration through it (integ/src/main.rs:25-120).  Here the
client is generated AT RUNTIME from ``/api/v1/openapi.json``: a method
exists only because the live spec declares the operation, so a drifting
spec breaks the black-box tests — which is the point of testing through
a generated client rather than hand-written URLs.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

_METHODS = ("get", "put", "post", "delete", "options", "head", "patch",
            "trace")


def validate_spec(spec: Dict[str, Any]) -> List[str]:
    """Structural OpenAPI 3.0 validation (the environment ships no
    openapi-spec-validator; these are the document requirements the
    generated client depends on).  Returns a list of problems — empty
    means valid."""
    problems: List[str] = []

    def p(msg: str) -> None:
        problems.append(msg)

    if not re.match(r"^3\.\d+\.\d+$", str(spec.get("openapi", ""))):
        p(f"openapi version {spec.get('openapi')!r} is not a 3.x.y semver")
    info = spec.get("info")
    if not isinstance(info, dict):
        p("missing info object")
    else:
        for k in ("title", "version"):
            if not info.get(k):
                p(f"info.{k} missing")
    paths = spec.get("paths")
    if not isinstance(paths, dict) or not paths:
        p("paths missing or empty")
        return problems
    seen_ops: Dict[str, str] = {}
    for path, entry in paths.items():
        if not path.startswith("/"):
            p(f"path {path!r} must start with '/'")
        if not isinstance(entry, dict):
            p(f"path {path!r} entry is not an object")
            continue
        tmpl_params = set(re.findall(r"\{(\w+)\}", path))
        for method, op in entry.items():
            if method not in _METHODS:
                p(f"{path}: unknown method {method!r}")
                continue
            if not isinstance(op, dict):
                p(f"{method.upper()} {path}: operation is not an object")
                continue
            op_id = op.get("operationId")
            if not op_id:
                p(f"{method.upper()} {path}: missing operationId")
            elif op_id in seen_ops:
                p(f"operationId {op_id!r} duplicated "
                  f"({seen_ops[op_id]} and {method.upper()} {path})")
            else:
                seen_ops[op_id] = f"{method.upper()} {path}"
            if not op.get("responses"):
                p(f"{method.upper()} {path}: missing responses")
            declared = set()
            for param in op.get("parameters", []):
                name = param.get("name")
                if param.get("in") == "path":
                    declared.add(name)
                    if not param.get("required"):
                        p(f"{method.upper()} {path}: path param "
                          f"{name!r} must be required")
                    if name not in tmpl_params:
                        p(f"{method.upper()} {path}: path param "
                          f"{name!r} not in the template")
            missing = tmpl_params - declared
            if missing:
                p(f"{method.upper()} {path}: template params "
                  f"{sorted(missing)} undeclared")
    return problems


class ApiError(RuntimeError):
    def __init__(self, status: int, body: str):
        super().__init__(f"HTTP {status}: {body[:300]}")
        self.status = status
        self.body = body


class GeneratedClient:
    """Black-box client whose methods are the spec's operationIds.

    ``await client.create_pipeline(body={...})``,
    ``await client.get_pipeline(id="pl_x")``,
    ``await client.job_checkpoints(pid="pl_x", jid="job_y")`` — path
    params by keyword, JSON body via ``body=``, query via ``params=``.
    """

    def __init__(self, base_url: str, spec: Dict[str, Any], http) -> None:
        self.base_url = base_url.rstrip("/")
        self.spec = spec
        self._http = http  # httpx.AsyncClient
        self.operations: Dict[str, Dict[str, str]] = {}
        for path, entry in spec["paths"].items():
            for method, op in entry.items():
                if method not in _METHODS or not isinstance(op, dict):
                    continue
                op_id = op.get("operationId")
                if op_id:
                    self.operations[op_id] = {"method": method,
                                              "path": path}

    def __getattr__(self, op_id: str):
        ops = self.__dict__.get("operations") or {}
        if op_id not in ops:
            raise AttributeError(
                f"operation {op_id!r} is not in the spec "
                f"(has: {sorted(ops)[:8]}...)")
        meta = ops[op_id]

        async def call(body: Optional[Any] = None,
                       params: Optional[Dict[str, Any]] = None,
                       **path_params: Any):
            path = meta["path"]
            for k, v in path_params.items():
                if "{%s}" % k not in path:
                    raise TypeError(f"{op_id}: unknown path param {k!r}")
                path = path.replace("{%s}" % k, str(v))
            left = re.findall(r"\{(\w+)\}", path)
            if left:
                raise TypeError(f"{op_id}: missing path params {left}")
            r = await self._http.request(
                meta["method"].upper(), self.base_url + path,
                json=body, params=params)
            if r.status_code >= 400:
                raise ApiError(r.status_code, r.text)
            ctype = r.headers.get("content-type", "")
            return r.json() if "json" in ctype else r.text

        call.__name__ = op_id
        return call


async def generate_client(base_url: str, http) -> GeneratedClient:
    """Fetch the live spec, validate it, and build the client."""
    r = await http.get(base_url.rstrip("/") + "/api/v1/openapi.json")
    spec = r.json()
    problems = validate_spec(spec)
    if problems:
        raise ValueError("invalid OpenAPI spec: " + "; ".join(problems))
    return GeneratedClient(base_url, spec, http)
