"""Web console served by the API (the arroyo-console analog).

The reference ships a React/Vite SPA (arroyo-console/) talking to the
REST API; this is a single-file, dependency-free page with the same core
workflow: SQL editor with validation + a layered SVG DAG preview, create
and supervise jobs, live per-operator throughput charts (rates derived
from the prometheus counters, as the reference's console derives them
from prometheus rate()), backpressure gauges, checkpoint history, job
errors, and SSE output tailing.
"""

CONSOLE_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>arroyo_tpu console</title>
<style>
  :root { --bg:#101418; --panel:#1a2027; --text:#d6dde5; --accent:#4aa3ff;
          --ok:#3fb68b; --bad:#e5604c; --dim:#7a8794; --warn:#e3b341; }
  * { box-sizing: border-box; }
  body { margin:0; background:var(--bg); color:var(--text);
         font:14px/1.5 system-ui, sans-serif; }
  header { padding:10px 20px; background:var(--panel);
           border-bottom:1px solid #2a323c; display:flex; gap:12px;
           align-items:baseline; }
  header h1 { font-size:16px; margin:0; }
  header span { color:var(--dim); font-size:12px; }
  header a { color:var(--dim); font-size:12px; margin-left:auto; }
  main { display:grid; grid-template-columns: 1fr 1fr; gap:16px;
         padding:16px 20px; }
  section { background:var(--panel); border:1px solid #2a323c;
            border-radius:8px; padding:14px; }
  h2 { font-size:13px; margin:0 0 10px; color:var(--dim);
       text-transform:uppercase; letter-spacing:.06em; }
  textarea { width:100%; height:170px; background:#0c1014; color:var(--text);
             border:1px solid #2a323c; border-radius:6px; padding:10px;
             font:13px/1.45 ui-monospace, monospace; resize:vertical; }
  input { background:#0c1014; color:var(--text); border:1px solid #2a323c;
          border-radius:6px; padding:7px 10px; }
  button { background:var(--accent); color:#fff; border:0; border-radius:6px;
           padding:7px 14px; margin:8px 8px 0 0; cursor:pointer;
           font-weight:600; }
  button.secondary { background:#2a323c; }
  table { width:100%; border-collapse:collapse; font-size:13px; }
  th, td { text-align:left; padding:5px 8px;
           border-bottom:1px solid #2a323c; }
  th { color:var(--dim); font-weight:500; }
  td a { color:var(--accent); text-decoration:none; margin-right:8px; }
  .state-Running { color:var(--accent); }
  .state-Finished, .state-Stopped { color:var(--ok); }
  .state-Failed { color:var(--bad); }
  pre { background:#0c1014; border:1px solid #2a323c; border-radius:6px;
        padding:10px; max-height:240px; overflow:auto; font-size:12px;
        white-space:pre-wrap; margin:0; }
  .err { color:var(--bad); }
  svg text { fill:var(--text); font:11px ui-monospace, monospace; }
  svg .nodebox { fill:#0c1014; stroke:#2a323c; rx:6; }
  svg .edge { stroke:#3a4450; stroke-width:1.2; fill:none;
              marker-end:url(#arr); }
  .chartrow { display:flex; align-items:center; gap:10px;
              margin-bottom:6px; }
  .chartrow .lbl { width:230px; font:11px ui-monospace, monospace;
                   color:var(--dim); overflow:hidden;
                   text-overflow:ellipsis; white-space:nowrap; }
  .chartrow .val { width:110px; text-align:right;
                   font:12px ui-monospace, monospace; }
  .bp { width:90px; height:8px; background:#0c1014; border-radius:4px;
        overflow:hidden; border:1px solid #2a323c; }
  .bp i { display:block; height:100%; background:var(--ok); }
  .bp i.hot { background:var(--bad); }
  canvas { background:#0c1014; border:1px solid #2a323c; border-radius:4px; }
</style>
</head>
<body>
<header><h1>arroyo_tpu</h1><span>streaming console</span>
  <a href="/api/v1/openapi.json">openapi</a></header>
<main>
  <section style="grid-column: 1 / 3">
    <h2>New pipeline</h2>
    <input id="plname" placeholder="pipeline name" value="pipeline"
           style="width:240px;margin-bottom:8px">
    <textarea id="sql">CREATE TABLE nexmark WITH (connector = 'nexmark',
  event_rate = '20000', num_events = '1000000', batch_size = '4096');
SELECT bid.auction as auction,
       HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) as window,
       count(*) AS num
FROM nexmark WHERE bid is not null GROUP BY 1, 2</textarea>
    <div>
      <button onclick="validateSql()">Validate</button>
      <button onclick="createPipeline()">Create &amp; run</button>
      <button onclick="previewPipeline()">Preview</button>
      <span id="planmsg" class="err"></span>
    </div>
    <div id="dag"></div>
  </section>
  <section>
    <h2>Pipelines</h2>
    <table><thead><tr><th>name</th><th>job</th><th>state</th><th>epoch</th>
    <th></th></tr></thead><tbody id="plrows"></tbody></table>
  </section>
  <section>
    <h2>Output <span id="tailinfo" style="color:var(--dim)"></span></h2>
    <pre id="output">select a job's "tail" to stream results…</pre>
  </section>
  <section style="grid-column: 1 / 3">
    <h2>Connection tables
      <span style="color:var(--dim)">(connector creation wizard)</span></h2>
    <div style="display:flex;gap:10px;align-items:center;flex-wrap:wrap">
      <select id="conn_sel"></select>
      <input id="ct_name" placeholder="table name" style="width:160px">
      <select id="ct_type"><option>source</option><option>sink</option></select>
      <button onclick="createConnTable()">Create</button>
      <span id="ct_msg" style="color:var(--dim)"></span>
    </div>
    <div id="conn_form" style="display:grid;
         grid-template-columns:repeat(auto-fill, minmax(220px, 1fr));
         gap:8px;margin-top:10px"></div>
    <table style="margin-top:10px"><thead><tr><th>name</th><th>connector</th>
      <th>type</th><th></th></tr></thead><tbody id="ctrows"></tbody></table>
  </section>
  <section style="grid-column: 1 / 3">
    <h2>Job detail <span id="jobinfo" style="color:var(--dim)"></span>
      <span style="float:right;text-transform:none;letter-spacing:0">
        <input id="rescale_p" type="number" min="1" max="64"
               placeholder="parallelism" style="width:110px">
        <button class="secondary" style="margin:0;padding:4px 10px"
                onclick="rescaleJob()">Rescale live</button>
        <span id="rescale_msg" style="color:var(--dim)"></span>
      </span></h2>
    <div id="jobdag"></div>
    <div id="charts">select a job's "watch" for live operator rates…</div>
    <div style="display:grid;grid-template-columns:1fr 1fr;gap:12px;
                margin-top:10px">
      <div><h2>Checkpoints
        <span style="color:var(--dim)">(click an epoch for detail)</span>
        </h2><pre id="ckpts">—</pre>
        <pre id="ckptdetail" style="display:none;margin-top:8px"></pre></div>
      <div><h2>Errors</h2><pre id="errors">—</pre></div>
    </div>
    <div style="margin-top:10px"><h2>Autoscaler
      <span id="as_state" style="color:var(--dim)"></span>
      <span style="float:right;text-transform:none;letter-spacing:0">
        <button class="secondary" style="margin:0;padding:4px 10px"
                onclick="toggleAutoscaler()" id="as_toggle">enable</button>
      </span></h2>
      <pre id="autoscaler">decision ledger: watch a job…</pre></div>
    <div style="margin-top:10px"><h2>Latency
      <span id="lat_state" style="color:var(--dim)"></span></h2>
      <pre id="latency">latency observatory: watch a job…</pre></div>
  </section>
</main>
<script>
const $ = (id) => document.getElementById(id);
const esc = (x) => String(x).replace(/[&<>"']/g, (c) => ({
  '&':'&amp;', '<':'&lt;', '>':'&gt;', '"':'&quot;', "'":'&#39;'}[c]));
let tailAbort = null;
let watching = null;       // {pid, jid}
let history = {};          // op -> {t, sent, rates: []}

// ---- SQL + DAG preview ----------------------------------------------------

function layoutDag(g) {
  // layered left-to-right layout (dagre-style): depth = longest path from
  // a source; per-layer order by barycenter sweeps (median of neighbor
  // positions) so multi-branch pipelines (joins, unions) don't tangle
  const depth = {}, order = {};
  const indeg = {};
  g.nodes.forEach(n => indeg[n.operator_id] = 0);
  g.edges.forEach(e => indeg[e.dst]++);
  const q = g.nodes.filter(n => !indeg[n.operator_id])
                   .map(n => n.operator_id);
  q.forEach(id => depth[id] = 0);
  const adj = {}, radj = {};
  g.edges.forEach(e => {
    (adj[e.src] = adj[e.src] || []).push(e.dst);
    (radj[e.dst] = radj[e.dst] || []).push(e.src);
  });
  while (q.length) {
    const u = q.shift();
    for (const v of adj[u] || []) {
      depth[v] = Math.max(depth[v] || 0, depth[u] + 1);
      if (--indeg[v] === 0) q.push(v);
    }
  }
  const layers = [];
  g.nodes.forEach(n => {
    const d = depth[n.operator_id] || 0;
    (layers[d] = layers[d] || []).push(n.operator_id);
  });
  layers.forEach(l => l.forEach((id, i) => order[id] = i));
  const bary = (id, nbrs) => {
    const ps = (nbrs[id] || []).map(v => order[v]).filter(p => p != null);
    return ps.length ? ps.reduce((a, b) => a + b, 0) / ps.length
                     : order[id];
  };
  for (let sweep = 0; sweep < 4; sweep++) {
    const nbrs = sweep % 2 ? adj : radj;  // down then up passes
    const idxs = sweep % 2
      ? [...layers.keys()].reverse() : [...layers.keys()];
    for (const d of idxs) {
      layers[d].sort((a, b) => bary(a, nbrs) - bary(b, nbrs));
      layers[d].forEach((id, i) => order[id] = i);
    }
  }
  // vertically center short layers against the tallest one
  const maxRows = Math.max(...layers.map(l => l.length));
  const offset = {};
  layers.forEach(l => l.forEach(
    id => offset[id] = (maxRows - l.length) / 2));
  return {depth, order, offset};
}

const opKey = (id) => String(id).replace(/\\W/g, '_');

function renderDag(g, overlay) {
  // overlay=true adds per-node live slots (rate text, backpressure bar,
  // history sparkline) that pollJob refreshes in place — the reference
  // console's pipeline-details DAG with live metric badges
  const {depth, order, offset} = layoutDag(g);
  const W = 210, H = 54, GX = 60, GY = 16;
  const pos = {};
  let maxd = 0, maxr = 0;
  g.nodes.forEach(n => {
    const d = depth[n.operator_id] || 0;
    const r = (order[n.operator_id] || 0) + (offset[n.operator_id] || 0);
    pos[n.operator_id] = {x: d * (W + GX) + 10, y: r * (H + GY) + 12};
    maxd = Math.max(maxd, d); maxr = Math.max(maxr, r);
  });
  const sw = (maxd + 1) * (W + GX), sh = (maxr + 1) * (H + GY) + 16;
  let out = `<svg width="100%" viewBox="0 0 ${sw} ${sh}"
    style="margin-top:10px"><defs>
    <marker id="arr" viewBox="0 0 8 8" refX="7" refY="4" markerWidth="7"
     markerHeight="7" orient="auto"><path d="M0 0L8 4L0 8z"
     fill="#3a4450"/></marker></defs>`;
  // chained operators (node.chain = head id) render as one grouped
  // task: a dashed outline behind the member boxes — these run fused in
  // a single TaskRunner with no queue hops between them
  const chains = {};
  g.nodes.forEach(n => {
    if (n.chain) (chains[n.chain] = chains[n.chain] || []).push(
      n.operator_id);
  });
  for (const ids of Object.values(chains)) {
    if (ids.length < 2) continue;
    const xs = ids.map(id => pos[id].x), ys = ids.map(id => pos[id].y);
    const x0 = Math.min(...xs) - 7, y0 = Math.min(...ys) - 7;
    const x1 = Math.max(...xs) + W + 7, y1 = Math.max(...ys) + H + 7;
    out += `<rect x="${x0}" y="${y0}" width="${x1 - x0}"
      height="${y1 - y0}" rx="9" fill="#10161d" stroke="#2a5a8a"
      stroke-dasharray="5 4"/>
      <text x="${x0 + 6}" y="${y0 - 3}" fill="#3f7fb5"
      >chain ×${ids.length}</text>`;
  }
  for (const e of g.edges) {
    const a = pos[e.src], b = pos[e.dst];
    if (!a || !b) continue;
    const x1 = a.x + W, y1 = a.y + H / 2, x2 = b.x, y2 = b.y + H / 2;
    out += `<path class="edge" d="M${x1} ${y1} C ${x1 + GX/2} ${y1},
      ${x2 - GX/2} ${y2}, ${x2} ${y2}"/>
      <text x="${(x1 + x2) / 2 - 20}" y="${(y1 + y2) / 2 - 4}"
      fill="#5a6672">${esc(e.edge_type)}</text>`;
  }
  for (const n of g.nodes) {
    const p = pos[n.operator_id];
    const k = opKey(n.operator_id);
    out += `<g transform="translate(${p.x},${p.y})">
      <rect class="nodebox" id="ov_box_${k}" width="${W}" height="${H}"
        rx="6"/>
      <text x="10" y="21">${esc(n.operator_id).slice(0, 28)}</text>
      <text x="10" y="40" fill="#7a8794">${esc(n.description)
        .slice(0, 26)} ×${n.parallelism}</text>`;
    if (overlay) out += `
      <title id="ov_tt_${k}"></title>
      <text id="ov_rate_${k}" x="${W - 8}" y="16" text-anchor="end"
        fill="#4aa3ff"></text>
      <text id="ov_lag_${k}" x="${W - 8}" y="34" text-anchor="end"
        fill="#7a8794"></text>
      <polyline id="ov_sp_${k}" points="" fill="none" stroke="#4aa3ff"
        stroke-width="1" opacity="0.7"/>
      <rect x="0" y="${H - 4}" width="${W}" height="4" rx="2"
        fill="#1a222c"/>
      <rect id="ov_bp_${k}" x="0" y="${H - 4}" width="0" height="4"
        rx="2" fill="#2e7d32"/>`;
    out += `</g>`;
  }
  return out + '</svg>';
}

function fmtLag(s) {
  if (s == null) return '';
  if (s >= 60) return 'lag ' + (s / 60).toFixed(1) + 'm';
  if (s >= 1) return 'lag ' + s.toFixed(1) + 's';
  return 'lag ' + (s * 1000).toFixed(0) + 'ms';
}

function updateDagOverlay(rows, rollups, profiles) {
  // rollups: controller-aggregated per-operator {event_time_lag,
  // watermark_lag, backpressure} — colors each node by the worse of its
  // backpressure and lag so the hot operator is visible at a glance.
  // profiles (phase profiler, when armed): node FILL tinted by the
  // operator's host-time share and the measured phase breakdown on
  // hover — "where does the time go", per node, at a glance
  const W = 210, H = 54;
  rollups = rollups || {};
  profiles = profiles || {};
  for (const r_ of rows) {
    const k = opKey(r_.op);
    const rateEl = $('ov_rate_' + k);
    if (!rateEl) continue;
    rateEl.textContent = fmtRate(r_.rate);
    const ru = rollups[r_.op] || {};
    const pr = profiles[r_.op];
    const box_ = $('ov_box_' + k);
    if (pr && box_ && pr.host_share != null) {
      // host-dominated nodes glow warm (the "kill the host path"
      // targets); device-dominated ones stay cool
      const hs = pr.host_share;
      box_.setAttribute('fill', hs > 0.9 ? '#3a1b1b'
                              : hs > 0.6 ? '#33241a' : '#16202a');
      const tt = $('ov_tt_' + k);
      if (tt) {
        const ph = Object.entries(pr.phases || {})
          .sort((a, b) => b[1] - a[1])
          .map(([n, s]) => `${n}: ${(s * 1e3).toFixed(1)}ms`);
        const wt = Object.entries(pr.waits || {})
          .sort((a, b) => b[1] - a[1])
          .map(([n, s]) => `${n} (wait): ${(s * 1e3).toFixed(1)}ms`);
        tt.textContent =
          `host ${(hs * 100).toFixed(0)}% · ` +
          `${(pr.host_seconds * 1e3).toFixed(1)}ms host / ` +
          `${(pr.device_seconds * 1e3).toFixed(1)}ms device\\n` +
          ph.concat(wt).join('\\n');
      }
    }
    const bpv = ru.backpressure != null ? ru.backpressure : r_.bp;
    const lag = ru.event_time_lag != null ? ru.event_time_lag
                                          : ru.watermark_lag;
    $('ov_lag_' + k).textContent = fmtLag(lag);
    const bp = $('ov_bp_' + k);
    bp.setAttribute('width', (bpv * W).toFixed(0));
    bp.setAttribute('fill', bpv > 0.7 ? '#c62828'
                           : bpv > 0.3 ? '#f9a825' : '#2e7d32');
    // node border: hot when backpressured OR lagging (10s warn, 60s hot)
    const hot = bpv > 0.7 || (lag != null && lag > 60);
    const warn = bpv > 0.3 || (lag != null && lag > 10);
    const box = $('ov_box_' + k);
    if (box) box.setAttribute(
      'stroke', hot ? '#c62828' : warn ? '#f9a825' : '#2a323c');
    const rates = r_.rates.slice(-40);
    const max = Math.max(1, ...rates);
    const pts = rates.map((v, i) =>
      `${10 + i * ((W - 70) / Math.max(rates.length - 1, 1))},` +
      `${(H - 10) - (v / max) * 18}`).join(' ');
    $('ov_sp_' + k).setAttribute('points', pts);
  }
}

async function validateSql() {
  $('planmsg').textContent = '';
  const r = await fetch('/v1/pipelines/validate', {method:'POST',
    headers:{'content-type':'application/json'},
    body: JSON.stringify({query: $('sql').value})});
  const j = await r.json();
  if (r.ok) {
    $('dag').innerHTML = renderDag(j.graph);
    const diags = j.diagnostics || [];
    const lines = diags.map(d =>
      d.severity + ': ' + d.code + (d.node ? ' [' + d.node + ']' : '')
      + ': ' + d.message);
    // shardcheck plan report: the sharded data plane's contract is 0
    // predicted reshards — surface the verifier's number either way
    // (null means the verifier was disabled: render nothing rather
    // than a fabricated "proven clean")
    if (j.predicted_reshards != null)
      lines.unshift('shardcheck: predicted_reshards='
        + j.predicted_reshards + ' (mesh_shards=' + j.mesh_shards + ')'
        + (j.predicted_reshards ? ' — plan pays device transfers' : ''));
    if (lines.length) $('planmsg').textContent = lines.join('\n');
  }
  else $('planmsg').textContent = j.error;
}

async function createPipeline() {
  $('planmsg').textContent = '';
  const r = await fetch('/v1/pipelines', {method:'POST',
    headers:{'content-type':'application/json'},
    body: JSON.stringify({name: $('plname').value, query: $('sql').value})});
  const j = await r.json();
  if (!r.ok) $('planmsg').textContent = j.error;
  refresh();
}

async function previewPipeline() {
  // bounded run: parallelism 1, sinks swapped to the preview sink, and
  // the output pane auto-tails the stream (reference preview mode)
  $('planmsg').textContent = '';
  const r = await fetch('/v1/pipelines', {method:'POST',
    headers:{'content-type':'application/json'},
    body: JSON.stringify({name: ($('plname').value || 'preview') +
      '-preview', query: $('sql').value, preview: true})});
  const j = await r.json();
  if (!r.ok) { $('planmsg').textContent = j.error; return; }
  refresh();
  watch(j.id, j.jobs[0].id);
  tail(j.id, j.jobs[0].id);
}

// ---- pipelines table ------------------------------------------------------

async function refresh() {
  const r = await fetch('/v1/pipelines');
  const j = await r.json();
  $('plrows').innerHTML = j.data.flatMap(p => p.jobs.map(job => `
    <tr><td>${esc(p.name)}</td><td>${esc(job.id)}</td>
    <td class="state-${esc(job.state)}">${esc(job.state)}</td>
    <td>${job.checkpoint_epoch ?? '—'}</td>
    <td><a href="#" onclick="watch('${p.id}','${job.id}');return false">watch</a>
        <a href="#" onclick="tail('${p.id}','${job.id}');return false">tail</a>
        <a href="#" onclick="stopPipeline('${p.id}');return false">stop</a>
        <a href="#" onclick="deletePipeline('${p.id}');return false">delete</a></td>
    </tr>`)).join('');
}

async function stopPipeline(pid) {
  await fetch('/v1/pipelines/' + pid, {method:'PATCH',
    headers:{'content-type':'application/json'},
    body: JSON.stringify({stop: 'checkpoint'})});
  refresh();
}

async function deletePipeline(pid) {
  if (!confirm('Delete pipeline (stops its jobs)?')) return;
  const r = await fetch('/v1/pipelines/' + pid, {method:'DELETE'});
  if (!r.ok) {
    const j = await r.json().catch(() => ({}));
    alert('delete failed: ' + (j.error || r.status));
    return;  // pipeline still exists: keep watching it
  }
  if (watching && watching.pid === pid) watching = null;
  refresh();
}

async function rescaleJob() {
  // live elastic rescale: snapshot -> re-shard state -> resume at the
  // new parallelism (reference console job-actions analog)
  if (!watching) { $('rescale_msg').textContent = 'watch a job first'; return; }
  const p = parseInt($('rescale_p').value);
  if (!p || p < 1 || p > 64) {
    $('rescale_msg').textContent = 'parallelism must be 1–64'; return;
  }
  $('rescale_msg').textContent = 'rescaling…';
  const r = await fetch('/v1/pipelines/' + watching.pid, {method:'PATCH',
    headers:{'content-type':'application/json'},
    body: JSON.stringify({parallelism: p})});
  const j = await r.json().catch(() => ({}));
  if (!r.ok) { $('rescale_msg').textContent = j.error || 'failed'; return; }
  if (!(j.rescaled_jobs || []).length) {
    $('rescale_msg').textContent = 'no live job to rescale'; return;
  }
  $('rescale_msg').textContent = `running at p=${p}`;
  // parallelism changed: rebuild the DAG (the server refreshed the
  // stored graph) + chart rows
  $('charts').dataset.built = '';
  watch(watching.pid, watching.jid);
}

// ---- live job detail ------------------------------------------------------

function spark(canvas, rates) {
  const ctx = canvas.getContext('2d');
  const w = canvas.width, h = canvas.height;
  ctx.clearRect(0, 0, w, h);
  const max = Math.max(1, ...rates);
  ctx.beginPath();
  ctx.strokeStyle = '#4aa3ff'; ctx.lineWidth = 1.5;
  rates.forEach((v, i) => {
    const x = i * (w / Math.max(rates.length - 1, 1));
    const y = h - 3 - (v / max) * (h - 8);
    i ? ctx.lineTo(x, y) : ctx.moveTo(x, y);
  });
  ctx.stroke();
}

function fmtRate(v) {
  if (v >= 1e6) return (v / 1e6).toFixed(2) + 'M/s';
  if (v >= 1e3) return (v / 1e3).toFixed(1) + 'k/s';
  return v.toFixed(0) + '/s';
}

async function pollJob() {
  if (!watching) return;
  const {pid, jid} = watching;
  // rollups fetch starts concurrently: it's independent of the metric
  // groups and awaiting it serially would add a full round-trip to
  // every poll tick before the sparklines refresh
  const rollupsP = fetch(
    `/v1/pipelines/${pid}/jobs/${jid}/operator_rollups`)
    .catch(() => null);
  const profilesP = fetch(
    `/v1/pipelines/${pid}/jobs/${jid}/profile_rollups`)
    .catch(() => null);
  const r = await fetch(
    `/v1/pipelines/${pid}/jobs/${jid}/operator_metric_groups`);
  if (!r.ok) return;
  const j = await r.json();
  const now = performance.now() / 1000;
  const rows = [];
  for (const g of j.data) {
    let sent = 0, qsize = 0, qrem = 0;
    for (const [k, v] of Object.entries(g.metrics)) {
      if (k.startsWith('arroyo_worker_messages_sent')) sent += v;
      if (k.startsWith('arroyo_worker_tx_queue_size')) qsize += v;
      if (k.startsWith('arroyo_worker_tx_queue_rem')) qrem += v;
    }
    const h_ = history[g.operator_id] ||
      (history[g.operator_id] = {t: now, sent, rates: []});
    const dt = now - h_.t;
    if (dt > 0.5) {
      h_.rates.push(Math.max(0, (sent - h_.sent) / dt));
      if (h_.rates.length > 60) h_.rates.shift();
      h_.t = now; h_.sent = sent;
    }
    const bp = qsize > 0 ? 1 - qrem / qsize : 0;  // backpressure 0..1
    rows.push({op: g.operator_id, rates: h_.rates,
               rate: h_.rates[h_.rates.length - 1] || 0, bp});
  }
  const box = $('charts');
  if (!box.dataset.built || box.dataset.n != rows.length) {
    box.innerHTML = rows.map((r_, i) => `
      <div class="chartrow"><span class="lbl">${esc(r_.op)}</span>
      <canvas id="c${i}" width="420" height="34"></canvas>
      <span class="val" id="v${i}"></span>
      <span class="bp" title="backpressure"><i id="b${i}"></i></span>
      </div>`).join('');
    box.dataset.built = '1'; box.dataset.n = rows.length;
  }
  rows.forEach((r_, i) => {
    spark($('c' + i), r_.rates);
    $('v' + i).textContent = fmtRate(r_.rate);
    const bar = $('b' + i);
    bar.style.width = (r_.bp * 100).toFixed(0) + '%';
    bar.className = r_.bp > 0.7 ? 'hot' : '';
  });
  // controller-side rollups (heartbeat-aggregated): lag + backpressure
  // per operator for the DAG coloring — fetched concurrently above
  let rollups = {};
  try {
    const ro = await rollupsP;
    if (ro && ro.ok) for (const g of (await ro.json()).data || [])
      rollups[g.operator_id] = g;
  } catch (e) { /* rollups are best-effort */ }
  // phase-profile rollups (only populated with ARROYO_PROFILE armed):
  // host-time-share node fill + phase breakdown on hover
  let profiles = {};
  try {
    const po = await profilesP;
    if (po && po.ok) for (const g of (await po.json()).operators || [])
      profiles[g.operator_id] = g;
  } catch (e) { /* profiles are best-effort */ }
  updateDagOverlay(rows, rollups, profiles);

  const ck = await fetch(
    `/v1/pipelines/${pid}/jobs/${jid}/checkpoints`);
  if (ck.ok) {
    const cj = await ck.json();
    $('ckpts').innerHTML = (cj.data || []).slice(-8).reverse().map(c =>
      `<a href="#" style="color:var(--accent);text-decoration:none"
        onclick="ckptDetail(${c.epoch});return false">epoch ${c.epoch}</a>` +
      `  ${esc(c.backend ?? '')} ${c.finished ? '✓' : '…'} ` +
      `(${c.subtasks_completed}/${c.subtasks_total} subtasks)`)
      .join('\\n') || '—';
  }
  const er = await fetch(`/v1/pipelines/${pid}/jobs/${jid}/errors`);
  if (er.ok) {
    const ej = await er.json();
    $('errors').textContent = (ej.data || []).slice(-6).map(e =>
      `${e.created_at ?? ''} ${e.message ?? JSON.stringify(e)}`)
      .join('\\n') || '—';
  }
  pollAutoscaler(jid);
  pollLatency(jid);
}

// ---- autoscaler decision ledger -------------------------------------------

let autoscalerEnabled = false;
async function pollAutoscaler(jid) {
  const r = await fetch(`/v1/jobs/${jid}/autoscaler`).catch(() => null);
  if (!r || !r.ok) return;
  const a = await r.json();
  autoscalerEnabled = !!a.enabled;
  $('as_state').textContent = !a.global_enabled
    ? '(globally disabled: ARROYO_AUTOSCALE=0)'
    : `(${a.enabled ? 'enabled' : 'disabled'} · ` +
      `${a.evaluations} evals · ${a.actuations} actuations · ` +
      `${a.vetoes} vetoes)`;
  $('as_toggle').textContent = a.enabled ? 'disable' : 'enable';
  // decision t is the policy's monotonic clock: show each entry as an
  // offset behind the newest one (0.0s = most recent evaluation)
  const ds = (a.decisions || []).slice(-10);
  const tmax = ds.length ? Number(ds[ds.length - 1].t) : 0;
  const rows = ds.reverse().map(d => {
    const what = d.action === 'scale_up' || d.action === 'scale_down'
      ? `${d.action} ${d.operator_id} ` +
        `${d.from_parallelism}→${d.to_parallelism}` +
        `${d.actuated ? ' ✓' : d.error ? ' ✗ ' + d.error : ''}`
      : d.action === 'veto'
        ? `veto [${d.reason}]` + (d.operator_id ? ` ${d.operator_id}` : '')
        : `hold (${d.reason})`;
    return `-${(tmax - Number(d.t)).toFixed(1)}s  ${what}`;
  });
  $('autoscaler').textContent = rows.join('\\n') || '(no evaluations yet)';
}

async function toggleAutoscaler() {
  if (!watching) { $('as_state').textContent = '(watch a job first)'; return; }
  const r = await fetch(`/v1/jobs/${watching.jid}/autoscaler`, {
    method: 'PUT', headers: {'content-type': 'application/json'},
    body: JSON.stringify({enabled: !autoscalerEnabled})});
  if (!r.ok) {
    const j = await r.json().catch(() => ({}));
    $('as_state').textContent = '(' + (j.error || r.status) + ')';
    return;
  }
  pollAutoscaler(watching.jid);
}

function fmtBytes(b) {
  if (b >= 1e6) return (b / 1e6).toFixed(2) + ' MB';
  if (b >= 1e3) return (b / 1e3).toFixed(1) + ' kB';
  return b + ' B';
}

// ---- latency observatory panel --------------------------------------------

async function pollLatency(jid) {
  // per-sink e2e quantiles + critical-path decomposition + SLO verdict
  // (obs/latency.py); empty unless a worker samples
  // (ARROYO_LATENCY_SAMPLE_N>0)
  const r = await fetch(`/v1/jobs/${jid}/latency`).catch(() => null);
  if (!r || !r.ok) return;
  const a = await r.json();
  const slo = a.slo || {};
  const last = slo.last || {};
  $('lat_state').textContent = !a.sample_n
    ? '(sampling off: set ARROYO_LATENCY_SAMPLE_N)'
    : `(1-in-${a.sample_n} sampling · ` +
      (slo.configured
        ? `SLO ${last.violating ? 'VIOLATING' : 'ok'} · ` +
          `burn ${last.burn_rate ?? 0} · ` +
          `${slo.violations_total ?? 0} violations`
        : 'no SLO') + ')';
  const lines = [];
  for (const [op, q] of Object.entries(a.sinks || {}))
    lines.push(`${op}  p50 ${q.p50_ms}ms  p99 ${q.p99_ms}ms` +
               `  (${q.count} samples)`);
  for (const [op, age] of Object.entries(a.watermark_age_ms || {}))
    lines.push(`${op}  watermark age ${age}ms`);
  const cp = a.critical_path || {};
  if (cp.dominant) {
    lines.push(`critical path: ${cp.dominant} ` +
               `(${((cp.dominant_share || 0) * 100).toFixed(0)}% of ` +
               `${(cp.total_secs || 0).toFixed(2)}s measured)`);
    for (const [st, secs] of Object.entries(cp.stages || {}))
      lines.push(`  ${st}: ${secs.toFixed(3)}s`);
  }
  for (const [t, b] of Object.entries(a.device_state_bytes || {}))
    lines.push(`device ${t}: ${fmtBytes(b)}`);
  $('latency').textContent = lines.join('\\n') || '—';
}

async function ckptDetail(epoch) {
  // per-operator files + bytes for one checkpoint epoch (the reference
  // console's checkpoint-details panel, jobs.rs get_checkpoint_details)
  if (!watching) return;
  const {pid, jid} = watching;
  const el = $('ckptdetail');
  el.style.display = '';
  el.textContent = `epoch ${epoch}: loading…`;
  const r = await fetch(`/v1/pipelines/${pid}/jobs/${jid}/checkpoints/` +
                        `${epoch}/operator_checkpoint_groups`);
  // the user may have switched jobs while the fetch was in flight
  if (!watching || watching.pid !== pid || watching.jid !== jid) return;
  if (!r.ok) { el.textContent = `epoch ${epoch}: ${r.status}`; return; }
  const j = await r.json();
  const rows = (j.data || []).map(g =>
    `${g.operator_id.padEnd(28)} ${fmtBytes(g.bytes).padStart(10)}` +
    `  ${g.files.length} file${g.files.length === 1 ? '' : 's'}`);
  el.textContent = `epoch ${epoch} ` +
    `${j.finished === false ? '(in progress)' : ''}\\n` +
    (rows.join('\\n') || '(no files)');
}

async function seedHistory(pid, jid) {
  // persistent server-side history (sqlite sampler): charts survive a
  // page reload instead of starting empty
  try {
    const r = await fetch(
      `/v1/pipelines/${pid}/jobs/${jid}/metrics_history`);
    if (!r.ok) return;
    const j = await r.json();
    for (const s of j.data || []) {
      const pts = s.points || [];
      if (!pts.length) continue;
      const rates = [];
      for (let i = 1; i < pts.length; i++) {
        const dt = pts[i][0] - pts[i-1][0];
        if (dt > 0) rates.push(Math.max(0, (pts[i][1] - pts[i-1][1]) / dt));
      }
      const last = pts[pts.length - 1];
      history[s.operator_id] = {
        t: performance.now() / 1000, sent: last[1],
        rates: rates.slice(-60)};
    }
  } catch (e) { /* history is best-effort */ }
}

function watch(pid, jid) {
  watching = {pid, jid};
  history = {};
  $('jobinfo').textContent = `(${jid})`;
  $('charts').dataset.built = '';
  $('jobdag').innerHTML = '';
  $('ckptdetail').style.display = 'none';
  fetch('/v1/pipelines/' + pid).then(r => r.json()).then(p => {
    if (p.graph) $('jobdag').innerHTML = renderDag(p.graph, true);
  }).catch(() => {});
  seedHistory(pid, jid).then(pollJob);
}

// ---- SSE output tail ------------------------------------------------------

async function tail(pid, jid) {
  if (tailAbort) tailAbort.abort();
  tailAbort = new AbortController();
  $('output').textContent = '';
  $('tailinfo').textContent = `(${jid})`;
  const resp = await fetch(`/v1/pipelines/${pid}/jobs/${jid}/output`,
                           {signal: tailAbort.signal});
  const reader = resp.body.getReader();
  const dec = new TextDecoder();
  let buf = '';
  for (;;) {
    const {done, value} = await reader.read();
    if (done) break;
    buf += dec.decode(value, {stream: true});
    let i;
    while ((i = buf.indexOf('\\n\\n')) >= 0) {
      const line = buf.slice(0, i); buf = buf.slice(i + 2);
      if (!line.startsWith('data: ')) continue;
      const ev = JSON.parse(line.slice(6));
      for (const row of ev.rows || [])
        $('output').textContent += JSON.stringify(row) + '\\n';
      if (ev.done) $('output').textContent += '— end of stream —\\n';
      $('output').scrollTop = $('output').scrollHeight;
    }
  }
}

let connectors = [];
async function loadConnectors() {
  connectors = (await (await fetch('/v1/connectors')).json()).data
    .filter((c) => c.config_schema);
  $('conn_sel').innerHTML = connectors.map(
    (c) => `<option value="${esc(c.id)}">${esc(c.id)}</option>`).join('');
  $('conn_sel').onchange = renderConnForm;
  renderConnForm();
}
function renderConnForm() {
  const meta = connectors.find((c) => c.id === $('conn_sel').value);
  if (!meta) return;
  const props = meta.config_schema.properties || {};
  const req = new Set(meta.config_schema.required || []);
  $('conn_form').innerHTML = Object.entries(props).map(([k, spec]) => {
    const ph = (spec.type || (spec.anyOf ? 'optional' : '')) +
      (spec.default !== undefined && spec.default !== null
        ? ' (default ' + esc(JSON.stringify(spec.default)) + ')' : '');
    return `<label style="font-size:12px;color:var(--dim)">` +
      `${esc(k)}${req.has(k) ? ' *' : ''}<br>` +
      `<input data-cfg="${esc(k)}" placeholder="${esc(ph)}" ` +
      `style="width:100%"></label>`;
  }).join('');
}
async function createConnTable() {
  const meta = connectors.find((c) => c.id === $('conn_sel').value);
  const props = (meta && meta.config_schema.properties) || {};
  const cfg = {};
  for (const inp of document.querySelectorAll('[data-cfg]')) {
    if (inp.value === '') continue;
    const spec = props[inp.dataset.cfg] || {};
    const t = spec.type;
    // object/array fields (format_options, client_configs) must post as
    // real JSON values, not strings
    cfg[inp.dataset.cfg] = (t === 'object' || t === 'array')
      ? JSON.parse(inp.value) : inp.value;
  }
  const body = {name: $('ct_name').value, connector: $('conn_sel').value,
                table_type: $('ct_type').value, config: cfg};
  const resp = await fetch('/v1/connection_tables',
    {method: 'POST', headers: {'content-type': 'application/json'},
     body: JSON.stringify(body)});
  const out = await resp.json();
  $('ct_msg').textContent = resp.ok ? 'created'
    : (out.error || JSON.stringify(out));
  $('ct_msg').className = resp.ok ? '' : 'err';
  refreshConnTables();
}
async function refreshConnTables() {
  const data = (await (await fetch('/v1/connection_tables')).json()).data
    || [];
  $('ctrows').innerHTML = data.map((t) =>
    `<tr><td>${esc(t.name)}</td><td>${esc(t.connector)}</td>` +
    `<td>${esc(t.table_type || '')}</td>` +
    `<td><a href="#" onclick="delConnTable('${esc(t.id)}');return false">` +
    `delete</a></td></tr>`).join('');
}
async function delConnTable(id) {
  await fetch('/v1/connection_tables/' + id, {method: 'DELETE'});
  refreshConnTables();
}
loadConnectors();
refreshConnTables();
setInterval(refreshConnTables, 5000);

refresh();
setInterval(refresh, 2000);
setInterval(pollJob, 1000);
</script>
</body>
</html>
"""
