"""Minimal web console served by the API (the arroyo-console analog).

The reference ships a React/Vite SPA (arroyo-console/) talking to the REST
API; this is a single-file, dependency-free page with the same core
workflow: write SQL, validate (pipeline DAG preview), create, watch job
state, tail output over SSE, and inspect per-operator metrics.
"""

CONSOLE_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>arroyo_tpu console</title>
<style>
  :root { --bg:#101418; --panel:#1a2027; --text:#d6dde5; --accent:#4aa3ff;
          --ok:#3fb68b; --bad:#e5604c; --dim:#7a8794; }
  * { box-sizing: border-box; }
  body { margin:0; background:var(--bg); color:var(--text);
         font:14px/1.5 system-ui, sans-serif; }
  header { padding:10px 20px; background:var(--panel);
           border-bottom:1px solid #2a323c; display:flex; gap:12px;
           align-items:baseline; }
  header h1 { font-size:16px; margin:0; }
  header span { color:var(--dim); font-size:12px; }
  main { display:grid; grid-template-columns: 1fr 1fr; gap:16px;
         padding:16px 20px; }
  section { background:var(--panel); border:1px solid #2a323c;
            border-radius:8px; padding:14px; }
  h2 { font-size:13px; margin:0 0 10px; color:var(--dim);
       text-transform:uppercase; letter-spacing:.06em; }
  textarea { width:100%; height:180px; background:#0c1014; color:var(--text);
             border:1px solid #2a323c; border-radius:6px; padding:10px;
             font:13px/1.45 ui-monospace, monospace; resize:vertical; }
  button { background:var(--accent); color:#fff; border:0; border-radius:6px;
           padding:7px 14px; margin:8px 8px 0 0; cursor:pointer;
           font-weight:600; }
  button.secondary { background:#2a323c; }
  table { width:100%; border-collapse:collapse; font-size:13px; }
  th, td { text-align:left; padding:5px 8px;
           border-bottom:1px solid #2a323c; }
  th { color:var(--dim); font-weight:500; }
  .state-Running { color:var(--accent); }
  .state-Finished, .state-Stopped { color:var(--ok); }
  .state-Failed { color:var(--bad); }
  pre { background:#0c1014; border:1px solid #2a323c; border-radius:6px;
        padding:10px; max-height:260px; overflow:auto; font-size:12px;
        white-space:pre-wrap; }
  #dag { color:var(--dim); font-size:12px; }
  .err { color:var(--bad); }
</style>
</head>
<body>
<header><h1>arroyo_tpu</h1><span>streaming console</span></header>
<main>
  <section style="grid-column: 1 / 3">
    <h2>New pipeline</h2>
    <input id="plname" placeholder="pipeline name" value="pipeline"
           style="width:240px;background:#0c1014;color:var(--text);
                  border:1px solid #2a323c;border-radius:6px;
                  padding:7px 10px;margin-bottom:8px">
    <textarea id="sql">CREATE TABLE impulse WITH (connector = 'impulse',
  event_rate = '1000', message_count = '10000', batch_size = '256');
SELECT counter, counter * 2 as doubled FROM impulse
WHERE counter % 2 = 0</textarea>
    <div>
      <button onclick="validateSql()">Validate</button>
      <button onclick="createPipeline()">Create &amp; run</button>
    </div>
    <div id="dag"></div>
  </section>
  <section>
    <h2>Pipelines</h2>
    <table><thead><tr><th>name</th><th>job</th><th>state</th><th>epoch</th>
    <th></th></tr></thead><tbody id="plrows"></tbody></table>
  </section>
  <section>
    <h2>Output <span id="tailinfo"></span></h2>
    <pre id="output">select a job's "tail" to stream results…</pre>
  </section>
  <section style="grid-column: 1 / 3">
    <h2>Operator metrics</h2>
    <pre id="metrics">—</pre>
  </section>
</main>
<script>
const $ = (id) => document.getElementById(id);
const esc = (x) => String(x).replace(/[&<>"']/g, (c) => ({
  '&':'&amp;', '<':'&lt;', '>':'&gt;', '"':'&quot;', "'":'&#39;'}[c]));
let tailAbort = null;

async function validateSql() {
  const r = await fetch('/v1/pipelines/validate', {method:'POST',
    headers:{'content-type':'application/json'},
    body: JSON.stringify({query: $('sql').value})});
  const j = await r.json();
  $('dag').innerHTML = r.ok
    ? 'DAG: ' + j.graph.nodes.map(n =>
        `${n.operator_id}[${n.parallelism}]`).join(' → ')
    : `<span class="err">${esc(j.error)}</span>`;
}

async function createPipeline() {
  const r = await fetch('/v1/pipelines', {method:'POST',
    headers:{'content-type':'application/json'},
    body: JSON.stringify({name: $('plname').value, query: $('sql').value})});
  const j = await r.json();
  $('dag').innerHTML = r.ok ? `created ${esc(j.id)}`
    : `<span class="err">${esc(j.error)}</span>`;
  refresh();
}

async function refresh() {
  const r = await fetch('/v1/pipelines');
  const j = await r.json();
  $('plrows').innerHTML = j.data.flatMap(p => p.jobs.map(job => `
    <tr><td>${esc(p.name)}</td><td>${esc(job.id)}</td>
    <td class="state-${esc(job.state)}">${esc(job.state)}</td>
    <td>${job.checkpoint_epoch ?? '—'}</td>
    <td><a href="#" onclick="tail('${p.id}','${job.id}');return false">tail</a>
        <a href="#" onclick="showMetrics('${p.id}','${job.id}');return false">metrics</a>
        <a href="#" onclick="stopPipeline('${p.id}');return false">stop</a></td>
    </tr>`)).join('');
}

async function stopPipeline(pid) {
  await fetch('/v1/pipelines/' + pid, {method:'PATCH',
    headers:{'content-type':'application/json'},
    body: JSON.stringify({stop: 'checkpoint'})});
  refresh();
}

async function tail(pid, jid) {
  if (tailAbort) tailAbort.abort();
  tailAbort = new AbortController();
  $('output').textContent = '';
  $('tailinfo').textContent = `(${jid})`;
  const resp = await fetch(`/v1/pipelines/${pid}/jobs/${jid}/output`,
                           {signal: tailAbort.signal});
  const reader = resp.body.getReader();
  const dec = new TextDecoder();
  let buf = '';
  for (;;) {
    const {done, value} = await reader.read();
    if (done) break;
    buf += dec.decode(value, {stream: true});
    let i;
    while ((i = buf.indexOf('\\n\\n')) >= 0) {
      const line = buf.slice(0, i); buf = buf.slice(i + 2);
      if (!line.startsWith('data: ')) continue;
      const ev = JSON.parse(line.slice(6));
      for (const row of ev.rows || [])
        $('output').textContent += JSON.stringify(row) + '\\n';
      if (ev.done) $('output').textContent += '— end of stream —\\n';
      $('output').scrollTop = $('output').scrollHeight;
    }
  }
}

async function showMetrics(pid, jid) {
  const r = await fetch(
    `/v1/pipelines/${pid}/jobs/${jid}/operator_metric_groups`);
  const j = await r.json();
  $('metrics').textContent = j.data.map(g =>
    g.operator_id + '\\n' + Object.entries(g.metrics).map(
      ([k, v]) => `  ${k} = ${v}`).join('\\n')).join('\\n') || '—';
}

refresh();
setInterval(refresh, 2000);
</script>
</body>
</html>
"""
