"""WorkerServer: the worker process main — registers with the controller,
hosts the engine for its assigned subtasks, relays control responses, and
heartbeats (analog of /root/reference/arroyo-worker/src/lib.rs:252-670).

Serves WorkerGrpc {StartExecution, Checkpoint, Commit, StopExecution,
JobFinished, LoadCompactedData} (lib.rs:489-670) over the msgpack transport
and opens the TCP data plane for cross-worker edges."""

from __future__ import annotations

import asyncio
import logging
import os
import cloudpickle as pickle
import uuid
from typing import Any, Dict, Optional, Tuple

from ..config import config
from ..engine.engine import Engine, RunningEngine
from ..network.data_plane import NetworkManager
from ..rpc.transport import RpcClient, RpcServer
from ..state.backend import ParquetBackend
from ..types import CheckpointBarrier, ControlMessage, ControlResp, StopMode, now_micros

logger = logging.getLogger(__name__)


class WorkerServer:
    def __init__(self, controller_addr: str, job_id: str,
                 slots: Optional[int] = None,
                 worker_id: Optional[str] = None,
                 host: str = "127.0.0.1"):
        self.controller_addr = controller_addr
        self.job_id = job_id
        self.slots = slots or config().task_slots
        self.worker_id = worker_id or f"worker-{uuid.uuid4().hex[:8]}"
        self.host = host
        self.network = NetworkManager(job_id=job_id or "")
        self.rpc = RpcServer()
        self.controller = RpcClient(controller_addr, "ControllerGrpc")
        self.engine: Optional[Engine] = None
        self.running: Optional[RunningEngine] = None
        self._relay_task: Optional[asyncio.Task] = None
        self._hb_task: Optional[asyncio.Task] = None
        self._hb_stop = None  # threading.Event, set by _heartbeat_loop
        self._done = asyncio.Event()

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        data_port = await self.network.open_listener(self.host)
        self.rpc.add_service("WorkerGrpc", {
            "StartExecution": self._start_execution,
            "Checkpoint": self._checkpoint,
            "Commit": self._commit,
            "StopExecution": self._stop_execution,
            "JobFinished": self._job_finished,
            "LoadCompactedData": self._load_compacted,
        })
        rpc_port = await self.rpc.start(self.host)
        await self.controller.wait_ready()
        await self.controller.call("RegisterWorker", {
            "worker_id": self.worker_id,
            "job_id": self.job_id,
            "rpc_address": f"{self.host}:{rpc_port}",
            "data_address": f"{self.host}:{data_port}",
            "slots": self.slots,
            "run_id": "0",
        })
        self._hb_task = asyncio.ensure_future(self._heartbeat_loop())
        logger.info("worker %s registered (rpc=%s data=%s)",
                    self.worker_id, rpc_port, data_port)

    async def wait_done(self) -> None:
        await self._done.wait()

    async def shutdown(self) -> None:
        if self._hb_stop is not None:
            # stop the heartbeat thread directly: cancelling the parked
            # task is not enough on every shutdown path, and a surviving
            # daemon thread keeps dialing the dead controller
            self._hb_stop.set()
        for t in (self._hb_task, self._relay_task):
            if t is not None:
                t.cancel()
        await self.network.close()
        await self.rpc.stop()
        await self.controller.close()
        self._done.set()

    async def _heartbeat_loop(self) -> None:
        """Heartbeats run on a dedicated thread with their own event loop
        and channel: a worker stalled in a long synchronous jit compile is
        busy, not dead, and must not trip the controller's 30s timeout
        (the reference's heartbeat likewise lives on the control thread,
        arroyo-worker/src/lib.rs:467-476)."""
        import threading

        interval = config().heartbeat_interval_secs
        controller_addr = self.controller_addr
        worker_id, job_id = self.worker_id, self.job_id
        stop = threading.Event()
        self._hb_stop = stop

        def run() -> None:
            async def beat() -> None:
                client = RpcClient(controller_addr, "ControllerGrpc")
                rollup_warned = False
                while not stop.is_set():
                    # chunked sleep: exit promptly on shutdown
                    slept = 0.0
                    while slept < interval and not stop.is_set():
                        await asyncio.sleep(0.2)
                        slept += 0.2
                    if stop.is_set():
                        break
                    try:
                        # piggyback a compact per-operator metric rollup on
                        # the heartbeat: the controller aggregates these
                        # into job-level rates/lag/backpressure without
                        # ever scraping workers over HTTP (registry
                        # collection is thread-safe, so reading it from
                        # the heartbeat thread is fine).  msgpack-packed:
                        # the proto field is opaque bytes so the nested
                        # {op: {metric: value}} map needs no proto schema
                        try:
                            from ..obs.metrics import job_operator_summary
                            from ..rpc.transport import _ser_msgpack

                            summary = _ser_msgpack(
                                job_operator_summary(job_id))
                        except Exception:
                            # heartbeats must keep flowing without the
                            # rollup, but say so once: a persistent pack
                            # failure otherwise silently blanks every
                            # job-level rollup the console serves
                            if not rollup_warned:
                                rollup_warned = True
                                logger.warning(
                                    "heartbeat metrics rollup failed; "
                                    "heartbeats continue without metrics",
                                    exc_info=True)
                            summary = None
                        await client.call("Heartbeat", {
                            "worker_id": worker_id, "job_id": job_id,
                            "time": now_micros(), "metrics": summary})
                    except Exception as e:
                        if not stop.is_set():
                            logger.warning("heartbeat failed: %s", e)
                await client.close()

            asyncio.run(beat())

        threading.Thread(target=run, name="heartbeat", daemon=True).start()
        # keep the asyncio task interface: park until cancelled, then stop
        # the thread
        try:
            await asyncio.Event().wait()
        finally:
            stop.set()

    # -- WorkerGrpc handlers ----------------------------------------------

    async def _start_execution(self, req: Dict) -> Dict:
        # return immediately: deserializing the program and building the
        # engine can take seconds (first jax init in a fresh process), and
        # the controller's RPC deadline must not ride on it — failures are
        # reported through WorkerError (the reference's StartExecution also
        # returns before tasks run, arroyo-worker/src/lib.rs:489-545)
        asyncio.ensure_future(self._start_execution_async(req))
        return {}

    async def _start_execution_async(self, req: Dict) -> None:
        try:
            program = pickle.loads(req["program"])
            assignments = {
                (t["operator_id"], t["subtask_index"]): t["worker_id"]
                for t in req["tasks"]}
            addrs = dict(req.get("worker_data_addrs") or {})
            for wid, addr in addrs.items():
                if wid != self.worker_id:
                    await self.network.connect(addr)
            backend = ParquetBackend.for_url(
                req.get("checkpoint_url") or config().checkpoint_url)
            self.engine = Engine(
                program, self.job_id, backend=backend,
                restore_epoch=req.get("restore_epoch"),
                assignments=assignments, my_worker_id=self.worker_id,
                worker_data_addrs=addrs, network=self.network)
            self.running = self.engine.start()
            self._relay_task = asyncio.ensure_future(self._relay_loop())
        except Exception as e:
            logger.error("StartExecution failed: %s", e, exc_info=True)
            try:
                await self.controller.call("WorkerError", {
                    "worker_id": self.worker_id, "job_id": self.job_id,
                    "error": f"StartExecution failed: {e}"})
            except Exception:
                pass

    async def _relay_loop(self) -> None:
        """Forward engine ControlResps to the controller (the reference's
        control thread, arroyo-worker/src/lib.rs:369-487)."""
        n_tasks = len(self.engine.subtasks)
        finished = 0
        while finished < n_tasks:
            resp: ControlResp = await self.engine.control_resp.get()
            try:
                await self._relay_one(resp)
            except Exception as e:
                logger.warning("relay to controller failed: %s", e)
            if resp.kind in ("task_finished", "task_failed"):
                finished += 1
        try:
            await self.controller.call("WorkerFinished", {
                "worker_id": self.worker_id, "job_id": self.job_id})
        except Exception as e:
            logger.warning("WorkerFinished failed: %s", e)

    async def _relay_one(self, resp: ControlResp) -> None:
        base = {"job_id": self.job_id, "operator_id": resp.operator_id,
                "subtask": resp.task_index}
        if resp.kind == "task_started":
            await self.controller.call("TaskStarted",
                                       base | {"worker_id": self.worker_id})
        elif resp.kind == "checkpoint_event":
            ev = resp.checkpoint_event
            await self.controller.call("TaskCheckpointEvent", base | {
                "epoch": ev.checkpoint_epoch,
                "event_type": ev.event_type.value, "time": ev.time})
        elif resp.kind == "checkpoint_completed":
            m = resp.subtask_metadata
            await self.controller.call("TaskCheckpointCompleted", base | {
                "epoch": m.epoch, "bytes": m.bytes,
                "watermark": m.watermark, "start_time": m.start_time,
                "finish_time": m.finish_time,
                "has_committing_data": bool(m.committing_data)})
        elif resp.kind == "task_finished":
            await self.controller.call("TaskFinished", base)
        elif resp.kind == "task_failed":
            await self.controller.call("TaskFailed",
                                       base | {"error": resp.error or ""})

    async def _await_started(self, timeout: float = 120.0) -> None:
        """StartExecution returns before the engine is built; control RPCs
        that need the running engine park here until it exists."""
        deadline = asyncio.get_event_loop().time() + timeout
        while self.running is None:
            if asyncio.get_event_loop().time() > deadline:
                raise RuntimeError("engine not started")
            await asyncio.sleep(0.05)

    async def _checkpoint(self, req: Dict) -> Dict:
        await self._await_started()
        barrier = CheckpointBarrier(req["epoch"], req.get("min_epoch", 0),
                                    req.get("timestamp", now_micros()),
                                    req.get("then_stop", False))
        # barriers are injected at sources only (§3.3)
        for q in self.running.source_controls():
            await q.put(ControlMessage.checkpoint(barrier))
        return {}

    async def _commit(self, req: Dict) -> Dict:
        await self._await_started()
        await self.running.commit(req["epoch"])
        return {}

    async def _stop_execution(self, req: Dict) -> Dict:
        if self.running is not None:
            mode = StopMode(req.get("stop_mode", "graceful"))
            await self.running.stop(mode)
        return {}

    async def _job_finished(self, req: Dict) -> Dict:
        asyncio.ensure_future(self.shutdown())
        return {}

    async def _load_compacted(self, req: Dict) -> Dict:
        # Hot-swap compacted checkpoint files (LoadCompactedData,
        # arroyo-worker/src/lib.rs:602-631): forward to the operator's tasks.
        if self.running is not None:
            await self.running.load_compacted(
                req.get("operator_id", ""),
                # operator_id rides in the payload so a chained task can
                # route the hot-swap to the right member
                {"operator_id": req.get("operator_id", ""),
                 "epoch": req.get("epoch"), "files": req.get("files", []),
                 "dropped": req.get("dropped", [])})
        return {}


async def run_worker(controller_addr: str, job_id: str,
                     slots: Optional[int] = None,
                     worker_id: Optional[str] = None) -> None:
    w = WorkerServer(controller_addr, job_id, slots, worker_id=worker_id)
    await w.start()
    await w.wait_done()


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    asyncio.run(run_worker(
        os.environ["CONTROLLER_ADDR"], os.environ["JOB_ID"],
        int(os.environ.get("TASK_SLOTS", "16")),
        # the node daemon assigns the id so its WorkerFinished reports
        # match what the controller registered
        os.environ.get("WORKER_ID")))


if __name__ == "__main__":
    main()
