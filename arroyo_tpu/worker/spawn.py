"""Shared worker-process spawn logic for every scheduler/daemon that
starts `python -m arroyo_tpu.worker.server` as an OS process."""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Dict, Optional


def spawn_worker_process(job_id: str, controller_addr: str, slots: int,
                         extra_env: Optional[Dict[str, str]] = None
                         ) -> subprocess.Popen:
    """Start a worker OS process with the package importable from any
    cwd; CPU workers are kept away from the axon TPU-tunnel plugin
    (its sitecustomize can stall interpreter start on tunnel
    handshakes)."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env.update(extra_env or {})
    env.update({
        "CONTROLLER_ADDR": controller_addr,
        "JOB_ID": job_id,
        "TASK_SLOTS": str(slots),
        "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu"),
        "PYTHONPATH": (pkg_root + os.pathsep + env["PYTHONPATH"]
                       if env.get("PYTHONPATH") else pkg_root),
    })
    if env["JAX_PLATFORMS"] == "cpu":
        env.pop("PALLAS_AXON_POOL_IPS", None)
    return subprocess.Popen(
        [sys.executable, "-m", "arroyo_tpu.worker.server"], env=env)
