"""Node daemon: a standalone per-host process manager for clusters
without Kubernetes (/root/reference/arroyo-node/src/main.rs:44-319).

Serves NodeGrpc {StartWorker, StopWorker, GetWorkers} on the protobuf
control-plane wire: StartWorker spawns a worker OS process with the
requested env (JOB_ID, CONTROLLER_ADDR, TASK_SLOTS, ...), a reaper task
watches for exits and reports WorkerFinished to the controller.  The
reference additionally ships a per-pipeline worker binary in 2MB gRPC
chunks (main.rs:98-236); here every pipeline runs the same Python
worker and receives its program via StartExecution, so no binary
transfer exists by design.

Run: ``python -m arroyo_tpu.node.daemon`` (NODE_PORT, default 9290).
"""

from __future__ import annotations

import asyncio
import logging
import os
import subprocess
import uuid
from typing import Dict, Optional

from ..rpc.transport import RpcClient, RpcServer

logger = logging.getLogger(__name__)


class NodeServer:
    def __init__(self, host: str = "127.0.0.1"):
        self.host = host
        self.rpc = RpcServer()
        self.addr: Optional[str] = None
        self._procs: Dict[str, subprocess.Popen] = {}  # worker_id -> proc
        self._meta: Dict[str, Dict] = {}  # worker_id -> {job_id, ctrl}
        self._reaper: Optional[asyncio.Task] = None

    async def start(self, port: int = 0) -> str:
        self.rpc.add_service("NodeGrpc", {
            "StartWorker": self._start_worker,
            "StopWorker": self._stop_worker,
            "GetWorkers": self._get_workers,
        })
        p = await self.rpc.start(self.host, port)
        self.addr = f"{self.host}:{p}"
        self._reaper = asyncio.ensure_future(self._reap_loop())
        logger.info("node daemon on %s", self.addr)
        return self.addr

    async def stop(self) -> None:
        if self._reaper:
            self._reaper.cancel()
        for wid in list(self._procs):
            self._kill(wid, force=True)
        await self.rpc.stop()

    # -- NodeGrpc ----------------------------------------------------------

    async def _start_worker(self, req: Dict) -> Dict:
        from ..worker.spawn import spawn_worker_process

        worker_id = f"worker-{uuid.uuid4().hex[:8]}"
        extra = dict(req.get("env") or {})
        extra["WORKER_ID"] = worker_id  # daemon-assigned id so reaper
        # reports match what the controller registered
        proc = spawn_worker_process(
            req["job_id"], req["controller_addr"],
            req.get("slots") or 16, extra)
        self._procs[worker_id] = proc
        self._meta[worker_id] = {"job_id": req["job_id"],
                                 "ctrl": req["controller_addr"]}
        logger.info("started worker %s (pid %d) for job %s",
                    worker_id, proc.pid, req["job_id"])
        return {"worker_id": worker_id}

    async def _stop_worker(self, req: Dict) -> Dict:
        self._kill(req["worker_id"], force=req.get("force", False))
        return {}

    async def _get_workers(self, req: Dict) -> Dict:
        return {"worker_ids": [w for w, p in self._procs.items()
                               if p.poll() is None]}

    # -- supervision --------------------------------------------------------

    def _kill(self, worker_id: str, force: bool) -> None:
        p = self._procs.get(worker_id)
        if p is None or p.poll() is not None:
            return
        if force:
            p.kill()
        else:
            p.terminate()

    async def _reap_loop(self) -> None:
        """Reap exited workers and report WorkerFinished to the controller
        (main.rs:237-319)."""
        while True:
            await asyncio.sleep(0.2)
            for wid, p in list(self._procs.items()):
                if p.poll() is None:
                    continue
                meta = self._meta.pop(wid, None)
                del self._procs[wid]
                logger.info("worker %s exited rc=%s", wid, p.returncode)
                if meta:
                    client = None
                    try:
                        client = RpcClient(meta["ctrl"], "ControllerGrpc")
                        await client.call("WorkerFinished", {
                            "worker_id": wid, "job_id": meta["job_id"]})
                    except Exception as e:
                        logger.warning("WorkerFinished report failed: %s", e)
                    finally:
                        if client is not None:
                            await client.close()


async def run_node(port: int = 0, host: str = "127.0.0.1") -> None:
    node = NodeServer(host)
    await node.start(port)
    await asyncio.Event().wait()


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    asyncio.run(run_node(int(os.environ.get("NODE_PORT", "9290")),
                         os.environ.get("NODE_HOST", "127.0.0.1")))


if __name__ == "__main__":
    main()
