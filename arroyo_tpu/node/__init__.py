from .daemon import NodeServer, run_node  # noqa: F401
