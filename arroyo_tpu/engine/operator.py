"""Physical operator base classes.

The reference generates each operator's runtime loop with proc-macros
(``#[process_fn]``/``#[source_fn]``/``#[co_process_fn]``,
/root/reference/arroyo-macro/src/lib.rs:292-371); hooks like
``on_start/on_close/handle_timer/handle_watermark/handle_commit/tables``
(lib.rs:763-822) become overridable methods here, and a single generic
:class:`~arroyo_tpu.engine.task.TaskRunner` replaces the generated loops.

Operators process whole columnar batches; hot paths are jitted JAX functions
the operator owns."""

from __future__ import annotations

import asyncio
from enum import Enum
from typing import Any, Dict, List, Optional

from ..state.tables import TableDescriptor
from ..types import Batch, CheckpointBarrier, ControlMessage
from .context import Context


class SourceFinishType(Enum):
    """SourceFinishType (arroyo-worker/src/lib.rs): how a source loop ended."""

    FINAL = "final"  # emit final watermark + EndOfData
    GRACEFUL = "graceful"  # stop requested; checkpoint state is current
    IMMEDIATE = "immediate"


class Operator:
    """Base for single-input (and generic) operators."""

    # True when the operator records its own per-batch lag/latency
    # metrics (ChainedOperator attributes them per member); the
    # TaskRunner then skips its task-level observation to avoid
    # double-counting.
    own_batch_metrics = False

    # arroyosan runtime sanitizer (analysis/sanitizer.py); the
    # TaskRunner installs the engine's instance here, None when
    # ARROYO_SANITIZE is off — hook sites guard on `is not None`
    sanitizer: Optional[Any] = None

    def __init__(self, name: str):
        self.name = name

    def tables(self) -> List[TableDescriptor]:
        return []

    async def open(self, ctx: Context) -> None:
        """Task startup: register state tables, restore persisted timers
        (reserved table '[' — arroyo-worker/src/lib.rs:152), then
        ``on_start``.  ChainedOperator overrides to open every member
        against its own per-member context."""
        for desc in self.tables():
            ctx.state.register(desc)
        timer_table = ctx.state.get_global_keyed_state("[", "timers")
        saved_timers = timer_table.get("timers")
        if saved_timers:
            ctx.timers.restore(saved_timers)
        await self.on_start(ctx)

    async def checkpoint_state(self, barrier: CheckpointBarrier,
                               ctx: Context) -> List[Any]:
        """Snapshot this operator's state at a barrier; returns the
        ``SubtaskCheckpointMetadata`` list to report (one entry here; a
        ChainedOperator returns one per member so chained checkpoints
        stay restorable un-chained and vice versa)."""
        from ..obs import tracing

        tid = ctx.task_info.task_id
        with tracing.span("checkpoint.pre", "checkpoint", tid=tid,
                          args={"epoch": barrier.epoch}):
            await self.pre_checkpoint(barrier, ctx)
        ctx.state.get_global_keyed_state("[").insert(
            "timers", ctx.timers.snapshot())
        with tracing.span("checkpoint.sync", "checkpoint", tid=tid,
                          args={"epoch": barrier.epoch}):
            metadata = ctx.state.checkpoint(barrier.epoch,
                                            ctx.last_watermark)
        if ctx.metrics is not None:
            ctx.metrics.checkpoint_duration.observe(max(
                (metadata.finish_time - metadata.start_time) / 1e6, 0.0))
            ctx.metrics.checkpoint_bytes.observe(metadata.bytes)
        return [metadata]

    async def on_start(self, ctx: Context) -> None:
        pass

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        raise NotImplementedError

    async def handle_timer(self, time: int, key: Any, payload: Any,
                           ctx: Context) -> None:
        pass

    async def handle_watermark(self, watermark: int, ctx: Context) -> None:
        """Called when the combined input watermark advances (after timers
        fire).  Default: forward it downstream.  Overriders that hold back or
        transform the watermark are responsible for their own forwarding."""
        from ..types import Message, Watermark

        await ctx.broadcast(Message.wm(Watermark.event_time(watermark)))

    async def pre_checkpoint(self, barrier: CheckpointBarrier, ctx: Context) -> None:
        """Flush any state living outside registered tables into them; called
        right before the state store snapshot."""
        pass

    async def handle_commit(self, epoch: int, ctx: Context) -> None:
        """Second phase of two-phase commit (sinks only)."""
        pass

    async def handle_load_compacted(self, payload: Any, ctx: Context) -> None:
        """Compaction hot-swap notice (ControlMessage::LoadCompacted): the
        operator's checkpoint files were merged into a compacted generation.
        Live state is in memory/HBM, so the default is a no-op; operators
        that lazily page state from checkpoint files override this."""
        pass

    async def on_close(self, ctx: Context) -> None:
        """Called when all inputs have finished, before EndOfData propagates."""
        pass


class SourceOperator(Operator):
    """Base for sources: drives its own loop instead of reacting to inputs
    (``#[source_fn]``, arroyo-macro/src/lib.rs:292-316)."""

    # source-side coalescer (engine/coalesce.py SourceBatcher): None
    # unless the connector installed one via make_batcher
    _batcher: Optional[Any] = None

    async def run(self, ctx: Context) -> SourceFinishType:
        raise NotImplementedError

    def make_batcher(self, ctx: Context, decode: Any,
                     target: int = 0, batch_always: bool = False) -> Any:
        """Install a :class:`~arroyo_tpu.engine.coalesce.SourceBatcher`
        assembling target-size batches at the source boundary.  The
        TaskRunner drains it via ``flush_pending`` before checkpoint
        barriers and stops, so connectors may record resume positions
        at fetch time without breaking exactly-once.  ``batch_always``
        is for connectors that assembled target-size batches themselves
        before the boundary batcher existed: their batching survives
        ``ARROYO_COALESCE=0`` (only the linger is escape-hatched)."""
        from .coalesce import SourceBatcher

        self._batcher = SourceBatcher(
            ctx, decode, target, prof_op=ctx.task_info.operator_id,
            batch_always=batch_always)
        return self._batcher

    async def flush_pending(self, ctx: Context) -> None:
        """Emit any payloads buffered at the source boundary.  Called by
        the TaskRunner before a checkpoint snapshots source state and
        when the source loop ends — buffered rows are always downstream
        of the state that claims them."""
        if self._batcher is not None:
            await self._batcher.flush()

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        raise RuntimeError("sources have no inputs")

    # Helper: sources call this between emissions to service control messages
    # (checkpoint barriers are *injected at sources*, §3.3 of SURVEY.md).
    async def check_control(self, ctx: Context, runner) -> Optional[ControlMessage]:
        return await runner.poll_source_control()
