"""Scalar / element-wise physical operators — analog of the reference's
operators/mod.rs:496-878 (Map/OptionMap/Filter/FlatMap/Flatten/ToGlobal/
KeyMap/Count/Aggregate) plus the periodic watermark generator
(operators/mod.rs:97-233)."""

from __future__ import annotations

import asyncio
import time as _time
from typing import Any, Dict, List, Optional

import numpy as np

from ..graph.logical import (
    AggKind,
    AggSpec,
    ColumnExpr,
    ExprReturnType,
    LogicalOperator,
    PeriodicWatermarkSpec,
)
from ..ops.expr import CompiledExpr, eval_host_expr, eval_predicate, eval_record_expr
from ..state.tables import TableDescriptor, TableType
from ..types import Batch, Message, Watermark, MAX_TIMESTAMP
from .context import Context
from .operator import Operator


class ExpressionOperator(Operator):
    """Map / Filter / OptionMap over a batch via a jitted column expression
    (Operator::ExpressionOperator; operators/mod.rs:496-610)."""

    def __init__(self, name: str, expr: ColumnExpr):
        super().__init__(name)
        self.expr = expr
        self.compiled = CompiledExpr(expr.name, expr.fn)
        self.return_type = expr.return_type

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        if self.return_type == ExprReturnType.PREDICATE:
            mask = eval_predicate(self.compiled, batch)
            if mask.any():
                await ctx.collect(batch.select(mask))
        elif self.return_type == ExprReturnType.RECORD:
            await ctx.collect(eval_record_expr(self.compiled, batch))
        else:  # OPTIONAL_RECORD: expr returns dict with '__valid' bool column
            out = eval_record_expr(self.compiled, batch)
            if "__valid" in out.columns:
                mask = out.columns.pop("__valid").astype(bool)
                out = out.select(mask)
            await ctx.collect(out)


class UdfOperator(Operator):
    """Python UDF over the raw batch (the reference's WasmOperator,
    operators/mod.rs:347-494: sandboxing is unnecessary for in-process
    Python)."""

    def __init__(self, name: str, expr: ColumnExpr):
        super().__init__(name)
        self.fn = expr.fn

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        await ctx.collect(eval_host_expr(self.fn, batch))


class UnionOperator(Operator):
    """UNION ALL merge: batches from every input side pass through
    unchanged; the runner's WatermarkHolder takes the min watermark across
    inputs.  The reference has no union support (pipeline.rs:393)."""

    def __init__(self, name: str):
        super().__init__(name)

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        await ctx.collect(batch)


class FlattenOperator(Operator):
    """Expand list-valued column '__flatten' rows into multiple rows
    (FlattenOperator, operators/mod.rs)."""

    def __init__(self, name: str, list_col: str = "__flatten"):
        super().__init__(name)
        self.list_col = list_col

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        col = batch.columns.get(self.list_col)
        if col is None:
            await ctx.collect(batch)
            return
        lengths = np.fromiter((len(x) for x in col), dtype=np.int64, count=len(col))
        idx = np.repeat(np.arange(len(col)), lengths)
        flat = np.concatenate([np.asarray(x) for x in col if len(x)]) if lengths.sum() else np.zeros(0)  # arroyolint: disable=host-sync -- flatten materializes list-column lengths on host by design (list cols never enter jit)
        out = batch.select(idx)
        out.columns[self.list_col] = flat
        await ctx.collect(out)


class FlatMapOperator(Operator):
    """Record expr producing a list column then flattening it."""

    def __init__(self, name: str, expr: ColumnExpr, list_col: str = "__flatten"):
        super().__init__(name)
        self.inner = UdfOperator(name, expr)
        self.flatten = FlattenOperator(name + "_flatten", list_col)

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        out = eval_host_expr(self.inner.fn, batch)
        await self.flatten.process_batch(out, ctx, side)


class KeyByOperator(Operator):
    """Re-key the stream: computes the composite key hash for shuffle routing
    (the reference expresses keying as an ExpressionOperator over keys)."""

    def __init__(self, name: str, key_cols: tuple):
        super().__init__(name)
        self.key_cols = key_cols

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        await ctx.collect(batch.with_key(list(self.key_cols)))


class GlobalKeyOperator(Operator):
    """Route everything to one shard (ToGlobalOperator)."""

    def __init__(self, name: str):
        super().__init__(name)

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        kh = np.zeros(len(batch), dtype=np.uint64)
        await ctx.collect(Batch(batch.timestamp, dict(batch.columns), kh,
                                ("__global",)))


class WatermarkOperator(Operator):
    """PeriodicWatermarkGenerator (operators/mod.rs:97-233): watermark =
    max(event_time) - max_lateness, emitted after each batch; Idle emitted
    when no data arrives for idle_time (1s tick in the reference; here an
    asyncio ticker).  Emits a final MAX watermark on close so downstream
    windows flush (operators/mod.rs:179-186)."""

    def __init__(self, name: str, spec: PeriodicWatermarkSpec):
        super().__init__(name)
        self.spec = spec
        self.max_ts: Optional[int] = None
        self.last_emitted: Optional[int] = None
        self.last_data_wall: float = _time.monotonic()
        self._last_trace_wall: float = 0.0
        self._idle_task: Optional[asyncio.Task] = None
        # watermark expressions produce int64 micros -> host eval only
        self._expr_fn = spec.expression.fn if spec.expression else None

    async def on_start(self, ctx: Context) -> None:
        if self.spec.idle_time_micros:
            self._idle_task = asyncio.ensure_future(self._idle_loop(ctx))

    async def _idle_loop(self, ctx: Context) -> None:
        idle_s = self.spec.idle_time_micros / 1e6
        while True:
            await asyncio.sleep(1.0)
            if _time.monotonic() - self.last_data_wall > idle_s:
                await ctx.broadcast(Message.wm(Watermark.idle()))

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        self.last_data_wall = _time.monotonic()
        if self._expr_fn is not None:
            out = eval_host_expr(self._expr_fn, batch)
            ts_max = int(np.max(out.timestamp)) if len(out) else None
        else:
            ts_max = int(np.max(batch.timestamp)) if len(batch) else None
        if ts_max is not None:
            self.max_ts = ts_max if self.max_ts is None else max(self.max_ts, ts_max)
        await ctx.collect(batch)
        if self.max_ts is not None:
            wm = self.max_ts - self.spec.max_lateness_micros
            if self.last_emitted is None or wm > self.last_emitted:
                self.last_emitted = wm
                # flight-recorder tap: the assigner's emitted watermark is
                # the origin every downstream lag measurement follows.
                # Throttled to 10/s per operator: monotonic sources emit a
                # new watermark on nearly every batch, and unthrottled
                # instants would wrap the bounded span ring in seconds,
                # evicting the rare checkpoint/barrier spans it exists
                # to keep
                wall = _time.monotonic()
                if wall - self._last_trace_wall >= 0.1:
                    self._last_trace_wall = wall
                    from ..obs import tracing
                    from ..types import now_micros

                    tracing.instant(
                        "watermark.emit", "watermark",
                        tid=tracing.ctx_tid(ctx),
                        args={"watermark": int(wm),
                              "lag_s": round((now_micros() - wm) / 1e6, 4)})
                await ctx.broadcast(Message.wm(Watermark.event_time(wm)))

    async def handle_watermark(self, watermark: int, ctx: Context) -> None:
        # Upstream watermarks (incl. the source's final MAX) pass through.
        if watermark >= int(MAX_TIMESTAMP) - self.spec.max_lateness_micros:
            await ctx.broadcast(Message.wm(Watermark.event_time(int(MAX_TIMESTAMP))))

    async def on_close(self, ctx: Context) -> None:
        if self._idle_task:
            self._idle_task.cancel()


class CountOperator(Operator):
    """Per-key running count over an updating stream (CountOperator,
    operators/mod.rs): emits the new count per key per batch."""

    def __init__(self, name: str):
        super().__init__(name)
        self.counts: Dict[int, int] = {}

    def tables(self) -> List[TableDescriptor]:
        return [TableDescriptor("c", TableType.KEYED, "counts")]

    async def on_start(self, ctx: Context) -> None:
        t = ctx.state.get_keyed_state("c")
        self.counts = {k: v for k, v in t.items()}

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        if batch.key_hash is None:
            return
        t = ctx.state.get_keyed_state("c")
        keys, cnt = np.unique(batch.key_hash, return_counts=True)
        out_counts = np.zeros(len(keys), dtype=np.int64)
        ts = int(np.max(batch.timestamp))
        for i, (k, c) in enumerate(zip(keys.tolist(), cnt.tolist())):
            nc = self.counts.get(k, 0) + c
            self.counts[k] = nc
            out_counts[i] = nc
            t.insert(ts, k, nc)
        out = Batch(np.full(len(keys), ts, dtype=np.int64),
                    {"count": out_counts}, keys.astype(np.uint64),
                    batch.key_cols)
        await ctx.collect(out)


class AggregateOperator(Operator):
    """Per-key running Max/Min/Sum (AggregateBehavior,
    operators/mod.rs:700-878)."""

    def __init__(self, name: str, agg: AggSpec):
        super().__init__(name)
        self.agg = agg
        self.values: Dict[int, float] = {}

    def tables(self) -> List[TableDescriptor]:
        return [TableDescriptor("a", TableType.KEYED, "aggregates")]

    async def on_start(self, ctx: Context) -> None:
        t = ctx.state.get_keyed_state("a")
        self.values = {k: v for k, v in t.items()}

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        if batch.key_hash is None or self.agg.column not in batch.columns:
            return
        t = ctx.state.get_keyed_state("a")
        vals = batch.columns[self.agg.column].astype(np.float64)
        order = np.argsort(batch.key_hash, kind="stable")
        kh = batch.key_hash[order]
        v = vals[order]
        keys, starts = np.unique(kh, return_index=True)
        ts = int(np.max(batch.timestamp))
        if self.agg.kind == AggKind.SUM:
            per = np.add.reduceat(v, starts)
        elif self.agg.kind == AggKind.MAX:
            per = np.maximum.reduceat(v, starts)
        elif self.agg.kind == AggKind.MIN:
            per = np.minimum.reduceat(v, starts)
        else:
            raise ValueError(self.agg.kind)
        out_vals = np.zeros(len(keys))
        for i, (k, x) in enumerate(zip(keys.tolist(), per.tolist())):
            cur = self.values.get(k)
            if cur is None:
                nv = x
            elif self.agg.kind == AggKind.SUM:
                nv = cur + x
            elif self.agg.kind == AggKind.MAX:
                nv = max(cur, x)
            else:
                nv = min(cur, x)
            self.values[k] = nv
            out_vals[i] = nv
            t.insert(ts, k, nv)
        out = Batch(np.full(len(keys), ts, dtype=np.int64),
                    {self.agg.output: out_vals}, keys.astype(np.uint64),
                    batch.key_cols)
        await ctx.collect(out)
