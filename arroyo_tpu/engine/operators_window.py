"""Windowed / keyed-state physical operators — the device-state heart of the
engine.

Maps the reference's window operator suite onto batched device kernels:

* :class:`BinAggOperator` — Operator::SlidingWindowAggregator /
  TumblingWindowAggregator (aggregating_window.rs:14-258,
  tumbling_aggregating_window.rs): per-(key, bin) pre-aggregates in HBM via
  :class:`~arroyo_tpu.ops.keyed_bins.KeyedBinState`, panes emitted on
  watermark advance by one device kernel over all pending panes.
* :class:`WindowOperator` — Operator::Window / KeyedWindowFunc
  (windows.rs:160-197): buffer rows, trigger at window end, segment-reduce on
  device; supports tumbling/sliding/instant windows, aggregate or flatten.
* :class:`SessionWindowOperator` — SessionWindowFunc (windows.rs:200-427):
  host-managed per-key gap-merged window sets (data-dependent merging stays
  on host, as the reference keeps it in KeyedState), aggregation on device.
* :class:`TumblingTopNOperator` — TumblingTopN (tumbling_top_n_window.rs);
  the fused SlidingAggregatingTopN lives as the ``top_n`` mode of
  :class:`BinAggOperator` (sliding_top_n_aggregating_window.rs).
* :class:`WindowJoinOperator` — Operator::WindowJoin (joins.rs:14-181):
  dual-sided buffers, sorted-merge join per fired window.
* :class:`JoinWithExpirationOperator` — JoinWithExpiration
  (join_with_expiration.rs): TTL'd buffers, inner/left/right/full with
  updating output.
* :class:`NonWindowAggOperator` — NonWindowAggregator
  (updating_aggregate.rs): running per-key aggregates with expiration,
  emitting updating (create/update) rows.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import asyncio
import time as _time

import numpy as np

from ..graph.logical import (
    AggKind,
    AggSpec,
    InstantWindow,
    JoinType,
    LogicalOperator,
    OpKind,
    SessionWindow,
    SlidingWindow,
    TumblingWindow,
)
from ..ops.expr import CompiledExpr, eval_record_expr
from ..ops.join import join_pairs
from ..ops.keyed_bins import KeyedBinState
from ..ops.segment import segment_aggregate
from ..state.tables import DeviceTable, TableDescriptor, TableType
from ..types import Batch, Message, UpdateOp, UPDATE_OP_COLUMN, Watermark
from .build import register_builder
from .context import Context
from .operator import Operator

MAX_SESSION_SIZE_MICROS = 24 * 3600 * 1_000_000  # windows.rs:17


def _window_params(typ) -> Tuple[int, int]:
    """(width, slide) micros for uniform window types."""
    if isinstance(typ, TumblingWindow):
        return typ.width_micros, typ.width_micros
    if isinstance(typ, SlidingWindow):
        return typ.width_micros, typ.slide_micros
    if isinstance(typ, InstantWindow):
        return 1, 1
    raise TypeError(f"not a uniform window: {typ}")


def _lat_track(pending: Optional[Tuple[int, float]], batch: Batch
               ) -> Optional[Tuple[int, float]]:
    """Latency-observatory pane inheritance, input side: fold one
    incoming batch's ingest stamp into the operator's pending
    ``(max_stamp, arrival_monotonic)``.  A fired pane inherits the MAX
    contributing stamp (the newest sampled record still waiting — the
    conservative bound on how fresh the pane's output can claim to be)."""
    if batch.lat_stamp is None:
        return pending
    stamp = (batch.lat_stamp if pending is None
             else max(pending[0], batch.lat_stamp))
    return (stamp, _time.monotonic())


def _lat_consume(pending: Optional[Tuple[int, float]]) -> Optional[int]:
    """Latency-observatory pane inheritance, fire side: consume the
    pending max-stamp.  Returns the stamp to attach to the fired batch
    and charges the ``watermark_hold`` critical-path stage with how
    long the sample sat in pane state waiting for the watermark."""
    if pending is None:
        return None
    from ..obs import latency as _latency

    lat = _latency.active()
    stamp, arrival = pending
    if lat is not None:
        lat.note_stage("watermark_hold",
                       max(_time.monotonic() - arrival, 0.0))
    return stamp


def _first_occurrence_cols(batch: Batch, uniq_keys: np.ndarray
                           ) -> Dict[str, np.ndarray]:
    """Key-column values for each unique key (first occurrence wins)."""
    if not batch.key_cols:
        return {}
    order = np.argsort(batch.key_hash, kind="stable")
    kh = batch.key_hash[order]
    _, first = np.unique(kh, return_index=True)
    rows = order[first]  # one row per unique key, aligned with sorted uniq
    return {c: batch.columns[c][rows] for c in batch.key_cols
            if c in batch.columns}


class _SlotKeyValues:
    """Host-side slot -> key-column-values store for bin-state operators."""

    def __init__(self) -> None:
        self.cols: Dict[str, np.ndarray] = {}
        self.size = 0

    def ensure(self, batch: Batch, slots: np.ndarray, prev_next: int,
               new_next: int) -> None:
        if new_next <= self.size and self.cols:
            return
        cap = max(new_next, 64)
        for c in list(self.cols):
            old = self.cols[c]
            if len(old) < cap:
                grown = np.empty(cap * 2, dtype=old.dtype)
                grown[:len(old)] = old
                self.cols[c] = grown
        for c in batch.key_cols:
            if c in batch.columns and c not in self.cols:
                self.cols[c] = np.empty(
                    cap * 2, dtype=batch.columns[c].dtype)
        new_mask = slots >= prev_next
        if new_mask.any():
            idx = new_mask.nonzero()[0]
            for c in batch.key_cols:
                if c in batch.columns:
                    self.cols[c][slots[idx]] = batch.columns[c][idx]
        self.size = max(self.size, new_next)

    def gather(self, slot_idx: np.ndarray) -> Dict[str, np.ndarray]:
        return {c: v[slot_idx] for c, v in self.cols.items()}

    def snapshot(self) -> Dict[str, np.ndarray]:
        return {f"kv_{c}": v[:self.size] for c, v in self.cols.items()} | {
            "kv_size": np.array([self.size])}

    def restore(self, arrays: Dict[str, np.ndarray]) -> None:
        self.size = int(arrays["kv_size"][0])
        for k, v in arrays.items():
            if k.startswith("kv_") and k != "kv_size":
                self.cols[k[3:]] = v.copy()


class BinAggOperator(Operator):
    """Two-phase binned window aggregate over device state (sliding or
    tumbling; SURVEY kernel #2)."""

    def __init__(self, name: str, width_micros: int, slide_micros: int,
                 aggs: Tuple[AggSpec, ...], projection=None,
                 top_n: Optional[Tuple[Tuple[str, ...], str, int]] = None,
                 argmax_local: Optional[Tuple[str, str]] = None):
        super().__init__(name)
        from ..parallel.mesh_window import make_bin_state

        self.width = width_micros
        self.slide = slide_micros
        self.aggs = aggs
        # mesh-sharded state when >1 device is available (all_to_all re-key
        # over ICI instead of a host shuffle); single-device KeyedBinState
        # otherwise
        self.state = make_bin_state(aggs, slide_micros, width_micros)
        if argmax_local is not None and hasattr(self.state, "set_argmax_local"):
            # emission pre-filters to local per-pane argmax candidates
            # (sole consumer is a WindowArgmax stage — planner-proven)
            self.state.set_argmax_local(*argmax_local)
        self.keyvals = _SlotKeyValues()
        self.projection = (CompiledExpr(projection.name, projection.fn)
                           if projection else None)
        self.top_n = top_n  # (partition_cols, sort_column, max_elements)
        self._key_cols: Tuple[str, ...] = ()
        self._offload: Optional[bool] = None  # decided at first batch
        # latency-observatory pane inheritance: (max contributing ingest
        # stamp, monotonic arrival) pending until the next pane fire
        self._lat_pending: Optional[Tuple[int, float]] = None
        self._ledger_updates = 0  # throttles the pane_state_registry note

    def _offload_transfers(self) -> bool:
        """Run device update/emit in an executor thread on accelerators:
        host<->device transfers there can block for tens of ms (remote-
        tunnel TPUs especially), and off the event loop sibling operators'
        transfers overlap instead of serializing.  On the CPU backend
        transfers are free, so the thread hop is pure overhead."""
        if self._offload is None:
            import jax

            self._offload = jax.default_backend() != "cpu"
        return self._offload

    def tables(self) -> List[TableDescriptor]:
        return []  # registered as a device table in on_start

    async def on_start(self, ctx: Context) -> None:
        from ..ops.keyed_bins import filter_canonical_snapshot

        par = ctx.task_info.parallelism
        if par > 1 and hasattr(self.state, "set_route_shift"):
            # subtask key ranges consume the TOP hash bits; the mesh
            # must route on the bits below them or this subtask's whole
            # key slice funnels onto ~nk/parallelism devices.  Must run
            # before register_device: a restore re-shards by _shard_of.
            # The shift expression is the shared contract in
            # types.route_shift_for — shardcheck's static model uses the
            # SAME function and its wiring audit pins this call site.
            from ..types import route_shift_for

            self.state.set_route_shift(route_shift_for(par))

        def snap():
            out = self.state.snapshot() | self.keyvals.snapshot()
            if self._lat_pending is not None:
                # pending pane stamp survives checkpoint/restore so a
                # sampled record held in pane state at barrier time is
                # still measured after recovery (restart cost included)
                out["__lat_stamp"] = np.array([self._lat_pending[0]],
                                              np.int64)
            return out

        def restore(arrays, _kr=ctx.task_info.key_range):
            st = arrays.pop("__lat_stamp", None)
            if st is not None:
                self._lat_pending = (int(st[0]), _time.monotonic())
            # rescale re-partitioning: keep only the keys this subtask owns
            arrays = filter_canonical_snapshot(arrays, _kr)
            self.state.restore(arrays)
            self.keyvals.restore(arrays)

        ctx.state.register_device(
            TableDescriptor("a", TableType.DEVICE, "bin aggregates",
                            retention_micros=self.width),
            DeviceTable(snap, restore))

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        assert batch.key_hash is not None, f"{self.name} requires keyed input"
        self._lat_pending = _lat_track(self._lat_pending, batch)
        self._key_cols = batch.key_cols
        prev = self.state.next_slot
        slots = self.state._lookup_or_insert(batch.key_hash)
        self.keyvals.ensure(batch, slots, prev, self.state.next_slot)
        # safe to offload: this operator's messages are processed
        # serially, so state is never touched concurrently
        if self._offload_transfers():
            from ..obs import perf

            await perf.run_offloaded(
                asyncio.get_running_loop(), self.state.update,
                batch.key_hash, batch.timestamp, batch.columns)
        else:
            self.state.update(batch.key_hash, batch.timestamp, batch.columns)
        self._ledger_updates += 1
        if self._ledger_updates % 16 == 1 and hasattr(self.state,
                                                      "device_bytes"):
            # throttled device-memory ledger note (join_state_registry
            # idiom): one entry per operator instance, metadata-only
            from ..obs import perf

            reg = perf.get_note("pane_state_registry")
            if not isinstance(reg, dict):
                reg = {}
                perf.note("pane_state_registry", reg)
            reg[self.name] = self.state.device_bytes()

    async def handle_watermark(self, watermark: int, ctx: Context) -> None:
        from ..obs import tracing
        from ..types import MAX_TIMESTAMP

        final = watermark >= int(MAX_TIMESTAMP) - 1
        # flight-recorder tap: pane firing is where windowed pipelines
        # spend their watermark-driven time
        with tracing.span("window.fire", "window",
                          tid=tracing.ctx_tid(ctx),
                          args={"watermark": int(watermark)}):
            # pane emission device_get is the biggest device->host transfer
            # in the pipeline (same offload rationale as update)
            if self._offload_transfers():
                from ..obs import perf

                fired = await perf.run_offloaded(
                    asyncio.get_running_loop(),
                    lambda: self.state.fire_panes(watermark, final=final))
            else:
                fired = self.state.fire_panes(watermark, final=final)
            if fired is not None:
                await self._emit(fired, ctx)
        await ctx.broadcast(Message.wm(Watermark.event_time(watermark)))

    async def _emit(self, fired, ctx: Context) -> None:
        keys, out_cols, window_end, counts = fired
        # key_idx into slot arrays for key-column recovery
        slot_idx = self.state.slot_of_sorted[
            np.searchsorted(self.state.key_sorted, keys)]
        cols: Dict[str, np.ndarray] = {}
        cols.update(self.keyvals.gather(slot_idx))
        cols["window_start"] = window_end - self.width
        cols["window_end"] = window_end
        cols.update(out_cols)
        ts = window_end - 1  # emit at w.end - 1ns analog (windows.rs:95)
        key_cols = self._key_cols or tuple(self.keyvals.cols)
        out = Batch(ts, cols, keys.astype(np.uint64), key_cols,
                    lat_stamp=_lat_consume(self._lat_pending))
        self._lat_pending = None

        if self.top_n is not None:
            out = _apply_top_n(out, *self.top_n)
        if self.projection is not None:
            out = eval_record_expr(self.projection, out)
        await ctx.collect(out)


class FactorPaneOperator(BinAggOperator):
    """The shared half of a factor-window rewrite
    (graph/factor_windows.py): a width == slide == pane BinAggOperator
    maintaining the member queries' decomposed partial aggregates once
    per pane.  Watermark fires emit completed panes exactly like any
    tumbling aggregate; the one extra behavior is the checkpoint-barrier
    DRAIN — pending (watermark-incomplete) panes ship downstream as
    deltas and reset on device BEFORE the snapshot, so this operator's
    own table never holds un-shipped mass and a factored checkpoint
    restores into an unfactored plan epoch for epoch (derived rings
    merge deltas losslessly; see ``KeyedBinState.drain_deltas``)."""

    def __init__(self, name: str, pane_micros: int,
                 aggs: Tuple[AggSpec, ...]):
        super().__init__(name, pane_micros, pane_micros, aggs)

    async def pre_checkpoint(self, barrier, ctx: Context) -> None:
        if self._offload_transfers():
            from ..obs import perf

            fired = await perf.run_offloaded(
                asyncio.get_running_loop(), self.state.drain_deltas)
        else:
            fired = self.state.drain_deltas()
        if fired is not None:
            await self._emit(fired, ctx)


class DerivedWindowOperator(BinAggOperator):
    """The per-query half of a factor-window rewrite: a BinAggOperator
    with the MEMBER's original (width, slide, aggs, projection) whose
    ring runs in merge-input mode — updates consume fired factor panes
    (one row per (key, pane), ``__f_*`` partial columns) instead of raw
    events, so the per-event scatter cost lives once in the shared
    factor while this ring pays only O(panes).  Channel layout, state
    table name and canonical snapshot format are EXACTLY the unfactored
    member's, so checkpoints interchange between factored and
    unfactored plans (incl. rescale key-range filtering)."""

    def __init__(self, name: str, width_micros: int, slide_micros: int,
                 pane_micros: int, aggs: Tuple[AggSpec, ...],
                 projection=None):
        from ..graph.factor_windows import ROWS_COLUMN, derived_channel_cols

        assert slide_micros % pane_micros == 0, \
            "factor pane must divide the derived slide"
        super().__init__(name, width_micros, slide_micros, aggs, projection)
        self.pane = pane_micros
        self.state.set_merge_inputs(derived_channel_cols(aggs), ROWS_COLUMN)


def _topn_partition(batch: Batch, partition_cols: Tuple[str, ...]
                    ) -> np.ndarray:
    if partition_cols:
        from ..types import hash_columns

        # the window instance is always part of the partition: TopN ranks
        # within a window, never across windows
        cols = [batch.columns[c] for c in partition_cols]
        if "window_end" in batch.columns:
            cols.append(batch.columns["window_end"])
        return hash_columns(cols)
    return batch.columns.get("window_end", np.zeros(len(batch), np.int64))


def _apply_top_n(batch: Batch, partition_cols: Tuple[str, ...],
                 sort_column: str, max_elements: Optional[int],
                 rank_column: Optional[str] = None) -> Batch:
    """Keep the top ``max_elements`` rows by ``sort_column`` (desc) per
    partition — one fused device sort over (partition, window) segments
    (ops/topk.py; SURVEY #14/#15 device top-k).  Tiny batches stay on a
    host lexsort: kernel dispatch costs more than the sort itself.

    ``max_elements=None`` ranks without pruning; ``rank_column`` emits
    the 1-based per-partition rank (ROW_NUMBER() materialized) — ranks
    are computed on the (small) surviving row set on host."""
    if len(batch) == 0:
        return batch
    sort_val = batch.columns[sort_column]
    part = _topn_partition(batch, partition_cols)
    if max_elements is not None:
        if len(batch) >= 512:
            from ..ops.topk import segment_top_k

            keep = segment_top_k(part, sort_val, max_elements)
        else:
            order = np.lexsort((-np.asarray(sort_val, dtype=np.float64),  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
                                part))
            part_sorted = np.asarray(part)[order]  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
            is_start = np.ones(len(order), dtype=bool)
            is_start[1:] = part_sorted[1:] != part_sorted[:-1]
            seg_id = np.cumsum(is_start) - 1
            seg_start = is_start.nonzero()[0]
            rank = np.arange(len(order)) - seg_start[seg_id]
            keep = order[rank < max_elements]
            keep.sort()
        batch = batch.select(keep)
        if rank_column is None:
            return batch
        part = np.asarray(part)[keep]  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
        sort_val = batch.columns[sort_column]
    if rank_column is None:
        return batch
    order = np.lexsort((-np.asarray(sort_val, dtype=np.float64), part))  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
    part_sorted = np.asarray(part)[order]  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
    is_start = np.ones(len(order), dtype=bool)
    is_start[1:] = part_sorted[1:] != part_sorted[:-1]
    seg_start = is_start.nonzero()[0]
    seg_id = np.cumsum(is_start) - 1
    ranks = np.empty(len(order), dtype=np.int64)
    ranks[order] = np.arange(len(order)) - seg_start[seg_id] + 1
    cols = dict(batch.columns)
    cols[rank_column] = ranks
    return Batch(batch.timestamp, cols, batch.key_hash, batch.key_cols,
                 lat_stamp=batch.lat_stamp)


class WindowOperator(Operator):
    """Generic keyed window function: buffer + trigger-at-window-end +
    device segment aggregation (KeyedWindowFunc, windows.rs:160-197)."""

    def __init__(self, name: str, typ, aggs: Tuple[AggSpec, ...],
                 flatten: bool, projection=None):
        super().__init__(name)
        self.typ = typ
        self.width, self.slide = _window_params(typ)
        self.aggs = aggs
        self.flatten = flatten or not aggs
        self.projection = (CompiledExpr(projection.name, projection.fn)
                           if projection else None)

    def tables(self) -> List[TableDescriptor]:
        return [TableDescriptor("w", TableType.BATCH_BUFFER, "window buffer",
                                retention_micros=self.width)]

    async def on_start(self, ctx: Context) -> None:
        self.buffer = ctx.state.get_batch_buffer("w")
        self._lat_pending: Optional[Tuple[int, float]] = None

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        assert batch.key_hash is not None
        self._lat_pending = _lat_track(self._lat_pending, batch)
        self.buffer.append(batch)
        # one timer per distinct window end (not per key): rows at ts belong
        # to windows ending at slide-aligned points in (ts, ts+width]
        first_end = (batch.timestamp // self.slide + 1) * self.slide
        if isinstance(self.typ, SlidingWindow):
            ends = np.unique(np.concatenate([
                first_end + i * self.slide
                for i in range(self.width // self.slide)]))
        else:
            ends = np.unique(first_end - self.slide + self.width)
        for e in ends.tolist():
            ctx.timers.schedule(int(e), ("w", int(e)))

    async def handle_timer(self, time: int, key: Any, payload: Any,
                           ctx: Context) -> None:
        end = key[1]
        start = end - self.width
        rows = self.buffer.query_range(start, end)
        if rows is not None and len(rows):
            if self.flatten:
                out_cols = dict(rows.columns)
                out_cols["window_start"] = np.full(len(rows), start, np.int64)
                out_cols["window_end"] = np.full(len(rows), end, np.int64)
                out = Batch(np.full(len(rows), end - 1, np.int64), out_cols,
                            rows.key_hash, rows.key_cols)
            else:
                uniq, agg_cols, _, _cnt, _vc = segment_aggregate(
                    rows.key_hash, rows.timestamp, rows.columns, self.aggs)
                cols = _first_occurrence_cols(rows, uniq)
                cols["window_start"] = np.full(len(uniq), start, np.int64)
                cols["window_end"] = np.full(len(uniq), end, np.int64)
                cols.update(agg_cols)
                out = Batch(np.full(len(uniq), end - 1, np.int64), cols,
                            uniq.astype(np.uint64), rows.key_cols)
            out.lat_stamp = _lat_consume(self._lat_pending)
            self._lat_pending = None
            if self.projection is not None:
                out = eval_record_expr(self.projection, out)
            await ctx.collect(out)
        # evict rows no future window needs
        self.buffer.evict_before(end - self.width + self.slide)


class SessionWindowOperator(Operator):
    """Session windows with gap merging: per-key window sets on host
    (SessionWindowFunc / WindowGroup, windows.rs:200-427)."""

    def __init__(self, name: str, gap_micros: int, aggs: Tuple[AggSpec, ...],
                 flatten: bool, projection=None):
        super().__init__(name)
        self.gap = gap_micros
        self.aggs = aggs
        self.flatten = flatten or not aggs
        self.projection = (CompiledExpr(projection.name, projection.fn)
                           if projection else None)
        self._pending_fires: List[Tuple[int, int, int]] = []
        self._min_end: Optional[int] = None  # no-fire fast-path bound

    def tables(self) -> List[TableDescriptor]:
        return [
            TableDescriptor("s", TableType.BATCH_BUFFER, "session data"),
            TableDescriptor("v", TableType.KEYED, "session windows per key"),
        ]

    async def on_start(self, ctx: Context) -> None:
        from ..state.session_state import SessionRunState

        self.buffer = ctx.state.get_batch_buffer("s")
        # partition-adaptive sorted interval runs unless
        # ARROYO_SESSION_STATE=legacy; both layouts speak the KeyedState
        # interface, so the per-key clamp path below runs unchanged
        self.windows = ctx.state.get_session_state("v")
        self._device_state = isinstance(self.windows, SessionRunState)
        self._lat_pending: Optional[Tuple[int, float]] = None

    def _merge_key(self, kh: int, times: np.ndarray, ctx: Context) -> None:
        """handle_event extend/merge/create (windows.rs:232-302)."""
        sessions: List[Tuple[int, int]] = list(self.windows.get(kh) or [])
        for t in np.sort(times).tolist():
            placed = False
            for i, (s, e) in enumerate(sessions):
                if s - self.gap <= t < e:
                    ns, ne = min(s, t), max(e, t + self.gap)
                    if ne - ns > MAX_SESSION_SIZE_MICROS:
                        ne = ns + MAX_SESSION_SIZE_MICROS
                    sessions[i] = (ns, ne)
                    placed = True
                    break
            if not placed:
                sessions.append((t, t + self.gap))
            # merge overlapping sessions
            sessions.sort()
            merged: List[Tuple[int, int]] = []
            for s, e in sessions:
                if merged and s <= merged[-1][1]:
                    ps, pe = merged[-1]
                    merged[-1] = (ps, max(pe, e))
                else:
                    merged.append((s, e))
            sessions = merged
        self.windows.insert(int(times.max()), kh, sessions)
        if sessions:
            me = min(e for _, e in sessions)
            if self._min_end is not None and me < self._min_end:
                self._min_end = me

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        assert batch.key_hash is not None
        self._lat_pending = _lat_track(self._lat_pending, batch)
        self.buffer.append(batch)
        # collapse events -> candidate session intervals for the WHOLE
        # batch in three vector ops (events within gap of their
        # predecessor merge, so a burst becomes ONE interval): the
        # per-key python work then scales with interval count, not event
        # count — the config5 hot loop (windows.rs:232-302 semantics)
        order = np.lexsort((batch.timestamp, batch.key_hash))
        kh = batch.key_hash[order]
        ts = batch.timestamp[order]
        n = len(kh)
        newkey = np.empty(n, dtype=bool)
        newkey[0] = True
        newkey[1:] = kh[1:] != kh[:-1]
        brk = newkey.copy()
        brk[1:] |= (ts[1:] - ts[:-1]) > self.gap
        ist = ts[brk]                      # interval starts
        ien = ts[np.append(brk[1:], True)] + self.gap  # last of group + gap
        ikh = kh[brk]
        kb = newkey[brk].nonzero()[0]      # key boundaries among intervals
        kb = np.append(kb, len(ikh))
        span_ok = (ien - ist) <= MAX_SESSION_SIZE_MICROS
        key_starts = np.append(newkey.nonzero()[0], n)
        if self._device_state:
            await self._merge_batch_device(kh, ts, ikh, ist, ien, kb,
                                           span_ok, key_starts, ctx)
            return
        from ..state.session_state import _count_merge

        _count_merge(0, n)  # legacy layout: every event merges on host
        for i in range(len(kb) - 1):
            k = int(ikh[kb[i]])
            lo, hi = kb[i], kb[i + 1]
            if not span_ok[lo:hi].all() or not self._merge_key_intervals(
                    k, ist[lo:hi].tolist(), ien[lo:hi].tolist(),
                    int(ts[key_starts[i + 1] - 1]), ctx):
                # a burst longer than MAX_SESSION_SIZE, or a merge that
                # would clamp-truncate past an incoming interval's end
                # (events beyond the clamp must START a new session, and
                # only the per-event path knows their positions): rare —
                # the incremental-clamp-splitting path is authoritative
                self._merge_key(k, ts[key_starts[i]:key_starts[i + 1]],
                                ctx)

    async def _merge_batch_device(self, kh, ts, ikh, ist, ien, kb,
                                  span_ok, key_starts, ctx: Context) -> None:
        """Device-state merge: ONE vectorized interval-union dispatch
        covers every in-bounds key; keys the clamp touches (overlong
        bursts, or merged spans crossing MAX_SESSION_SIZE) re-run the
        authoritative per-key path against the same state object — the
        device/host row split is counted, and sanitized parity vs
        ARROYO_SESSION_STATE=legacy is asserted by the smoke gate."""
        from ..obs import perf, profiler
        from ..state.session_state import _count_merge

        nkeys = len(kb) - 1
        # per-interval key ordinal + per-key last event time (the KEYED
        # snapshot time column, matching the legacy insert(max_t, ...))
        key_maxt = ts[key_starts[1:] - 1]
        counts = np.diff(kb)
        itm = np.repeat(key_maxt, counts)
        # keys with an overlong burst go straight to the per-event path:
        # only it knows the event positions past the clamp
        key_ord = np.repeat(np.arange(nkeys), counts)
        bad = np.unique(key_ord[~span_ok])
        good_iv = ~np.isin(key_ord, bad)
        prof = profiler.active()
        frame = (prof.begin(perf.active_operator_id() or self.name,
                            "session_merge") if prof is not None else None)
        try:
            flagged = self.windows.merge_intervals(
                ikh[good_iv], ist[good_iv], ien[good_iv], itm[good_iv])
        finally:
            if prof is not None:
                prof.end(frame)
        if len(bad) or len(flagged):
            keys_arr = ikh[kb[:-1]]  # sorted ascending (lexsort by key)
            fb = set(bad.tolist())
            if len(flagged):
                fb.update(np.searchsorted(keys_arr, flagged).tolist())
            host_events = 0
            for i in sorted(fb):
                lo, hi = key_starts[i], key_starts[i + 1]
                host_events += int(hi - lo)
                self._merge_key(int(keys_arr[i]), ts[lo:hi], ctx)
            _count_merge(0, host_events)
        # exact no-fire bound straight off the runs (cheap: P partition
        # minima), replacing the legacy conservative tracking
        self._min_end = self.windows.min_end()

    def _merge_key_intervals(self, kh: int, ists: List[int],
                             iens: List[int], max_t: int,
                             ctx: Context) -> bool:
        """Union sorted candidate intervals into the key's sorted session
        list — linear two-pointer sweep with the same touching-merges and
        incremental max-size clamp as the per-event path.  Returns False
        WITHOUT touching state when a clamp would truncate below a
        contributing interval's end (events past the clamp would be
        silently swallowed; the caller re-runs the per-event path)."""
        old: List[Tuple[int, int]] = list(self.windows.get(kh) or [])
        merged: List[Tuple[int, int]] = []
        i = j = 0
        no, ni = len(old), len(ists)
        while i < no or j < ni:
            if i < no and (j >= ni or old[i][0] <= ists[j]):
                s, e = old[i]
                i += 1
            else:
                s, e = ists[j], iens[j]
                j += 1
            if merged and s <= merged[-1][1]:
                ps, pe = merged[-1]
                ne = max(pe, e)
                if ne - ps > MAX_SESSION_SIZE_MICROS:
                    if ps + MAX_SESSION_SIZE_MICROS < e:
                        return False  # clamp would swallow interval tail
                    ne = ps + MAX_SESSION_SIZE_MICROS
                merged[-1] = (ps, ne)
            else:
                if e - s > MAX_SESSION_SIZE_MICROS:
                    return False  # guarded by span_ok; belt-and-braces
                merged.append((s, e))
        self.windows.insert(max_t, kh, merged if merged != old else old)
        if merged:
            # keep the no-fire fast-path bound conservative: a fresh
            # short session may end before the cached minimum
            me = min(e for _, e in merged)
            if self._min_end is not None and me < self._min_end:
                self._min_end = me
        return True

    def _collect_expired(self, watermark: int, ctx: Context) -> None:
        """Move every session with end <= watermark into the pending-fire
        list.  Event-time timers only ever fire on watermark advance, so
        scanning the (bounded, active) per-key session map at each
        watermark is equivalent to a per-session timer heap — without
        the heap churn of cancel/reschedule on every batch that extends
        a session (measured ~13% of the config5 run).  A min-end bound
        skips the scan entirely while nothing can fire (many dormant
        keys, slowly advancing watermark)."""
        if self._min_end is not None and watermark < self._min_end:
            return
        if self._device_state:
            # mask-compress every closed session out of the runs in one
            # vector pass per partition — no key iteration
            fk, fs, fe, removed = self.windows.expire(watermark)
            self._pending_fires.extend(
                zip((int(k) for k in fk.tolist()), fs.tolist(),
                    fe.tolist()))
            for kh in removed:
                ctx.state.note_delete("v", kh)
            self._min_end = self.windows.min_end()
            return
        expired_keys = []
        min_end = None
        for kh, sessions in self.windows.items():
            fire = [(s, e) for (s, e) in sessions if e <= watermark]
            if not fire:
                for (_s, e) in sessions:
                    if min_end is None or e < min_end:
                        min_end = e
                continue
            remain = [(s, e) for (s, e) in sessions if e > watermark]
            if remain:
                self.windows.insert(watermark, kh, remain)
                for (_s, e) in remain:
                    if min_end is None or e < min_end:
                        min_end = e
            else:
                expired_keys.append(kh)
            self._pending_fires.extend((int(kh), s, e) for (s, e) in fire)
        for kh in expired_keys:
            self.windows.remove(kh)
            ctx.state.note_delete("v", kh)
        self._min_end = min_end

    async def _flush_fires(self, ctx: Context) -> None:
        fires = self._pending_fires
        if not fires:
            return
        self._pending_fires = []
        rows = self.buffer.query_range(min(s for _, s, _ in fires),
                                       max(e for _, _, e in fires))
        if rows is None or not len(rows):
            return
        # assign every buffered row to its fired session in ONE combined
        # sweep: sessions (as start events) and rows merge-sort by
        # (key, time, starts-first); a running count of starts gives each
        # row the global index of the latest session start at-or-before
        # it — valid iff that session shares the row's key and the row
        # precedes its end.  No per-key python, no buffer argsort.
        m = len(fires)
        fk = np.array([k for k, _, _ in fires], dtype=np.uint64)  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
        fs = np.array([s for _, s, _ in fires], dtype=np.int64)  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
        fe = np.array([e for _, _, e in fires], dtype=np.int64)  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
        fo = np.lexsort((fs, fk))
        fk, fs, fe = fk[fo], fs[fo], fe[fo]
        n = len(rows)
        all_kh = np.concatenate([fk, rows.key_hash])
        all_t = np.concatenate([fs, rows.timestamp])
        prio = np.concatenate([np.zeros(m, np.int8), np.ones(n, np.int8)])
        o = np.lexsort((prio, all_t, all_kh))
        started = np.cumsum(o < m)
        pos = np.empty(m + n, dtype=np.int64)
        pos[o] = np.arange(m + n)
        si = started[pos[m:]] - 1  # per row: global session ordinal
        sic = np.clip(si, 0, m - 1)
        ok = ((si >= 0) & (fk[sic] == rows.key_hash)
              & (rows.timestamp < fe[sic]))
        if not ok.any():
            return
        sel = ok.nonzero()[0]
        segs = sic[sel].astype(np.uint64)
        sub = rows.select(sel)
        seg_kh_a, seg_s_a, seg_e_a = fk, fs, fe

        if self.flatten:
            si = segs.astype(np.int64)
            cols = dict(sub.columns)
            cols["window_start"] = seg_s_a[si]
            cols["window_end"] = seg_e_a[si]
            out = Batch(seg_e_a[si] - 1, cols, sub.key_hash, sub.key_cols)
        else:
            uniq, agg_cols, _, _cnt, _vc = segment_aggregate(
                segs, sub.timestamp, sub.columns, self.aggs)
            ui = uniq.astype(np.int64)
            # key columns: first row of each emitted segment
            cols: Dict[str, np.ndarray] = {}
            if sub.key_cols:
                so = np.argsort(segs, kind="stable")
                seg_sorted = segs[so]
                _, first = np.unique(seg_sorted, return_index=True)
                first_rows = so[first]  # aligned with sorted uniq
                cols = {c: sub.columns[c][first_rows] for c in sub.key_cols
                        if c in sub.columns}
            cols["window_start"] = seg_s_a[ui]
            cols["window_end"] = seg_e_a[ui]
            cols.update(agg_cols)
            out = Batch(seg_e_a[ui] - 1, cols, seg_kh_a[ui], sub.key_cols)
        out.lat_stamp = _lat_consume(self._lat_pending)
        self._lat_pending = None
        if self.projection is not None:
            out = eval_record_expr(self.projection, out)
        await ctx.collect(out)

    async def handle_watermark(self, watermark: int, ctx: Context) -> None:
        from ..obs import tracing

        with tracing.span("window.session_fire", "window",
                          tid=tracing.ctx_tid(ctx),
                          args={"watermark": int(watermark)}):
            self._collect_expired(watermark, ctx)
            await self._flush_fires(ctx)
        # evict data older than every live session start
        if self._device_state:
            ls = self.windows.min_live_start()
        else:
            live_starts = [s for _, sessions in self.windows.items()
                           for (s, _) in sessions]
            ls = min(live_starts) if live_starts else None
        self.buffer.evict_before(
            ls if ls is not None else watermark - MAX_SESSION_SIZE_MICROS)
        await ctx.broadcast(Message.wm(Watermark.event_time(watermark)))


class TumblingTopNOperator(Operator):
    """Windowed TopN (TumblingTopNWindowFunc, tumbling_top_n_window.rs)."""

    def __init__(self, name: str, width_micros: int,
                 max_elements: Optional[int],
                 sort_column: str, partition_cols: Tuple[str, ...],
                 projection=None, rank_column: Optional[str] = None):
        super().__init__(name)
        self.width = width_micros
        self.max_elements = max_elements
        self.sort_column = sort_column
        self.partition_cols = partition_cols
        self.rank_column = rank_column
        self.projection = (CompiledExpr(projection.name, projection.fn)
                           if projection else None)

    def tables(self) -> List[TableDescriptor]:
        return [TableDescriptor("t", TableType.BATCH_BUFFER, "topn buffer",
                                retention_micros=self.width)]

    async def on_start(self, ctx: Context) -> None:
        self.buffer = ctx.state.get_batch_buffer("t")

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        self.buffer.append(batch)
        ends = np.unique((batch.timestamp // self.width + 1) * self.width)
        for e in ends.tolist():
            ctx.timers.schedule(int(e), ("tn", int(e)))

    async def handle_timer(self, time: int, key: Any, payload: Any,
                           ctx: Context) -> None:
        end = key[1]
        start = end - self.width
        rows = self.buffer.query_range(start, end)
        if rows is not None and len(rows):
            out_cols = dict(rows.columns)
            # rows that already carry window columns (a global TopN merge
            # over upstream windowed aggregates) keep them: this stage's
            # 1us buckets are an implementation detail, not the window
            if "window_start" not in out_cols:
                out_cols["window_start"] = np.full(len(rows), start,
                                                   np.int64)
            if "window_end" not in out_cols:
                out_cols["window_end"] = np.full(len(rows), end, np.int64)
            out = Batch(np.full(len(rows), end - 1, np.int64), out_cols,
                        rows.key_hash, rows.key_cols)
            out = _apply_top_n(out, self.partition_cols, self.sort_column,
                               self.max_elements, self.rank_column)
            if self.projection is not None:
                out = eval_record_expr(self.projection, out)
            await ctx.collect(out)
        self.buffer.evict_before(end)


def _null_column(n: int, like: Optional[np.ndarray] = None,
                 kind: str = "") -> np.ndarray:
    """A NULL-filled column: None for object/string columns, NaN (f64)
    for everything else — the engine's null conventions."""
    stringy = (kind == "s" if like is None
               else (like.dtype == object or like.dtype.kind in "US"))
    if stringy:
        return np.full(n, None, dtype=object)
    return np.full(n, np.nan, dtype=np.float64)


def _join_name_maps(l_names, r_names, l_prefix: str = "",
                    r_prefix: str = ""):
    """Column-name mapping for a join output (left names win; colliding
    right names get the ``r_`` prefix) — one definition so matched-pair,
    padded, and retraction batches of the same join all agree."""
    lmap: Dict[str, str] = {}
    for c in l_names:
        lmap[c] = (l_prefix + c) if (c in r_names or l_prefix) else c
    rmap: Dict[str, str] = {}
    taken = set(lmap.values())
    for c in r_names:
        name = (r_prefix + c) if (c in l_names or r_prefix) else c
        if name in taken:
            name = "r_" + name
        rmap[c] = name
        taken.add(name)
    return lmap, rmap


def _internal_join_col(name: str) -> bool:
    """Planner-internal join key columns: ``__jk<i>`` + ``__jknonce``."""
    return name.startswith("__jk")


def _drop_null_keyed(batch: Batch) -> Optional[Batch]:
    """Strip rows whose ``__jknonce`` is nonzero — SQL-NULL join keys
    hashed to a unique nonce, so they can never match ANY row on any
    side.  The one home of the nonce-drop rule: buffering such rows on
    a side that cannot emit them padded is pure state growth until TTL
    (the round-4 deferral, retired).  Returns None when nothing
    survives."""
    nonce = batch.columns.get("__jknonce")
    if nonce is None:
        return batch
    keep = np.asarray(nonce) == 0  # arroyolint: disable=host-sync -- nonce is a host-resident key column (null-key routing never enters jit)
    if keep.all():
        return batch
    if not keep.any():
        return None
    return batch.select(keep)


def _stable_join_part(left_cols: Dict[str, np.ndarray],
                      right_cols: Dict[str, np.ndarray], n: int,
                      key_names: Sequence[str],
                      l_prefix: str = "", r_prefix: str = ""
                      ) -> Dict[str, np.ndarray]:
    """One joined-output column layout per join, regardless of which
    side a row came from or whether a side is a null pad (arroyosan's
    schema-stability invariant surfaced that matched pairs carried the
    buffered batch's internal ``__jk*`` columns through the ``r_``
    mapping while spec-template pads did not — the edge layout then
    flipped with arrival order, forcing a coalescer flush and a full
    data-plane frame on every flip).

    The rule: the right role never carries internal join-key columns
    (duplicates for matched rows, meaningless nulls for pads); the left
    role always carries them — filled when the left role is itself a
    pad — in the planner's layout (keys first, ``__jknonce`` last).
    Pad fills for the key columns use same-dtype zeros (witnessed from
    the right role's dropped internals) so the key dtype never flips
    between emission paths: an f64 NaN fill would flip the Arrow edge
    schema per path and concat-promote u64 keys past 2^53."""
    witness = {c: v for c, v in right_cols.items()
               if _internal_join_col(c)}
    right_cols = {c: v for c, v in right_cols.items()
                  if not _internal_join_col(c)}

    def _key_fill(c: str) -> np.ndarray:
        w = witness.get(c)
        if w is not None:
            return np.zeros(n, dtype=w.dtype)
        return _null_column(n)

    ordered: Dict[str, Optional[np.ndarray]] = {}
    for c in key_names:
        if c != "__jknonce":
            ordered[c] = left_cols.get(c)
    for c, v in left_cols.items():
        if c not in ordered and c != "__jknonce":
            ordered[c] = v
    if "__jknonce" in key_names:
        ordered["__jknonce"] = left_cols.get("__jknonce")
    cols = {c: (v if v is not None else _key_fill(c))
            for c, v in ordered.items()}
    lmap, rmap = _join_name_maps(list(cols), list(right_cols),
                                 l_prefix, r_prefix)
    out = {lmap[c]: v for c, v in cols.items()}
    for c, v in right_cols.items():
        out[rmap[c]] = v
    return out


class _SideTemplate:
    """Column template for null-padding one side of an outer join: prefers
    the dtypes of batches actually seen on that side, falls back to the
    planner-provided (name, kind) schema before any batch arrives."""

    def __init__(self, spec_cols: Tuple[Tuple[str, str], ...]):
        self.spec_cols = tuple(spec_cols)
        self.seen: Optional[Dict[str, np.dtype]] = None

    def observe(self, batch: Batch) -> None:
        self.seen = {c: v.dtype for c, v in batch.columns.items()}

    def names(self) -> List[str]:
        if self.seen is not None:
            return list(self.seen)
        return [c for c, _k in self.spec_cols]

    def null_cols(self, n: int) -> Dict[str, np.ndarray]:
        if self.seen is not None:
            return {c: _null_column(n, like=np.empty(0, dtype=dt))
                    for c, dt in self.seen.items()}
        return {c: _null_column(n, kind=k) for c, k in self.spec_cols}


class WindowJoinOperator(Operator):
    """Windowed stream-stream hash join (SURVEY kernel #3): both sides
    buffered, joined per fired window by sorted-merge on key hash
    (WindowedHashJoin, joins.rs:14-181).  Outer kinds null-pad the
    unmatched side per fired window — append-only, no retractions, since
    each window fires exactly once (the reference's list-merge codegen,
    arroyo-sql/src/expressions.rs:134-230)."""

    def __init__(self, name: str, typ, join_type: JoinType = JoinType.INNER,
                 left_cols: Tuple[Tuple[str, str], ...] = (),
                 right_cols: Tuple[Tuple[str, str], ...] = ()):
        super().__init__(name)
        self.typ = typ
        self.join_type = join_type
        self.width, self.slide = _window_params(typ)
        self._tmpl = (_SideTemplate(left_cols), _SideTemplate(right_cols))

    def tables(self) -> List[TableDescriptor]:
        return [
            TableDescriptor("l", TableType.BATCH_BUFFER, "left buffer",
                            retention_micros=self.width),
            TableDescriptor("r", TableType.BATCH_BUFFER, "right buffer",
                            retention_micros=self.width),
        ]

    async def on_start(self, ctx: Context) -> None:
        from ..state.join_state import PartitionedJoinBuffer

        self.left = ctx.state.get_join_buffer("l")
        self.right = ctx.state.get_join_buffer("r")
        self._partitioned = isinstance(self.left, PartitionedJoinBuffer) \
            and isinstance(self.right, PartitionedJoinBuffer)
        self._lat_pending: Optional[Tuple[int, float]] = None

    def _drop_never_emitting(self, batch: Batch,
                             side: int) -> Optional[Batch]:
        """Null-keyed rows stay ONLY when this side's unmatched rows
        null-pad at fire; otherwise they can never emit
        (:func:`_drop_null_keyed`)."""
        padded = self.join_type in (
            (JoinType.LEFT, JoinType.FULL) if side == 0
            else (JoinType.RIGHT, JoinType.FULL))
        if padded:
            return batch
        return _drop_null_keyed(batch)

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        assert batch.key_hash is not None, "window join requires keyed inputs"
        self._lat_pending = _lat_track(self._lat_pending, batch)
        self._tmpl[side].observe(batch)
        buffered = self._drop_never_emitting(batch, side)
        if buffered is not None and len(buffered):
            (self.left if side == 0 else self.right).append(buffered)
        first_end = (batch.timestamp // self.slide + 1) * self.slide
        if isinstance(self.typ, SlidingWindow):
            ends = np.unique(np.concatenate([
                first_end + i * self.slide
                for i in range(self.width // self.slide)]))
        else:
            ends = np.unique(first_end - self.slide + self.width)
        for e in ends.tolist():
            ctx.timers.schedule(int(e), ("wj", int(e)))

    async def handle_timer(self, time: int, key: Any, payload: Any,
                           ctx: Context) -> None:
        end = key[1]
        start = end - self.width
        how = self.join_type
        if self._partitioned:
            # sorted-run fire: mask-compress each partition's resident
            # run to the window range (stays key-sorted — no sort) and
            # merge-probe; only matched/unmatched rows materialize
            lg, rg, lu, ru = self.left.range_join(self.right, start, end)
            have_l = bool(len(lg) or len(lu))
            have_r = bool(len(rg) or len(ru))
            fire = ((have_l and have_r)
                    or (have_l and how in (JoinType.LEFT, JoinType.FULL))
                    or (have_r and how in (JoinType.RIGHT, JoinType.FULL)))
            if fire:
                l_rows = self.left.gather(lg)
                r_rows = self.right.gather(rg)
                if not len(l_rows.columns):
                    l_rows = _empty_like_side(self._tmpl[0], r_rows)
                if not len(r_rows.columns):
                    r_rows = _empty_like_side(self._tmpl[1], l_rows)
                key_cols = (self.left.key_cols or self.right.key_cols
                            or l_rows.key_cols)
                # unmatched rows only materialize on the side that pads
                # them — an INNER fire's cost scales with matches, not
                # window size
                l_un = (self.left.gather(lu)
                        if how in (JoinType.LEFT, JoinType.FULL) else None)
                r_un = (self.right.gather(ru)
                        if how in (JoinType.RIGHT, JoinType.FULL)
                        else None)
                out = _assemble_join_output(
                    l_rows, r_rows, l_un, r_un, end, how, key_cols,
                    tmpl=(self._tmpl[0], self._tmpl[1]))
                if len(out):
                    out.lat_stamp = _lat_consume(self._lat_pending)
                    self._lat_pending = None
                    await ctx.collect(out)
        else:
            l = self.left.query_range(start, end)
            r = self.right.query_range(start, end)
            have_l = l is not None and len(l)
            have_r = r is not None and len(r)
            fire = ((have_l and have_r)
                    or (have_l and how in (JoinType.LEFT, JoinType.FULL))
                    or (have_r and how in (JoinType.RIGHT, JoinType.FULL)))
            if fire:
                if not have_l:
                    l = _empty_like_side(self._tmpl[0], r)
                if not have_r:
                    r = _empty_like_side(self._tmpl[1], l)
                out = join_batches(l, r, end, how=how,
                                   tmpl=(self._tmpl[0], self._tmpl[1]))
                if len(out):
                    out.lat_stamp = _lat_consume(self._lat_pending)
                    self._lat_pending = None
                    await ctx.collect(out)
        evict_to = end - self.width + self.slide
        self.left.evict_before(evict_to)
        self.right.evict_before(evict_to)


class WindowArgmaxOperator(Operator):
    """Fused ``A JOIN (SELECT max(x), window FROM A GROUP BY window)``
    (the optimizer's argmax rewrite, WindowArgmaxSpec): rows arrive
    keyed by window, buffer per window until the watermark passes, then
    emit exactly the rows achieving the window's max/min of
    ``value_col`` — ties included, like the self-join — plus the pruned
    side's synthesized columns.

    Sound at any upstream parallelism: every global argmax row is also
    a local argmax row in its upstream subtask (value <= local max <=
    global max, with equality required end-to-end), so upstream may
    pre-filter to local candidates and this window-keyed stage settles
    the global answer."""

    def __init__(self, name: str, value_col: str, minmax: str,
                 synth_cols: Tuple[Tuple[str, str], ...],
                 width_micros: int, raw: bool = False,
                 late_ttl_micros: int = 0):
        super().__init__(name)
        self.value_col = value_col
        self.minmax = minmax
        self.synth_cols = synth_cols
        self.width = max(int(width_micros), 1)
        self.raw = raw
        # raw mode must bound the final-extrema table: with no TTL the
        # table would grow one entry per window forever (the SQL planner
        # always passes the join TTL it replaced; direct Stream API users
        # who omit it get one window span — the tightest bound that
        # still catches in-flight stragglers)
        self.late_ttl = (max(int(late_ttl_micros), self.width)
                         if raw else max(int(late_ttl_micros), 0))
        # raw mode: per-window running extremum for the admission
        # pre-filter.  Memory only — on restore the buffer holds exactly
        # the rows that survived the filter, so an empty dict merely
        # means the first post-restore batch per window is admitted
        # unfiltered (correctness never depends on it)
        self._running: Dict[int, float] = {}
        self._released_wm: Optional[int] = None

    def tables(self) -> List[TableDescriptor]:
        tables = [TableDescriptor("b", TableType.BATCH_BUFFER,
                                  "per-window candidate rows",
                                  retention_micros=self.width)]
        if self.raw:
            # released windows' FINAL extrema, retained for the TTL of
            # the join this fusion replaced: a genuinely-late row still
            # matches exactly as it would have against the TTL'd max row
            tables.append(TableDescriptor(
                "f", TableType.TIME_KEY_MAP,
                "released-window final extrema",
                retention_micros=self.late_ttl))
        return tables

    async def on_start(self, ctx: Context) -> None:
        self.buf = ctx.state.get_batch_buffer("b")
        self.final = (ctx.state.get_time_key_map("f") if self.raw
                      else None)
        if ctx.last_watermark is not None:
            # windows at or below the checkpoint watermark fired before
            # the crash; re-arming the guard keeps a late replayed row
            # from re-emitting a whole partial duplicate window (late
            # rows instead match the persisted final extrema)
            self._released_wm = ctx.last_watermark

    def ctx_watermark(self, ctx: Context) -> Optional[int]:
        """Release threshold: the operator's current input watermark,
        floored by the last timer-fired window end (covers restore, where
        both are checkpointed together)."""
        wm = ctx.last_watermark
        if self._released_wm is not None:
            wm = (self._released_wm if wm is None
                  else max(wm, self._released_wm))
        return wm

    async def _admit(self, batch: Batch, ctx: Context) -> Optional[Batch]:
        """Raw mode admission: SQL-NULL values drop (they never equal an
        extremum); rows of already-released windows match the window's
        retained FINAL extremum and emit immediately (the TTL'd join
        this operator replaces would still hold the max row — a late
        tying probe emits there too, and expires the same way once the
        TTL evicts it); live rows strictly dominated by the window's
        running extremum drop (the extremum only tightens, so a
        dominated row can never tie the final answer; ties at the
        current extremum must stay).  Returns the batch to buffer."""
        ends = np.asarray(batch.columns["window_end"], dtype=np.int64)  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
        vals = np.asarray(batch.columns[self.value_col])  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
        keep = (~np.isnan(vals) if vals.dtype.kind == "f"
                else np.ones(len(vals), dtype=bool))
        # lateness keys off the operator's CURRENT input watermark: any
        # row with window_end <= watermark is late, whether or not that
        # window ever fired.  Keying off the last-fired window end let a
        # late row for an EMPTY middle window (no on-time rows, so no
        # timer, so _released_wm never advanced past it) re-open the
        # window and emit as its max — the unfused TTL-join plan (and the
        # reference, whose aggregate drops late rows) emits nothing
        # there.  _released_wm stays as a lower bound for timer-released
        # windows at equal watermark.
        released = self.ctx_watermark(ctx)
        if released is not None:
            late = keep & (ends <= released)
            if late.any():
                keep &= ~late
                hit = np.zeros(len(ends), dtype=bool)
                for e in np.unique(ends[late]).tolist():
                    best = self.final.get(e, "x")
                    if best is not None:
                        hit |= late & (ends == e) & (vals == best)
                if hit.any():
                    await self._emit(batch.select(np.nonzero(hit)[0]), ctx)
        sign = 1.0 if self.minmax == "max" else -1.0
        for e in np.unique(ends[keep]).tolist():
            m = keep & (ends == e)
            best = self._running.get(e)
            if best is not None:
                m_new = m & (sign * vals >= best)
                keep &= ~m | m_new
                m = m_new
            if m.any():
                local = (sign * vals[m]).max()
                self._running[e] = (local if best is None
                                    else max(best, local))
        if keep.all():
            return batch
        if not keep.any():
            return None
        return batch.select(np.nonzero(keep)[0])

    async def _emit(self, rows: Batch, ctx: Context) -> None:
        cols = dict(rows.columns)
        for out_name, src in self.synth_cols:
            cols[out_name] = cols[src]
        await ctx.collect(Batch(rows.timestamp, cols, rows.key_hash,
                                rows.key_cols))

    async def process_batch(self, batch: Batch, ctx: Context,
                            side: int = 0) -> None:
        if self.raw:
            admitted = await self._admit(batch, ctx)
            if admitted is None:
                return
            batch = admitted
        self.buf.append(batch)
        # one timer per distinct window end; aggregate rows stamp
        # timestamp = window_end - 1 (operator _emit convention)
        for e in np.unique(
                np.asarray(batch.columns["window_end"],  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
                           dtype=np.int64)).tolist():
            ctx.timers.schedule(int(e), ("am", int(e)))

    async def handle_timer(self, time: int, key: Any, payload: Any,
                           ctx: Context) -> None:
        end = key[1]
        rows = self.buf.query_range(end - 1, end)  # ts == end - 1
        self.buf.evict_before(end)
        self._running.pop(end, None)
        self._released_wm = (end if self._released_wm is None
                             else max(self._released_wm, end))
        if rows is None or not len(rows):
            return
        vals = np.asarray(rows.columns[self.value_col])  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
        # SQL NULL values (NaN — e.g. SUM over an all-null pane) never
        # equal the max in the join this operator replaces; a plain
        # vals.max() would let one NaN poison the extremum and drop the
        # whole window's rows
        valid = (~np.isnan(vals) if vals.dtype.kind == "f"
                 else np.ones(len(vals), dtype=bool))
        if not valid.any():
            return
        vv = vals[valid]
        best = vv.max() if self.minmax == "max" else vv.min()
        if self.final is not None:
            self.final.insert(end, "x", best)
            if self.late_ttl:
                self.final.evict_before(end - self.late_ttl)
        sel = np.nonzero(valid & (vals == best))[0]
        await self._emit(rows.select(sel), ctx)


def _empty_like_side(tmpl: "_SideTemplate", other: Batch) -> Batch:
    """A 0-row batch shaped like one join side (for windows where that
    side saw no data)."""
    cols = {c: v[:0] for c, v in tmpl.null_cols(0).items()}
    return Batch(np.zeros(0, dtype=np.int64), cols,
                 np.zeros(0, dtype=np.uint64), other.key_cols)


def _concat_col(parts: List[np.ndarray]) -> np.ndarray:
    """Concatenate column fragments, promoting to object when any
    fragment is (None-padded rows mix with typed rows).

    int64 fragments mixed with NaN-padded (outer-join null) fragments
    promote to float64 — the engine-wide nullable-int convention
    (docs/architecture.md): BIGINT values above 2^53 lose precision in
    outer-join output batches that mix matched and unmatched rows.
    Nexmark ids and realistic key spaces sit far below that bound; a
    lossless alternative (object dtype with None pads) would take every
    downstream vectorized op off the fast path."""
    if any(p.dtype == object for p in parts):
        out = np.empty(sum(len(p) for p in parts), dtype=object)
        at = 0
        for p in parts:
            out[at:at + len(p)] = p
            at += len(p)
        return out
    return np.concatenate(parts)


def _assemble_join_output(l_rows: Batch, r_rows: Batch,
                          l_un: Optional[Batch], r_un: Optional[Batch],
                          end: int, how: JoinType, key_cols,
                          l_prefix: str = "", r_prefix: str = "",
                          tmpl: Optional[Tuple["_SideTemplate",
                                               "_SideTemplate"]] = None,
                          r_fallback: Optional[Batch] = None,
                          l_fallback: Optional[Batch] = None) -> Batch:
    """Build one join-output batch from aligned matched rows plus the
    per-side unmatched rows — the single emission home for BOTH the
    legacy re-sort path and the partitioned sorted-run path.  Every part
    goes through the same layout normalization so matched, left-padded
    and right-padded rows of one join share ONE column layout (and so do
    successive fires on the same edge)."""
    key_names = tuple(key_cols)
    parts: List[Tuple[Dict[str, np.ndarray], np.ndarray]] = []  # (cols, kh)
    parts.append((_stable_join_part(
        dict(l_rows.columns), dict(r_rows.columns), len(l_rows),
        key_names, l_prefix, r_prefix), l_rows.key_hash))

    if how in (JoinType.LEFT, JoinType.FULL) and l_un is not None \
            and len(l_un):
        pad = ((tmpl[1].null_cols(len(l_un))) if tmpl is not None
               else {c: _null_column(len(l_un), like=v)
                     for c, v in (r_fallback or r_rows).columns.items()})
        parts.append((_stable_join_part(
            dict(l_un.columns), pad, len(l_un), key_names,
            l_prefix, r_prefix), l_un.key_hash))
    if how in (JoinType.RIGHT, JoinType.FULL) and r_un is not None \
            and len(r_un):
        pad = ((tmpl[0].null_cols(len(r_un))) if tmpl is not None
               else {c: _null_column(len(r_un), like=v)
                     for c, v in (l_fallback or l_rows).columns.items()})
        parts.append((_stable_join_part(
            pad, dict(r_un.columns), len(r_un), key_names,
            l_prefix, r_prefix), r_un.key_hash))

    if len(parts) == 1:
        cols, kh = parts[0]
        ts = np.full(len(kh), end - 1, dtype=np.int64)
        return Batch(ts, cols, kh, key_names)
    names = list(parts[0][0])
    out_cols = {c: _concat_col([p[0][c] for p in parts]) for c in names}
    kh = np.concatenate([p[1] for p in parts])
    ts = np.full(len(kh), end - 1, dtype=np.int64)
    return Batch(ts, out_cols, kh, key_names)


def join_batches(l: Batch, r: Batch, end: int,
                 l_prefix: str = "", r_prefix: str = "",
                 how: JoinType = JoinType.INNER,
                 tmpl: Optional[Tuple["_SideTemplate", "_SideTemplate"]] = None
                 ) -> Batch:
    """Sorted-merge equi-join of two keyed batches on key_hash, with
    LEFT/RIGHT/FULL null-padding of unmatched rows (the reference's
    windowed list-merge, arroyo-sql/src/expressions.rs:134-230).

    This is the legacy full re-sort path (both key arrays argsorted per
    call); the partitioned sorted-run fire path computes the same four
    row groups from incrementally maintained state (state/join_state.py)
    and shares the assembly/normalization above."""
    lo, ro, lidx, ridx, counts = join_pairs(l.key_hash, r.key_hash)

    l_rows = l.select(lo[lidx])
    r_rows = r.select(ro[ridx])
    l_un = (l.select(lo[counts == 0])
            if how in (JoinType.LEFT, JoinType.FULL) else None)
    r_un = None
    if how in (JoinType.RIGHT, JoinType.FULL):
        r_matched = np.zeros(len(r.key_hash), dtype=bool)
        if len(ridx):
            r_matched[ro[ridx]] = True
        r_un = r.select(~r_matched)
    from ..state.join_state import _count_gather

    _count_gather(0, len(l_rows) + len(r_rows)
                  + (len(l_un) if l_un is not None else 0)
                  + (len(r_un) if r_un is not None else 0))
    return _assemble_join_output(l_rows, r_rows, l_un, r_un, end, how,
                                 l.key_cols, l_prefix, r_prefix, tmpl,
                                 r_fallback=r, l_fallback=l)


class JoinWithExpirationOperator(Operator):
    """Unwindowed stream-stream join with TTL state
    (join_with_expiration.rs:14-483).  Inner joins emit append rows; outer
    joins emit updating (``__op``) rows: an arriving row with no opposite
    match emits a null-padded CREATE, and when the FIRST opposite-side row
    for that key later arrives, the padded rows are retracted (DELETE) and
    replaced by joined CREATEs — the reference's ``UpdatingData::Update
    {old, new}`` model (join_with_expiration.rs:80-95, 162-218)."""

    def __init__(self, name: str, left_ttl: int, right_ttl: int,
                 join_type: JoinType,
                 left_cols: Tuple[Tuple[str, str], ...] = (),
                 right_cols: Tuple[Tuple[str, str], ...] = ()):
        super().__init__(name)
        self.left_ttl = left_ttl
        self.right_ttl = right_ttl
        self.join_type = join_type
        self._tmpl = (_SideTemplate(left_cols), _SideTemplate(right_cols))

    def tables(self) -> List[TableDescriptor]:
        return [
            TableDescriptor("l", TableType.BATCH_BUFFER, "left state",
                            retention_micros=self.left_ttl),
            TableDescriptor("r", TableType.BATCH_BUFFER, "right state",
                            retention_micros=self.right_ttl),
        ]

    async def on_start(self, ctx: Context) -> None:
        from ..state.join_state import PartitionedJoinBuffer

        self.left = ctx.state.get_join_buffer("l")
        self.right = ctx.state.get_join_buffer("r")
        self._partitioned = isinstance(self.left, PartitionedJoinBuffer) \
            and isinstance(self.right, PartitionedJoinBuffer)
        self._lat_pending: Optional[Tuple[int, float]] = None

    def _orient(self, mine_rows: Batch, opp_cols: Dict[str, np.ndarray],
                side: int, end: int, op: Optional[int],
                kh: Optional[np.ndarray] = None) -> Batch:
        """Build an output batch from rows of MY side joined against
        already-named opposite-side columns, in left-right orientation.
        All four emission paths (matched, padded, retraction, either
        arrival side) route through ``_stable_join_part`` so the edge
        carries one column layout for the life of the join."""
        n = len(mine_rows)
        key_names = tuple(mine_rows.key_cols)
        if side == 0:
            cols = _stable_join_part(dict(mine_rows.columns),
                                     dict(opp_cols), n, key_names)
        else:
            cols = _stable_join_part(dict(opp_cols),
                                     dict(mine_rows.columns), n,
                                     key_names)
        if op is not None:
            cols[UPDATE_OP_COLUMN] = np.full(n, op, np.int8)
        ts = np.full(n, end - 1, dtype=np.int64)
        return Batch(ts, cols,
                     mine_rows.key_hash if kh is None else kh,
                     mine_rows.key_cols)

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        assert batch.key_hash is not None
        if not len(batch):
            return
        how = self.join_type
        self._tmpl[side].observe(batch)
        mine, other = ((self.left, self.right) if side == 0
                       else (self.right, self.left))
        my_tmpl, opp_tmpl = self._tmpl[side], self._tmpl[1 - side]
        # is MY side / the OPPOSITE side null-padded when unmatched?
        my_outer = how in ((JoinType.LEFT, JoinType.FULL) if side == 0
                           else (JoinType.RIGHT, JoinType.FULL))
        opp_outer = how in ((JoinType.RIGHT, JoinType.FULL) if side == 0
                            else (JoinType.LEFT, JoinType.FULL))
        updating = how != JoinType.INNER
        op_create = UpdateOp.CREATE.value if updating else None

        # emptiness check must stay O(P): len() counts LIVE rows with a
        # full timestamp scan; resident-but-dead rows are fine here (the
        # probe filters them), so non-empty partitions suffice
        have_opp = (any(part.n for part in other.parts)
                    if self._partitioned else len(other) > 0)
        end = int(batch.timestamp.max()) + 1

        # 1. retract padded opposite rows: keys NEW to my buffer that
        #    match existing opposite rows previously emitted as
        #    (null, opp) — the reference's first_left/first_right Update.
        #    Caveat shared with the reference: "new" is judged from the
        #    CURRENT buffer, so after TTL eviction a re-arriving key can
        #    retract a padded row that was already retracted (the
        #    reference's first_right is likewise recomputed from post-
        #    eviction state, join_with_expiration.rs:420-430) — accepted
        #    as parity behavior for expired-state edge cases
        if opp_outer and have_opp:
            batch_keys = np.unique(batch.key_hash)
            new_keys = batch_keys[~mine.contains_keys(batch_keys)]
            if len(new_keys):
                if self._partitioned:
                    # sorted-run probe for exactly the hit rows — the
                    # opposite buffer is never materialized or re-sorted
                    padded = other.rows_with_keys(new_keys)
                else:
                    opp_all = other.all()
                    padded = opp_all.select(
                        np.isin(opp_all.key_hash, new_keys))
                    from ..state.join_state import _count_gather

                    _count_gather(0, len(padded))
                if len(padded):
                    # the hit rows are OPPOSITE-side rows whose padded
                    # (null, row) emission is now stale; my side is the pad
                    pad = my_tmpl.null_cols(len(padded))
                    out = self._orient(padded, pad, 1 - side, end,
                                       UpdateOp.DELETE.value)
                    await ctx.collect(out)

        # 2. joined CREATEs for matched pairs.  Partitioned state probes
        #    the arriving batch against each partition's resident sorted
        #    run (only the batch's delta gets sorted); the legacy path
        #    re-sorts both sides per call (ops/join.py kernels).
        if have_opp:
            if self._partitioned:
                bsel, opp_rows, counts = other.probe_batch(batch)
                if len(bsel):
                    my_rows = batch.select(bsel)
                    out = self._orient(my_rows, dict(opp_rows.columns),
                                       side, end, op_create)
                    await ctx.collect(out)
                unmatched = counts == 0
            else:
                opp = other.all()
                lo, ro, lidx, ridx, counts = join_pairs(batch.key_hash,
                                                        opp.key_hash)
                if len(lidx):
                    my_rows = batch.select(lo[lidx])
                    opp_rows = opp.select(ro[ridx])
                    from ..state.join_state import _count_gather

                    _count_gather(0, len(opp_rows))
                    out = self._orient(my_rows, dict(opp_rows.columns),
                                       side, end, op_create)
                    await ctx.collect(out)
                unmatched = np.zeros(len(batch), dtype=bool)
                unmatched[lo[counts == 0]] = True  # back to original order
        else:
            unmatched = np.ones(len(batch), dtype=bool)

        # 3. null-padded CREATEs for my unmatched rows
        if my_outer and unmatched.any():
            un = batch.select(unmatched)
            pad = opp_tmpl.null_cols(len(un))
            out = self._orient(un, pad, side, end, op_create)
            await ctx.collect(out)

        # 4. buffer — EXCEPT null-keyed rows: their pad (if any) was
        #    emitted above and can never be matched or retracted
        #    (no opposite row shares the nonce), so they never enter
        #    state (_drop_null_keyed)
        batch = _drop_null_keyed(batch)
        if batch is not None and len(batch):
            mine.append(batch)

    async def handle_watermark(self, watermark: int, ctx: Context) -> None:
        self.left.evict_before(watermark - self.left_ttl)
        self.right.evict_before(watermark - self.right_ttl)
        await ctx.broadcast(Message.wm(Watermark.event_time(watermark)))


class MultiWayJoinOperator(Operator):
    """N-ary INNER equi-join over sides sharing one key (the planner's
    cascaded-join rewrite; MultiWayJoinSpec).  Per fire (windowed mode)
    or per arriving batch (TTL mode), the per-key cross product across
    ALL sides expands directly from the sides' sorted runs — no pairwise
    intermediate is ever materialized, re-keyed, or re-buffered."""

    def __init__(self, name: str, typ, ttl_micros: int, n_sides: int):
        super().__init__(name)
        self.typ = typ
        self.ttl = ttl_micros
        self.n_sides = n_sides
        if typ is not None:
            self.width, self.slide = _window_params(typ)
        else:
            self.width = self.slide = 0

    def tables(self) -> List[TableDescriptor]:
        retention = self.width if self.typ is not None else self.ttl
        return [TableDescriptor(f"j{i}", TableType.BATCH_BUFFER,
                                f"join side {i}",
                                retention_micros=retention)
                for i in range(self.n_sides)]

    async def on_start(self, ctx: Context) -> None:
        # always partitioned: the N-ary probe needs sorted runs (the
        # checkpoint form is the same BATCH_BUFFER batch either way)
        self.bufs = [ctx.state.get_join_buffer(f"j{i}",
                                               force_partitioned=True)
                     for i in range(self.n_sides)]

    # -- shared expansion --------------------------------------------------

    @staticmethod
    def _expand(counts: List[np.ndarray]
                ) -> Tuple[np.ndarray, List[np.ndarray]]:
        """Cross-product expansion: for groups g with per-side match
        counts ``counts[i][g]``, return (group_id per output row, per-side
        offset within the group's side-i match list)."""
        from ..ops.join import expand_counts

        S = len(counts)
        m = counts[0].astype(np.int64).copy()
        for c in counts[1:]:
            m *= c
        gid, within = expand_counts(m)
        offs: List[np.ndarray] = [np.zeros(0, np.int64)] * S
        stride = np.ones(len(m), dtype=np.int64)
        for i in range(S - 1, -1, -1):
            ci = np.maximum(counts[i].astype(np.int64), 1)
            offs[i] = (within // stride[gid]) % ci[gid]
            stride = stride * ci
        return gid, offs

    def _emit_sides(self, side_rows: List[Batch], end: int,
                    ctx: Context) -> Batch:
        """Assemble the joined output left-to-right: side 0 plays the
        left role (carries the internal join-key columns), every later
        side folds in through the same layout normalization the pairwise
        join uses — one stable column layout per edge."""
        key_names = tuple(side_rows[0].key_cols)
        cols = dict(side_rows[0].columns)
        n = len(side_rows[0])
        for rows in side_rows[1:]:
            cols = _stable_join_part(cols, dict(rows.columns), n,
                                     key_names)
        ts = np.full(n, end - 1, dtype=np.int64)
        return Batch(ts, cols, side_rows[0].key_hash, key_names)

    # -- windowed mode -----------------------------------------------------

    async def process_batch(self, batch: Batch, ctx: Context,
                            side: int = 0) -> None:
        assert batch.key_hash is not None, "multi-way join requires keys"
        if not len(batch):
            return
        # inner-only: null-keyed rows can never match any side — never
        # buffered (_drop_null_keyed)
        batch = _drop_null_keyed(batch)
        if batch is None or not len(batch):
            return
        if self.typ is None:
            await self._probe_ttl(batch, side, ctx)
            self.bufs[side].append(batch)
            return
        self.bufs[side].append(batch)
        first_end = (batch.timestamp // self.slide + 1) * self.slide
        if isinstance(self.typ, SlidingWindow):
            ends = np.unique(np.concatenate([
                first_end + i * self.slide
                for i in range(self.width // self.slide)]))
        else:
            ends = np.unique(first_end - self.slide + self.width)
        for e in ends.tolist():
            ctx.timers.schedule(int(e), ("mw", int(e)))

    async def handle_timer(self, time: int, key: Any, payload: Any,
                           ctx: Context) -> None:
        end = key[1]
        start = end - self.width
        P = self.bufs[0].P
        out_parts: List[Batch] = []
        for p in range(P):
            views = [b.parts[p].range_view(start, end) for b in self.bufs]
            if any(len(k) == 0 for k, _pos in views):
                continue
            # keys present on EVERY side (all views key-sorted)
            uk = np.unique(views[0][0])
            for k, _pos in views[1:]:
                idx = np.searchsorted(k, uk)
                ok = idx < len(k)
                ok[ok] = k[idx[ok]] == uk[ok]
                uk = uk[ok]
                if not len(uk):
                    break
            if not len(uk):
                continue
            starts: List[np.ndarray] = []
            cnts: List[np.ndarray] = []
            for k, _pos in views:
                s = np.searchsorted(k, uk, side="left")
                e = np.searchsorted(k, uk, side="right")
                starts.append(s)
                cnts.append(e - s)
            gid, offs = self._expand(cnts)
            if not len(gid):
                continue
            side_rows = []
            for i, (k, pos) in enumerate(views):
                rows = starts[i][gid] + offs[i]
                side_rows.append(self.bufs[i].gather(
                    p * (1 << 48) + pos[rows]))
            out_parts.append(self._emit_sides(side_rows, end, ctx))
        if out_parts:
            out = (out_parts[0] if len(out_parts) == 1
                   else Batch.concat(out_parts))
            if len(out):
                await ctx.collect(out)
        evict_to = end - self.width + self.slide
        for b in self.bufs:
            b.evict_before(evict_to)

    # -- TTL mode ----------------------------------------------------------

    async def _probe_ttl(self, batch: Batch, side: int,
                         ctx: Context) -> None:
        n = len(batch)
        kh = batch.key_hash
        sorter = np.argsort(kh, kind="stable")
        counts: List[np.ndarray] = []
        groups: List[Optional[Tuple[np.ndarray, np.ndarray]]] = []
        for i, buf in enumerate(self.bufs):
            if i == side:
                counts.append(np.ones(n, dtype=np.int64))
                groups.append(None)
                continue
            qidx, gpos = buf.probe_positions(kh[sorter], pre_sorted=True)
            order = np.argsort(qidx, kind="stable")
            qidx, gpos = qidx[order], gpos[order]
            c = np.bincount(qidx, minlength=n)
            counts.append(c)
            groups.append((np.cumsum(c) - c, gpos))
        gid, offs = self._expand(counts)
        if not len(gid):
            return
        end = int(batch.timestamp.max()) + 1
        side_rows = []
        for i, buf in enumerate(self.bufs):
            if i == side:
                side_rows.append(batch.select(sorter[gid]))
            else:
                starts, gpos = groups[i]
                side_rows.append(buf.gather(gpos[starts[gid] + offs[i]]))
        out = self._emit_sides(side_rows, end, ctx)
        if len(out):
            await ctx.collect(out)

    async def handle_watermark(self, watermark: int, ctx: Context) -> None:
        if self.typ is None:
            for b in self.bufs:
                b.evict_before(watermark - self.ttl)
        await ctx.broadcast(Message.wm(Watermark.event_time(watermark)))


class SemiJoinOperator(Operator):
    """Streaming semi-join — the executor behind ``x IN (SELECT ...)``:
    left rows emit EXACTLY ONCE when a matching right key exists (now or
    within the TTL), never duplicated per right-side match.

    Left rows without a current match wait in a batch buffer; when a right
    key is seen for the first time, matching buffered left rows emit and
    leave the buffer.  Right keys live in keyed state with the right TTL.
    """

    def __init__(self, name: str, left_ttl: int, right_ttl: int):
        super().__init__(name)
        self.left_ttl = left_ttl
        self.right_ttl = right_ttl

    def tables(self) -> List[TableDescriptor]:
        return [
            TableDescriptor("l", TableType.BATCH_BUFFER, "left pending",
                            retention_micros=self.left_ttl),
            TableDescriptor("r", TableType.KEYED, "right keys seen",
                            retention_micros=self.right_ttl),
        ]

    async def on_start(self, ctx: Context) -> None:
        self.left = ctx.state.get_batch_buffer("l")
        self.rkeys = ctx.state.get_keyed_state("r")

    def _right_has(self, kh: np.ndarray) -> np.ndarray:
        uniq = np.unique(kh)
        known = np.array([self.rkeys.get(int(k)) is not None  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
                          for k in uniq])
        return known[np.searchsorted(uniq, kh)]

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        assert batch.key_hash is not None
        if side == 0:  # left: emit matches now, buffer the rest
            mask = self._right_has(batch.key_hash)
            if mask.any():
                await ctx.collect(batch.select(mask))
            if not mask.all():
                self.left.append(batch.select(~mask))
            return
        # right: refresh every key's timestamp (a continuously-hot key
        # must not expire off its FIRST sighting; a LATE re-sighting must
        # not move it backward); first sightings release waiting left rows
        uniq, first = np.unique(batch.key_hash, return_index=True)
        fresh = np.array([self.rkeys.get(int(k)) is None for k in uniq])  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
        for k, i in zip(uniq.tolist(), first.tolist()):
            prev_t = self.rkeys.get_time(int(k))
            t = int(batch.timestamp[i])
            self.rkeys.insert(t if prev_t is None else max(t, prev_t),
                              int(k), True)
        if not fresh.any():
            return
        new_keys = uniq[fresh]
        pending = self.left.all()
        if pending is not None and len(pending):
            m = np.isin(pending.key_hash, new_keys)
            if m.any():
                await ctx.collect(pending.select(m))
                self.left.remove_keys(new_keys)

    async def handle_watermark(self, watermark: int, ctx: Context) -> None:
        self.left.evict_before(watermark - self.left_ttl)
        for t, k, _v in self.rkeys.snapshot():
            if t < watermark - self.right_ttl:
                self.rkeys.remove(k)
        await ctx.broadcast(Message.wm(Watermark.event_time(watermark)))


class NonWindowAggOperator(Operator):
    """Running per-key aggregates over an updating stream with expiration
    (UpdatingAggregateOperator, updating_aggregate.rs:11-150): each batch
    merges into per-key running state and emits create/update rows.

    With ``flush_key`` set (GROUP BY the window of a windowed input, q5's
    MaxBids shape), refinements are instead CONSOLIDATED in state and each
    key emits its final row exactly once, when the watermark passes the
    named key column — upstream panes always precede the watermark that
    releases them (shuffle fan-in takes the min across subtasks), so this
    is append-only-correct even when one window's rows arrive in several
    batches from several upstream subtasks."""

    def __init__(self, name: str, expiration_micros: int,
                 aggs: Tuple[AggSpec, ...], projection=None,
                 flush_key: Optional[str] = None):
        super().__init__(name)
        self.expiration = expiration_micros
        self.aggs = aggs
        self.flush_key = flush_key
        # highest flush bound already released: a record re-created for a
        # window at or below it is a LATE refinement (its panes arrived
        # after the watermark released the window) — emitting it again
        # would duplicate the window's final row downstream
        self._released_wm: Optional[int] = None
        self.projection = (CompiledExpr(projection.name, projection.fn)
                           if projection else None)

    def tables(self) -> List[TableDescriptor]:
        return [TableDescriptor("u", TableType.KEYED, "running aggregates",
                                retention_micros=self.expiration)]

    async def on_start(self, ctx: Context) -> None:
        self.table = ctx.state.get_keyed_state("u")
        # re-arm the duplicate-flush guard across restore: every window at
        # or below the checkpoint watermark was already released before
        # the crash (flush runs on each watermark ahead of the barrier),
        # so restored records at or below it are late re-creations
        if ctx.last_watermark is not None:
            self._released_wm = ctx.last_watermark

    async def process_batch(self, batch: Batch, ctx: Context, side: int = 0) -> None:
        assert batch.key_hash is not None
        uniq, agg_cols, max_ts, row_counts, valid_counts = segment_aggregate(
            batch.key_hash, batch.timestamp, batch.columns, self.aggs)
        key_cols = _first_occurrence_cols(batch, uniq)
        n = len(uniq)
        ops = np.zeros(n, dtype=np.int8)
        out_cols: Dict[str, List] = {a.output: [] for a in self.aggs}
        for i, k in enumerate(uniq.tolist()):
            prev = self.table.get(k)
            merged: Dict[str, float] = {}
            for a in self.aggs:
                new = agg_cols[a.output][i]
                # an all-null segment contributes nothing to the running
                # aggregate (NaN marks SQL NULL from segment_aggregate)
                new_null = (new is None
                            or (isinstance(new, (float, np.floating))
                                and np.isnan(new)))
                if a.kind == AggKind.AVG:
                    # mergeable avg: store (sum, non-null count) internally
                    nv = int(valid_counts[a.output][i])
                    new_sum = 0.0 if new_null else float(new) * nv
                    old_sum = prev[f"{a.output}__sum"] if prev else 0.0
                    old_cnt = prev[f"{a.output}__cnt"] if prev else 0
                    merged[f"{a.output}__sum"] = old_sum + new_sum
                    merged[f"{a.output}__cnt"] = old_cnt + nv
                    cnt = merged[f"{a.output}__cnt"]
                    merged[a.output] = (merged[f"{a.output}__sum"] / cnt
                                        if cnt else float("nan"))
                elif prev is None:
                    merged[a.output] = new
                else:
                    old = prev[a.output]
                    old_null = (old is None
                                or (isinstance(old, (float, np.floating))
                                    and np.isnan(old)))
                    if new_null:
                        merged[a.output] = old
                    elif old_null:
                        merged[a.output] = new
                    elif a.kind in (AggKind.SUM, AggKind.COUNT):
                        merged[a.output] = old + new
                    elif a.kind == AggKind.MAX:
                        merged[a.output] = max(old, new)
                    elif a.kind == AggKind.MIN:
                        merged[a.output] = min(old, new)
                out_cols[a.output].append(merged[a.output])
            ops[i] = (UpdateOp.CREATE.value if prev is None
                      else UpdateOp.UPDATE.value)
            if self.flush_key is not None:
                # stash key-column values for the watermark-time emission
                # (state-resident, so a restore can still flush correctly)
                for c, arr in key_cols.items():
                    merged[f"__kc::{c}"] = arr[i]
            self.table.insert(int(max_ts[i]), k, merged)
        if self.flush_key is not None:
            return  # emission happens at watermark passage
        cols = dict(key_cols)
        for a in self.aggs:
            arr = np.asarray(out_cols[a.output])  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
            if a.kind == AggKind.COUNT:
                arr = arr.astype(np.int64)
            cols[a.output] = arr
        cols[UPDATE_OP_COLUMN] = ops
        out = Batch(max_ts, cols, uniq.astype(np.uint64), batch.key_cols)
        if self.projection is not None:
            out = eval_record_expr(self.projection, out)
        await ctx.collect(out)

    async def handle_watermark(self, watermark: int, ctx: Context) -> None:
        if self.flush_key is not None:
            from ..obs import tracing

            with tracing.span("window.flush_ready", "window",
                              tid=tracing.ctx_tid(ctx),
                              args={"watermark": int(watermark)}):
                await self._flush_ready(watermark, ctx)
        await ctx.broadcast(Message.wm(Watermark.event_time(watermark)))

    async def _flush_ready(self, watermark: int, ctx: Context) -> None:
        fk = f"__kc::{self.flush_key}"
        ready = []
        for t, k, rec in list(self.table.snapshot()):
            bound = rec.get(fk)
            # integer comparison: window_end is epoch micros (~1.8e18,
            # above 2^53), where a float round-trip can round DOWN and
            # flush a window before a lagging subtask's pane arrives
            if bound is None or int(bound) <= watermark:
                if (bound is not None and self._released_wm is not None
                        and int(bound) <= self._released_wm):
                    # late re-creation of an already-released window:
                    # its final row went downstream at an earlier
                    # watermark — a second (partial) row would duplicate
                    # it.  Late panes drop, matching lateness semantics.
                    self.table.remove(k)
                    continue
                ready.append((t, k, rec))
        self._released_wm = (watermark if self._released_wm is None
                             else max(self._released_wm, watermark))
        if not ready:
            return
        ts = np.array([t for t, _, _ in ready], dtype=np.int64)  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
        kh = np.array([k for _, k, _ in ready], dtype=np.uint64)  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
        kc_names = [n[len("__kc::"):] for n in ready[0][2]
                    if n.startswith("__kc::")]
        cols: Dict[str, np.ndarray] = {}
        for c in kc_names:
            cols[c] = np.asarray([rec[f"__kc::{c}"] for _, _, rec in ready])  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
        for a in self.aggs:
            arr = np.asarray([rec[a.output] for _, _, rec in ready])  # arroyolint: disable=host-sync -- intentional pane-emission readback: fired panes must materialize on the host to become output batch columns
            if a.kind == AggKind.COUNT:
                arr = arr.astype(np.int64)
            cols[a.output] = arr
        for _, k, _ in ready:
            self.table.remove(k)
        out = Batch(ts, cols, kh, tuple(kc_names))
        if self.projection is not None:
            out = eval_record_expr(self.projection, out)
        await ctx.collect(out)


# -- builder registration ----------------------------------------------------


@register_builder(OpKind.SLIDING_WINDOW_AGGREGATOR)
def _build_sliding(op: LogicalOperator) -> Operator:
    s = op.spec
    return BinAggOperator(op.name, s.width_micros, s.slide_micros, s.aggs,
                          s.projection,
                          argmax_local=getattr(s, "argmax_local", None))


@register_builder(OpKind.TUMBLING_WINDOW_AGGREGATOR)
def _build_tumbling(op: LogicalOperator) -> Operator:
    s = op.spec
    return BinAggOperator(op.name, s.width_micros, s.width_micros, s.aggs,
                          s.projection,
                          argmax_local=getattr(s, "argmax_local", None))


@register_builder(OpKind.WINDOW_FACTOR)
def _build_window_factor(op: LogicalOperator) -> Operator:
    s = op.spec
    return FactorPaneOperator(op.name, s.pane_micros, s.aggs)


@register_builder(OpKind.DERIVED_WINDOW)
def _build_derived_window(op: LogicalOperator) -> Operator:
    s = op.spec
    return DerivedWindowOperator(op.name, s.width_micros, s.slide_micros,
                                 s.pane_micros, s.aggs, s.projection)


@register_builder(OpKind.SLIDING_AGGREGATING_TOP_N)
def _build_sliding_topn(op: LogicalOperator) -> Operator:
    s = op.spec
    return BinAggOperator(op.name, s.width_micros, s.slide_micros, s.aggs,
                          s.projection,
                          top_n=(s.partition_cols, s.sort_column,
                                 s.max_elements))


@register_builder(OpKind.WINDOW)
def _build_window(op: LogicalOperator) -> Operator:
    s = op.spec
    if isinstance(s.typ, SessionWindow):
        return SessionWindowOperator(op.name, s.typ.gap_micros, s.aggs,
                                     s.flatten, s.projection)
    return WindowOperator(op.name, s.typ, s.aggs, s.flatten, s.projection)


@register_builder(OpKind.TUMBLING_TOP_N)
def _build_topn(op: LogicalOperator) -> Operator:
    s = op.spec
    return TumblingTopNOperator(op.name, s.width_micros, s.max_elements,
                                s.sort_column, s.partition_cols, s.projection,
                                getattr(s, "rank_column", None))


@register_builder(OpKind.WINDOW_JOIN)
def _build_window_join(op: LogicalOperator) -> Operator:
    s = op.spec
    return WindowJoinOperator(op.name, s.typ,
                              getattr(s, "join_type", JoinType.INNER),
                              getattr(s, "left_cols", ()),
                              getattr(s, "right_cols", ()))


@register_builder(OpKind.WINDOW_ARGMAX)
def _build_window_argmax(op: LogicalOperator) -> Operator:
    s = op.spec
    return WindowArgmaxOperator(op.name, s.value_col, s.minmax,
                                s.synth_cols, s.width_micros,
                                raw=getattr(s, "raw", False),
                                late_ttl_micros=getattr(
                                    s, "late_ttl_micros", 0))


@register_builder(OpKind.JOIN_WITH_EXPIRATION)
def _build_join_exp(op: LogicalOperator) -> Operator:
    s = op.spec
    if s.join_type == JoinType.SEMI:
        return SemiJoinOperator(op.name, s.left_expiration_micros,
                                s.right_expiration_micros)
    return JoinWithExpirationOperator(op.name, s.left_expiration_micros,
                                      s.right_expiration_micros, s.join_type,
                                      s.left_cols, s.right_cols)


@register_builder(OpKind.MULTI_WAY_JOIN)
def _build_multi_way_join(op: LogicalOperator) -> Operator:
    s = op.spec
    return MultiWayJoinOperator(op.name, s.typ, s.ttl_micros,
                                len(s.side_cols))


@register_builder(OpKind.NON_WINDOW_AGGREGATOR)
def _build_nonwindow(op: LogicalOperator) -> Operator:
    s = op.spec
    return NonWindowAggOperator(op.name, s.expiration_micros, s.aggs,
                                s.projection,
                                getattr(s, "flush_key", None))
