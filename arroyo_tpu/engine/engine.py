"""Engine: physical graph construction and execution.

Analog of /root/reference/arroyo-worker/src/engine.rs: expands the logical
graph by parallelism into subtasks (engine.rs:597-705), wires Forward (1:1)
vs Shuffle (all-to-all) channels, spawns one asyncio task per subtask
(``Engine::start``/``schedule_node``/``run_locally``, engine.rs:813-1102) and
exposes source/operator control handles (``RunningEngine``, engine.rs:720-811).

``Engine.for_local`` + :class:`LocalRunner` reproduce the reference's
in-process multi-task "cluster" (engine.rs:606-619, 837-863): the full
physical graph — all parallel subtasks, real queues, real state — in one
process.  This is the standard test fixture and the single-host execution
mode; multi-host splits this same graph across workers with network channels.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..config import config
from ..graph.logical import EdgeType, Program, StreamNode
from ..state.backend import BackingStore, InMemoryBackend, ParquetBackend
from ..state.store import StateStore
from ..types import (
    CheckpointBarrier,
    ControlMessage,
    ControlResp,
    Message,
    StopMode,
    TaskInfo,
    now_micros,
)
from .build import build_operator
from .context import Collector, Context, OutQueue
from .operator import SourceOperator
from .task import TaskRunner

logger = logging.getLogger(__name__)

_compile_cache_enabled = False


def _enable_compile_cache() -> None:
    """Point jax at the persistent compilation cache once per process
    (config knob ``compile_cache_dir``; empty = the env-keyed default
    under /tmp, 'off' disables).  Repeated bench probes, engine rebuilds
    and worker restarts then reuse XLA executables instead of paying
    full recompile cost."""
    global _compile_cache_enabled
    if _compile_cache_enabled:
        return
    _compile_cache_enabled = True
    d = config().compile_cache_dir
    if d.lower() in ("off", "0", "false", "disabled", "none"):
        return
    try:
        from .aot import enable_persistent_cache

        enable_persistent_cache(d or None)
    except Exception:
        logger.warning("persistent compile cache unavailable",
                       exc_info=True)


@dataclass
class SubtaskHandle:
    task_info: TaskInfo
    runner: TaskRunner
    control_tx: asyncio.Queue  # ControlMessage -> task
    is_source: bool
    task: Optional[asyncio.Task] = None
    # logical operators executed by this runner — [op_id] for a plain
    # subtask, the full member list (head first) for a chained one
    member_ids: List[str] = field(default_factory=list)


class Engine:
    def __init__(self, program: Program, job_id: str = "local-job",
                 run_id: str = "0",
                 backend: Optional[BackingStore] = None,
                 restore_epoch: Optional[int] = None,
                 assignments: Optional[Dict[Tuple[str, int], str]] = None,
                 my_worker_id: Optional[str] = None,
                 worker_data_addrs: Optional[Dict[str, str]] = None,
                 network: Optional[Any] = None):
        """``assignments`` maps (operator_id, subtask_idx) -> worker_id; when
        given with ``my_worker_id``, only this worker's subtasks are built and
        cross-worker edges ride the network data plane (``network`` must be a
        NetworkManager, ``worker_data_addrs`` maps worker_id -> host:port)."""
        # factor-window sharing for Stream-API-built programs (SQL plans
        # arrive already rewritten by the planner; the pass is idempotent
        # — rewritten plans have no eligible member groups left).  Must
        # run before validation so the validator sees the factored shape.
        from ..graph.factor_windows import apply_factor_windows

        self.factor_decisions = apply_factor_windows(program)
        errors = program.validate()
        if errors:
            raise ValueError("; ".join(errors))
        # full plan-time validation (analysis.plan_validator): keyed
        # state behind shuffles, join key schemas, dangling nodes —
        # reject before any operator is built
        from .build import validate_before_build

        validate_before_build(program)
        self.program = program
        self.job_id = job_id
        self.run_id = run_id
        self.backend = backend if backend is not None else InMemoryBackend()
        self.restore_epoch = restore_epoch
        self.assignments = assignments
        self.my_worker_id = my_worker_id
        self.worker_data_addrs = worker_data_addrs or {}
        self.network = network
        self.control_resp: asyncio.Queue = asyncio.Queue()
        self.sanitizer: Optional[Any] = None  # set by start()
        self.subtasks: Dict[Tuple[str, int], SubtaskHandle] = {}
        self.resps: List[ControlResp] = []  # responses drained so far

    def _is_mine(self, op_id: str, idx: int) -> bool:
        if self.assignments is None:
            return True
        return self.assignments.get((op_id, idx)) == self.my_worker_id

    def _worker_of(self, op_id: str, idx: int) -> Optional[str]:
        if self.assignments is None:
            return None
        return self.assignments.get((op_id, idx))

    @staticmethod
    def for_local(program: Program, job_id: str = "local-job",
                  checkpoint_url: Optional[str] = None,
                  restore_epoch: Optional[int] = None) -> "Engine":
        backend: BackingStore
        if checkpoint_url:
            backend = ParquetBackend.for_url(checkpoint_url)
        else:
            backend = InMemoryBackend()
        return Engine(program, job_id, backend=backend, restore_epoch=restore_epoch)

    # ------------------------------------------------------------------

    def start(self) -> "RunningEngine":
        """Build the physical graph and spawn all subtask loops."""
        _enable_compile_cache()
        # arroyosan runtime sanitizer: one instance per engine run (so a
        # rescale restore starts from fresh invariant state); None unless
        # ARROYO_SANITIZE armed it — the hook sites then cost nothing
        from ..analysis.sanitizer import maybe_sanitizer

        sanitizer = maybe_sanitizer(self.job_id)
        self.sanitizer = sanitizer
        # phase profiler (obs/profiler.py): armed by ARROYO_PROFILE=1 or
        # an explicit profiler.arm() (bench, tests) — must happen before
        # subtask construction so Collectors/coalescers capture it; the
        # hook sites cost one `is not None` test when disarmed
        from ..obs import profiler as _profiler

        prof = _profiler.ensure_armed(self.job_id)
        # latency observatory (obs/latency.py): armed by
        # ARROYO_LATENCY_SAMPLE_N>0 or an explicit latency.arm() — same
        # before-subtask-construction + None-when-disarmed contract as
        # the profiler
        from ..obs import latency as _latency

        _latency.ensure_armed(self.job_id)
        g = self.program.graph
        # operator chaining (graph/chaining.py): maximal linear runs of
        # same-parallelism forward-edge operators execute inside ONE
        # TaskRunner — no intermediate queues, one alignment per chain.
        # ARROYO_CHAIN=0 yields an empty plan and reproduces the
        # per-operator topology bit-for-bit.
        from ..graph.chaining import plan_chains, validate_chain_plan

        chain_plan = plan_chains(self.program)
        validate_chain_plan(self.program, chain_plan)
        chain_interior = {m for grp in chain_plan.groups for m in grp[1:]}
        # observable mesh carriage: how many chain-interior SHUFFLE
        # edges the active mesh carries as on-device all_to_all (0 when
        # ARROYO_MESH=off — those edges are then plain identity-routed
        # queue hops inside the chain).  Set UNCONDITIONALLY: the gauge
        # is process-global per job_id, so a re-plan that lost its
        # carried edges (rescale past parallelism 1, chaining off) must
        # drop it back to 0, not report the previous topology forever.
        from ..obs.metrics import mesh_carried_gauge
        from ..parallel.mesh_window import mesh_key_shards

        mesh_carried_gauge(self.job_id).set(
            len(chain_plan.shuffle_edges)
            if chain_plan.shuffle_edges and mesh_key_shards() > 1 else 0)
        # factor-window shape (set unconditionally: a re-plan that lost
        # its factored groups must drop the gauges to 0, same policy as
        # the mesh-carried gauge)
        from ..graph.logical import OpKind as _OpKind
        from ..obs.metrics import (factor_derived_windows_gauge,
                                   factor_shared_panes_gauge)

        kinds = [n.operator.kind for n in self.program.nodes()]
        factor_shared_panes_gauge(self.job_id).set(
            kinds.count(_OpKind.WINDOW_FACTOR))
        factor_derived_windows_gauge(self.job_id).set(
            kinds.count(_OpKind.DERIVED_WINDOW))
        # queues[(src_id, src_idx, dst_id, dst_idx)] — the reference's Quad
        queues: Dict[Tuple[str, int, str, int], asyncio.Queue] = {}
        qsize = config().queue_size

        def queue_for(quad: Tuple[str, int, str, int]) -> asyncio.Queue:
            if quad not in queues:
                queues[quad] = asyncio.Queue(maxsize=qsize)
            return queues[quad]

        def out_queue(quad: Tuple[str, int, str, int]) -> OutQueue:
            """Local queue or remote network sender for an outgoing edge."""
            _, _, dst_op, dst_idx = quad
            w = self._worker_of(dst_op, dst_idx)
            if w is None or w == self.my_worker_id:
                return OutQueue(queue_for(quad))
            addr = self.worker_data_addrs[w]
            return OutQueue(sender=self.network.remote_sender(addr, quad))

        def in_queue(quad: Tuple[str, int, str, int]) -> asyncio.Queue:
            """Local queue for an incoming edge; remote sources are demuxed
            into it by the network listener."""
            src_op, src_idx, _, _ = quad
            q = queue_for(quad)
            w = self._worker_of(src_op, src_idx)
            if w is not None and w != self.my_worker_id:
                self.network.register_in_edge(quad, q)
            return q

        def build_subtask(ms: List[str], idx: int) -> None:
            """One runner for the member run ``ms`` (a full chain, or a
            single operator) at subtask index ``idx``."""
            head_id, tail_id = ms[0], ms[-1]
            head_node: StreamNode = self.program.node(head_id)
            parallelism = head_node.parallelism
            out_edges = list(g.out_edges(tail_id, data=True))
            in_edges = list(g.in_edges(head_id, data=True))

            # output edge groups (one group per downstream operator),
            # leaving from the chain TAIL
            edge_groups: List[List[OutQueue]] = []
            for _, dst, data in out_edges:
                dst_par = self.program.node(dst).parallelism
                typ: EdgeType = data["edge"].typ
                if typ == EdgeType.FORWARD:
                    # equal parallelism: 1:1 chain; mismatched: rebalance —
                    # fan-in (src i -> dst i % dst_par) or fan-out
                    # (src i -> every dst j with j % src_par == i,
                    # round-robined per batch by the Collector)
                    if dst_par > parallelism:
                        group = [out_queue((tail_id, idx, dst, j))
                                 for j in range(dst_par)
                                 if j % parallelism == idx]
                    else:
                        group = [out_queue((tail_id, idx, dst,
                                            idx % dst_par))]
                else:
                    group = [out_queue((tail_id, idx, dst, j))
                             for j in range(dst_par)]
                edge_groups.append(group)

            # input channels into the chain HEAD: (side, queue) per
            # upstream subtask
            inputs: List[Tuple[int, asyncio.Queue]] = []
            for src, _, data in sorted(
                    in_edges, key=lambda e: e[2]["edge"].typ.value):
                src_par = self.program.node(src).parallelism
                typ = data["edge"].typ
                side = typ.join_side or 0  # shuffle_join_N carries N
                if typ == EdgeType.FORWARD:
                    if parallelism > src_par:
                        inputs.append((side, in_queue(
                            (src, idx % src_par, head_id, idx))))
                    else:
                        for j in range(src_par):
                            if j % parallelism == idx:
                                inputs.append((side, in_queue(
                                    (src, j, head_id, idx))))
                else:
                    for j in range(src_par):
                        inputs.append((side, in_queue((src, j, head_id,
                                                       idx))))

            from ..obs.metrics import (CHAIN_MEMBERS, TaskMetrics,
                                       gauge_for_task)

            infos = [TaskInfo(self.job_id, m,
                              self.program.node(m).operator.name, idx,
                              parallelism) for m in ms]
            metrics_list = [TaskMetrics(ti) for ti in infos]
            stores = [StateStore(ti, self.backend, self.restore_epoch)
                      for ti in infos]
            for st in stores:
                st.sanitizer = sanitizer
            collector = Collector(edge_groups, metrics_list[-1],
                                  op_id=tail_id, sanitizer=sanitizer,
                                  subtask=idx)
            if len(ms) == 1:
                operator = build_operator(head_node.operator)
                rwm = (stores[0].restore_watermark()
                       if self.restore_epoch else None)
                ctx = Context(infos[0], collector, n_inputs=len(inputs),
                              state_store=stores[0],
                              control_tx=self.control_resp,
                              restore_watermark=rwm,
                              metrics=metrics_list[0])
            else:
                from .chained import ChainedOperator

                ops = [build_operator(self.program.node(m).operator)
                       for m in ms]
                operator = ChainedOperator(infos, ops)
                ctxs: List[Context] = []
                for i, (ti, st, mx) in enumerate(
                        zip(infos, stores, metrics_list)):
                    coll = (collector if i == len(ms) - 1
                            else operator.make_link(i))
                    rwm = (st.restore_watermark()
                           if self.restore_epoch else None)
                    ctxs.append(Context(
                        ti, coll,
                        n_inputs=len(inputs) if i == 0 else 1,
                        state_store=st, control_tx=self.control_resp,
                        restore_watermark=rwm, metrics=mx))
                operator.bind(ctxs)
                ctx = ctxs[0]
            gauge_for_task(infos[0], CHAIN_MEMBERS,
                           "operators fused into this task").set(len(ms))
            control_rx: asyncio.Queue = asyncio.Queue()
            runner = TaskRunner(infos[0], operator, ctx, inputs,
                                control_rx, self.control_resp,
                                sanitizer=sanitizer)
            ctx._runner = runner  # sources poll control via the runner
            self.subtasks[(head_id, idx)] = SubtaskHandle(
                infos[0], runner, control_rx,
                isinstance(operator, SourceOperator),
                member_ids=list(ms))

        # construct subtasks in topo order (chain heads only; interior
        # members are built inside their head's runner)
        for op_id in self.program.topo_order():
            if op_id in chain_interior:
                continue
            members = chain_plan.members_of.get(op_id, [op_id])
            for idx in range(self.program.node(op_id).parallelism):
                mine = [m for m in members if self._is_mine(m, idx)]
                if not mine:
                    continue
                if len(mine) == len(members):
                    build_subtask(members, idx)
                else:
                    # split assignment across workers (the controller's
                    # slot packing never produces this, but defensively):
                    # run each local member unchained so cross-worker
                    # member edges ride the data plane
                    for m in mine:
                        build_subtask([m], idx)

        for handle in self.subtasks.values():
            handle.task = asyncio.ensure_future(handle.runner.start())
        if prof is not None:
            # event-loop stall watchdog: one ticker per loop (idempotent),
            # sampler thread started lazily; the task dies with its loop
            prof.watchdog.ensure_ticker()
        return RunningEngine(self)


class RunningEngine:
    """Control handles over a started engine (engine.rs:720-811)."""

    def __init__(self, engine: Engine):
        self.engine = engine

    def source_controls(self) -> List[asyncio.Queue]:
        return [h.control_tx for h in self.engine.subtasks.values() if h.is_source]

    def operator_controls(self) -> Dict[str, List[asyncio.Queue]]:
        """Per-operator control queues; every member of a chained task
        maps to its runner's queue, so operator-addressed control
        (compaction hot-swaps) still reaches fused operators."""
        out: Dict[str, List[asyncio.Queue]] = {}
        for (op_id, _), h in sorted(self.engine.subtasks.items()):
            for m in (h.member_ids or [op_id]):
                out.setdefault(m, []).append(h.control_tx)
        return out

    def sink_controls(self) -> List[asyncio.Queue]:
        sink_ids = {n.operator_id for n in self.engine.program.sinks()}
        return [h.control_tx for (op_id, _), h in self.engine.subtasks.items()
                if op_id in sink_ids]

    async def checkpoint(self, epoch: int, min_epoch: int = 0,
                         then_stop: bool = False) -> None:
        """Inject a barrier at all sources (§3.3: barriers enter at sources)."""
        barrier = CheckpointBarrier(epoch, min_epoch, now_micros(), then_stop)
        for q in self.source_controls():
            await q.put(ControlMessage.checkpoint(barrier))

    async def wait_for_checkpoint(self, epoch: int,
                                  timeout: float = 30.0) -> bool:
        """Block until every subtask reported checkpoint_completed for
        ``epoch`` — only then is the epoch restorable (the reference's
        controller CheckpointState aggregation, checkpointer.rs:186-410).
        Returns False on timeout."""
        import time as _time

        # one completion per (member operator, subtask index): a chained
        # runner reports each member separately, so counting runners
        # would return before unrelated tasks (e.g. the source) finished
        expected = {(m, idx) for (op, idx), h in self.engine.subtasks.items()
                    for m in (h.member_ids or [op])}
        deadline = _time.monotonic() + timeout
        done = {(r.operator_id, r.task_index) for r in self.engine.resps
                if r.kind == "checkpoint_completed"
                and r.subtask_metadata.epoch == epoch}
        while not expected <= done:
            remain = deadline - _time.monotonic()
            if remain <= 0:
                return False
            try:
                resp = await asyncio.wait_for(
                    self.engine.control_resp.get(),
                    timeout=min(remain, 0.25))
            except asyncio.TimeoutError:
                # a barrier that raced a draining bounded stream can
                # never seal once every subtask has exited — bail
                # immediately instead of sitting the full deadline on a
                # queue nobody will ever write to (measured: six fuzz
                # restore tests each burned the whole 30s here)
                if self.engine.control_resp.empty() and all(
                        h.task is None or h.task.done()
                        for h in self.engine.subtasks.values()):
                    return False
                continue
            self.engine.resps.append(resp)
            if (resp.kind == "checkpoint_completed"
                    and resp.subtask_metadata.epoch == epoch):
                done.add((resp.operator_id, resp.task_index))
        return True

    async def stop(self, mode: StopMode = StopMode.GRACEFUL) -> None:
        if mode == StopMode.IMMEDIATE:
            # kill-style stop reaches every subtask directly (the reference's
            # recovering path SIGKILLs workers; in-process we signal all loops)
            for h in self.engine.subtasks.values():
                await h.control_tx.put(ControlMessage.stop(mode))
        else:
            for q in self.source_controls():
                await q.put(ControlMessage.stop(mode))

    async def commit(self, epoch: int) -> None:
        for q in self.sink_controls():
            await q.put(ControlMessage.commit(epoch))

    async def load_compacted(self, operator_id: str, payload) -> None:
        """Deliver a compaction hot-swap notice to one operator's subtasks."""
        for q in self.operator_controls().get(operator_id, []):
            await q.put(ControlMessage("load_compacted", compacted=payload))

    async def join(self) -> List[ControlResp]:
        """Wait for all subtasks to finish; drain + return control responses."""
        tasks = [h.task for h in self.engine.subtasks.values() if h.task]
        await asyncio.gather(*tasks, return_exceptions=True)
        resps: List[ControlResp] = self.engine.resps
        while not self.engine.control_resp.empty():
            resps.append(self.engine.control_resp.get_nowait())
        failures = [r for r in resps if r.kind == "task_failed"]
        if failures:
            raise RuntimeError(
                f"{len(failures)} task(s) failed: "
                + "; ".join(f"{f.operator_id}-{f.task_index}: {f.error}"
                            for f in failures[:5]))
        return resps


class LocalRunner:
    """Run a bounded pipeline to completion in-process
    (``LocalRunner``, arroyo-worker/src/lib.rs:213-250)."""

    def __init__(self, program: Program, job_id: str = "local-job",
                 checkpoint_url: Optional[str] = None,
                 restore_epoch: Optional[int] = None):
        self.engine = Engine.for_local(program, job_id,
                                       checkpoint_url=checkpoint_url,
                                       restore_epoch=restore_epoch)

    async def run_async(self, checkpoint_interval_secs: Optional[float] = None
                        ) -> List[ControlResp]:
        running = self.engine.start()
        epoch = [self.engine.restore_epoch or 0]
        ticker: Optional[asyncio.Task] = None
        if checkpoint_interval_secs:
            async def tick():
                while True:
                    await asyncio.sleep(checkpoint_interval_secs)
                    epoch[0] += 1
                    e = epoch[0]
                    await running.checkpoint(e)
                    # act as the mini-controller: once the epoch is sealed,
                    # drive the commit phase so two-phase sinks finalize
                    if await running.wait_for_checkpoint(e):
                        await running.commit(e)

            ticker = asyncio.ensure_future(tick())
        try:
            return await running.join()
        finally:
            if ticker:
                ticker.cancel()

    def run(self, checkpoint_interval_secs: Optional[float] = None
            ) -> List[ControlResp]:
        return asyncio.run(self.run_async(checkpoint_interval_secs))
