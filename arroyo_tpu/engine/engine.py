"""Engine: physical graph construction and execution.

Analog of /root/reference/arroyo-worker/src/engine.rs: expands the logical
graph by parallelism into subtasks (engine.rs:597-705), wires Forward (1:1)
vs Shuffle (all-to-all) channels, spawns one asyncio task per subtask
(``Engine::start``/``schedule_node``/``run_locally``, engine.rs:813-1102) and
exposes source/operator control handles (``RunningEngine``, engine.rs:720-811).

``Engine.for_local`` + :class:`LocalRunner` reproduce the reference's
in-process multi-task "cluster" (engine.rs:606-619, 837-863): the full
physical graph — all parallel subtasks, real queues, real state — in one
process.  This is the standard test fixture and the single-host execution
mode; multi-host splits this same graph across workers with network channels.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..config import config
from ..graph.logical import EdgeType, Program, StreamNode
from ..state.backend import BackingStore, InMemoryBackend, ParquetBackend
from ..state.store import StateStore
from ..types import (
    CheckpointBarrier,
    ControlMessage,
    ControlResp,
    Message,
    StopMode,
    TaskInfo,
    now_micros,
)
from .build import build_operator
from .context import Collector, Context, OutQueue
from .operator import SourceOperator
from .task import TaskRunner

logger = logging.getLogger(__name__)


@dataclass
class SubtaskHandle:
    task_info: TaskInfo
    runner: TaskRunner
    control_tx: asyncio.Queue  # ControlMessage -> task
    is_source: bool
    task: Optional[asyncio.Task] = None


class Engine:
    def __init__(self, program: Program, job_id: str = "local-job",
                 run_id: str = "0",
                 backend: Optional[BackingStore] = None,
                 restore_epoch: Optional[int] = None,
                 assignments: Optional[Dict[Tuple[str, int], str]] = None,
                 my_worker_id: Optional[str] = None,
                 worker_data_addrs: Optional[Dict[str, str]] = None,
                 network: Optional[Any] = None):
        """``assignments`` maps (operator_id, subtask_idx) -> worker_id; when
        given with ``my_worker_id``, only this worker's subtasks are built and
        cross-worker edges ride the network data plane (``network`` must be a
        NetworkManager, ``worker_data_addrs`` maps worker_id -> host:port)."""
        errors = program.validate()
        if errors:
            raise ValueError("; ".join(errors))
        # full plan-time validation (analysis.plan_validator): keyed
        # state behind shuffles, join key schemas, dangling nodes —
        # reject before any operator is built
        from .build import validate_before_build

        validate_before_build(program)
        self.program = program
        self.job_id = job_id
        self.run_id = run_id
        self.backend = backend if backend is not None else InMemoryBackend()
        self.restore_epoch = restore_epoch
        self.assignments = assignments
        self.my_worker_id = my_worker_id
        self.worker_data_addrs = worker_data_addrs or {}
        self.network = network
        self.control_resp: asyncio.Queue = asyncio.Queue()
        self.subtasks: Dict[Tuple[str, int], SubtaskHandle] = {}
        self.resps: List[ControlResp] = []  # responses drained so far

    def _is_mine(self, op_id: str, idx: int) -> bool:
        if self.assignments is None:
            return True
        return self.assignments.get((op_id, idx)) == self.my_worker_id

    def _worker_of(self, op_id: str, idx: int) -> Optional[str]:
        if self.assignments is None:
            return None
        return self.assignments.get((op_id, idx))

    @staticmethod
    def for_local(program: Program, job_id: str = "local-job",
                  checkpoint_url: Optional[str] = None,
                  restore_epoch: Optional[int] = None) -> "Engine":
        backend: BackingStore
        if checkpoint_url:
            backend = ParquetBackend.for_url(checkpoint_url)
        else:
            backend = InMemoryBackend()
        return Engine(program, job_id, backend=backend, restore_epoch=restore_epoch)

    # ------------------------------------------------------------------

    def start(self) -> "RunningEngine":
        """Build the physical graph and spawn all subtask loops."""
        g = self.program.graph
        # queues[(src_id, src_idx, dst_id, dst_idx)] — the reference's Quad
        queues: Dict[Tuple[str, int, str, int], asyncio.Queue] = {}
        qsize = config().queue_size

        def queue_for(quad: Tuple[str, int, str, int]) -> asyncio.Queue:
            if quad not in queues:
                queues[quad] = asyncio.Queue(maxsize=qsize)
            return queues[quad]

        def out_queue(quad: Tuple[str, int, str, int]) -> OutQueue:
            """Local queue or remote network sender for an outgoing edge."""
            _, _, dst_op, dst_idx = quad
            w = self._worker_of(dst_op, dst_idx)
            if w is None or w == self.my_worker_id:
                return OutQueue(queue_for(quad))
            addr = self.worker_data_addrs[w]
            return OutQueue(sender=self.network.remote_sender(addr, quad))

        def in_queue(quad: Tuple[str, int, str, int]) -> asyncio.Queue:
            """Local queue for an incoming edge; remote sources are demuxed
            into it by the network listener."""
            src_op, src_idx, _, _ = quad
            q = queue_for(quad)
            w = self._worker_of(src_op, src_idx)
            if w is not None and w != self.my_worker_id:
                self.network.register_in_edge(quad, q)
            return q

        # construct subtasks in topo order
        for op_id in self.program.topo_order():
            node: StreamNode = self.program.node(op_id)
            parallelism = node.parallelism
            out_edges = list(g.out_edges(op_id, data=True))
            in_edges = list(g.in_edges(op_id, data=True))

            for idx in range(parallelism):
                if not self._is_mine(op_id, idx):
                    continue
                task_info = TaskInfo(self.job_id, op_id, node.operator.name,
                                     idx, parallelism)

                # output edge groups (one group per downstream operator)
                edge_groups: List[List[OutQueue]] = []
                for _, dst, data in out_edges:
                    dst_par = self.program.node(dst).parallelism
                    typ: EdgeType = data["edge"].typ
                    if typ == EdgeType.FORWARD:
                        # equal parallelism: 1:1 chain; mismatched: rebalance —
                        # fan-in (src i -> dst i % dst_par) or fan-out
                        # (src i -> every dst j with j % src_par == i,
                        # round-robined per batch by the Collector)
                        if dst_par > parallelism:
                            group = [out_queue((op_id, idx, dst, j))
                                     for j in range(dst_par)
                                     if j % parallelism == idx]
                        else:
                            group = [out_queue((op_id, idx, dst,
                                                idx % dst_par))]
                    else:
                        group = [out_queue((op_id, idx, dst, j))
                                 for j in range(dst_par)]
                    edge_groups.append(group)

                # input channels: (side, queue) per upstream subtask
                inputs: List[Tuple[int, asyncio.Queue]] = []
                for src, _, data in sorted(
                        in_edges, key=lambda e: e[2]["edge"].typ.value):
                    src_par = self.program.node(src).parallelism
                    typ = data["edge"].typ
                    side = 1 if typ == EdgeType.SHUFFLE_JOIN_RIGHT else 0
                    if typ == EdgeType.FORWARD:
                        if parallelism > src_par:
                            inputs.append((side, in_queue(
                                (src, idx % src_par, op_id, idx))))
                        else:
                            for j in range(src_par):
                                if j % parallelism == idx:
                                    inputs.append((side, in_queue((src, j, op_id, idx))))
                    else:
                        for j in range(src_par):
                            inputs.append((side, in_queue((src, j, op_id, idx))))

                operator = build_operator(node.operator)
                store = StateStore(task_info, self.backend, self.restore_epoch)
                restore_wm = store.restore_watermark() if self.restore_epoch else None
                from ..obs.metrics import TaskMetrics

                metrics = TaskMetrics(task_info)
                ctx = Context(task_info, Collector(edge_groups, metrics),
                              n_inputs=len(inputs), state_store=store,
                              control_tx=self.control_resp,
                              restore_watermark=restore_wm,
                              metrics=metrics)
                control_rx: asyncio.Queue = asyncio.Queue()
                runner = TaskRunner(task_info, operator, ctx, inputs,
                                    control_rx, self.control_resp)
                ctx._runner = runner  # sources poll control via the runner
                self.subtasks[(op_id, idx)] = SubtaskHandle(
                    task_info, runner, control_rx,
                    isinstance(operator, SourceOperator))

        for handle in self.subtasks.values():
            handle.task = asyncio.ensure_future(handle.runner.start())
        return RunningEngine(self)


class RunningEngine:
    """Control handles over a started engine (engine.rs:720-811)."""

    def __init__(self, engine: Engine):
        self.engine = engine

    def source_controls(self) -> List[asyncio.Queue]:
        return [h.control_tx for h in self.engine.subtasks.values() if h.is_source]

    def operator_controls(self) -> Dict[str, List[asyncio.Queue]]:
        out: Dict[str, List[asyncio.Queue]] = {}
        for (op_id, _), h in sorted(self.engine.subtasks.items()):
            out.setdefault(op_id, []).append(h.control_tx)
        return out

    def sink_controls(self) -> List[asyncio.Queue]:
        sink_ids = {n.operator_id for n in self.engine.program.sinks()}
        return [h.control_tx for (op_id, _), h in self.engine.subtasks.items()
                if op_id in sink_ids]

    async def checkpoint(self, epoch: int, min_epoch: int = 0,
                         then_stop: bool = False) -> None:
        """Inject a barrier at all sources (§3.3: barriers enter at sources)."""
        barrier = CheckpointBarrier(epoch, min_epoch, now_micros(), then_stop)
        for q in self.source_controls():
            await q.put(ControlMessage.checkpoint(barrier))

    async def wait_for_checkpoint(self, epoch: int,
                                  timeout: float = 30.0) -> bool:
        """Block until every subtask reported checkpoint_completed for
        ``epoch`` — only then is the epoch restorable (the reference's
        controller CheckpointState aggregation, checkpointer.rs:186-410).
        Returns False on timeout."""
        import time as _time

        n_subtasks = len(self.engine.subtasks)
        deadline = _time.monotonic() + timeout
        count = sum(1 for r in self.engine.resps
                    if r.kind == "checkpoint_completed"
                    and r.subtask_metadata.epoch == epoch)
        while count < n_subtasks:
            remain = deadline - _time.monotonic()
            if remain <= 0:
                return False
            try:
                resp = await asyncio.wait_for(
                    self.engine.control_resp.get(), timeout=remain)
            except asyncio.TimeoutError:
                return False
            self.engine.resps.append(resp)
            if (resp.kind == "checkpoint_completed"
                    and resp.subtask_metadata.epoch == epoch):
                count += 1
        return True

    async def stop(self, mode: StopMode = StopMode.GRACEFUL) -> None:
        if mode == StopMode.IMMEDIATE:
            # kill-style stop reaches every subtask directly (the reference's
            # recovering path SIGKILLs workers; in-process we signal all loops)
            for h in self.engine.subtasks.values():
                await h.control_tx.put(ControlMessage.stop(mode))
        else:
            for q in self.source_controls():
                await q.put(ControlMessage.stop(mode))

    async def commit(self, epoch: int) -> None:
        for q in self.sink_controls():
            await q.put(ControlMessage.commit(epoch))

    async def load_compacted(self, operator_id: str, payload) -> None:
        """Deliver a compaction hot-swap notice to one operator's subtasks."""
        for q in self.operator_controls().get(operator_id, []):
            await q.put(ControlMessage("load_compacted", compacted=payload))

    async def join(self) -> List[ControlResp]:
        """Wait for all subtasks to finish; drain + return control responses."""
        tasks = [h.task for h in self.engine.subtasks.values() if h.task]
        await asyncio.gather(*tasks, return_exceptions=True)
        resps: List[ControlResp] = self.engine.resps
        while not self.engine.control_resp.empty():
            resps.append(self.engine.control_resp.get_nowait())
        failures = [r for r in resps if r.kind == "task_failed"]
        if failures:
            raise RuntimeError(
                f"{len(failures)} task(s) failed: "
                + "; ".join(f"{f.operator_id}-{f.task_index}: {f.error}"
                            for f in failures[:5]))
        return resps


class LocalRunner:
    """Run a bounded pipeline to completion in-process
    (``LocalRunner``, arroyo-worker/src/lib.rs:213-250)."""

    def __init__(self, program: Program, job_id: str = "local-job",
                 checkpoint_url: Optional[str] = None,
                 restore_epoch: Optional[int] = None):
        self.engine = Engine.for_local(program, job_id,
                                       checkpoint_url=checkpoint_url,
                                       restore_epoch=restore_epoch)

    async def run_async(self, checkpoint_interval_secs: Optional[float] = None
                        ) -> List[ControlResp]:
        running = self.engine.start()
        epoch = [self.engine.restore_epoch or 0]
        ticker: Optional[asyncio.Task] = None
        if checkpoint_interval_secs:
            async def tick():
                while True:
                    await asyncio.sleep(checkpoint_interval_secs)
                    epoch[0] += 1
                    e = epoch[0]
                    await running.checkpoint(e)
                    # act as the mini-controller: once the epoch is sealed,
                    # drive the commit phase so two-phase sinks finalize
                    if await running.wait_for_checkpoint(e):
                        await running.commit(e)

            ticker = asyncio.ensure_future(tick())
        try:
            return await running.join()
        finally:
            if ticker:
                ticker.cancel()

    def run(self, checkpoint_interval_secs: Optional[float] = None
            ) -> List[ControlResp]:
        return asyncio.run(self.run_async(checkpoint_interval_secs))
