"""Adaptive micro-batch coalescing at task inputs.

The TPU microbenches show per-dispatch overhead (~0.26 ms through the
tunnel) and tiny-batch padding dominating steady-state cost: a stream of
sub-``target_batch_size`` batches pays one kernel dispatch, one padding
pass and one queue hop *per fragment*.  The coalescer merges consecutive
RECORD batches arriving at a task (chain) input into one batch before
the operator sees them, amortizing dispatch and killing shape-churn
recompiles.

Ordering guarantees (the invariants the tests pin):

* a buffered batch is **never reordered past a watermark, barrier or
  end-of-stream marker** — the task loop flushes all buffers before
  handling any non-record message;
* batches only merge within one input *side* (join sides never mix) and
  only while schema/key layout match — a mismatch flushes the old
  buffer first;
* a buffer never outlives the **linger bound**: the first buffered
  fragment starts a deadline, and the task loop flushes on expiry even
  if the target size was never reached.

``ARROYO_COALESCE=0`` disables coalescing entirely; ``COALESCE_TARGET``
(default: ``target_batch_size``) and ``COALESCE_LINGER_MICROS`` bound
size and added latency.
"""

from __future__ import annotations

import os
import time as _time
from typing import Any, Dict, List, Optional, Tuple

from ..types import Batch


def coalescing_enabled() -> bool:
    """``ARROYO_COALESCE=0`` is the escape hatch (read per call so tests
    can toggle without a config reset)."""
    return os.environ.get("ARROYO_COALESCE", "1") not in ("0", "off",
                                                          "false")


def _signature(batch: Batch) -> Tuple:
    """Concat compatibility key: column names, key columns, and whether
    a key hash rides along.  Dtypes are left out — numpy concat promotes
    them, which is exactly what an un-coalesced downstream would see
    across successive batches anyway."""
    return (tuple(batch.columns.keys()), batch.key_cols,
            batch.key_hash is not None)


class _SideBuffer:
    __slots__ = ("sig", "batches", "rows")

    def __init__(self, sig: Tuple, batch: Batch):
        self.sig = sig
        self.batches: List[Batch] = [batch]
        self.rows = len(batch)


class BatchCoalescer:
    """Per-side accumulation of record batches up to ``target`` rows
    within a ``linger`` deadline.  The task loop drives it: ``add``
    returns any batches that became ready, ``flush_all`` drains before
    control messages / on linger expiry."""

    def __init__(self, target: int, linger_secs: float,
                 histogram: Optional[Any] = None,
                 prof: Optional[Any] = None, prof_op: str = ""):
        self.target = max(int(target), 1)
        self.linger = max(float(linger_secs), 0.0)
        self.histogram = histogram  # batches merged per flush
        # phase profiler (obs/profiler.py): None unless armed — the
        # merge concat is then charged to the `coalesce_merge` phase
        self.prof = prof
        self.prof_op = prof_op
        self._bufs: Dict[int, _SideBuffer] = {}  # side -> buffer (ordered)
        self._deadline: Optional[float] = None

    @property
    def pending(self) -> bool:
        return bool(self._bufs)

    @property
    def deadline(self) -> Optional[float]:
        """Monotonic time by which pending buffers must flush."""
        return self._deadline

    def _merge(self, buf: _SideBuffer) -> Batch:
        if self.histogram is not None:
            self.histogram.observe(len(buf.batches))
        if len(buf.batches) == 1:
            return buf.batches[0]
        if self.prof is None:
            return Batch.concat(buf.batches)
        frame = self.prof.begin(self.prof_op, "coalesce_merge")
        try:
            return Batch.concat(buf.batches)
        finally:
            self.prof.end(frame)

    def add(self, side: int, batch: Batch) -> List[Tuple[int, Batch]]:
        """Buffer one incoming batch; returns ``[(side, merged_batch)]``
        for anything that became ready to process (a schema change can
        release the previous buffer AND the new batch in one call)."""
        out: List[Tuple[int, Batch]] = []
        if len(batch) == 0:
            return out  # empty fragments carry nothing to merge
        sig = _signature(batch)
        buf = self._bufs.get(side)
        if buf is not None and buf.sig != sig:
            # incompatible layout: release the old run first, in order
            out.append((side, self._merge(buf)))
            del self._bufs[side]
            buf = None
        if buf is None:
            if len(batch) >= self.target:
                # already at target: pass through, no copy, no linger
                if self.histogram is not None:
                    self.histogram.observe(1)
                out.append((side, batch))
                self._retime()
                return out
            self._bufs[side] = _SideBuffer(sig, batch)
            if self._deadline is None:
                self._deadline = _time.monotonic() + self.linger
            return out
        buf.batches.append(batch)
        buf.rows += len(batch)
        if buf.rows >= self.target:
            out.append((side, self._merge(buf)))
            del self._bufs[side]
            self._retime()
        return out

    def flush_all(self) -> List[Tuple[int, Batch]]:
        """Drain every buffer in arrival order (called before any
        watermark/barrier/end handling and on linger expiry)."""
        out = [(side, self._merge(buf)) for side, buf in self._bufs.items()]
        self._bufs.clear()
        self._deadline = None
        return out

    def _retime(self) -> None:
        if not self._bufs:
            self._deadline = None


class SourceBatcher:
    """Source-boundary coalescing of raw connector fragments.

    Connectors that read small fragments (kafka partition fetches,
    kinesis shard reads, HTTP polls) historically decoded and emitted
    each fragment as its own Batch — one format decode, one collect and
    one downstream envelope per fragment.  The batcher accumulates raw
    payloads *before* decode and hands the engine one target-size batch:
    decode amortizes (the vectorized formats fast path parses the whole
    run in one pass) and the per-batch dispatch envelope is paid once.

    Exactly-once contract: connectors record their resume positions
    (offsets / sequence numbers) at fetch time, so buffered payloads
    must be flushed downstream **before** any checkpoint snapshots that
    state and before the source returns — otherwise a restore would
    skip them.  The TaskRunner guarantees this by awaiting the source's
    ``flush_pending`` before handling a checkpoint barrier or stop, and
    after the source loop returns; connectors additionally flush on
    linger expiry (``maybe_flush``) so a sub-target trickle still
    emits within the bounded latency.
    """

    def __init__(self, ctx: Any, decode: Any, target: int,
                 linger_secs: Optional[float] = None,
                 prof_op: str = "", batch_always: bool = False):
        from ..config import config
        from ..obs import profiler

        self.ctx = ctx
        self.decode = decode  # payload list -> Batch
        cfg = config()
        self.target = max(int(target or cfg.coalesce_target
                              or cfg.target_batch_size), 1)
        self.linger = (cfg.coalesce_linger_micros / 1e6
                       if linger_secs is None else max(linger_secs, 0.0))
        self.prof = profiler.active()
        self.prof_op = prof_op
        # batch_always: the connector assembled target-size batches
        # itself BEFORE this PR (e.g. the SSE event buffer), so target
        # batching must survive ARROYO_COALESCE=0 — the escape disables
        # only the linger, restoring the pre-coalescer behavior instead
        # of regressing to one decode+collect per fragment
        self.batch_always = batch_always
        self._payloads: List[Any] = []
        self._deadline: Optional[float] = None

    @property
    def pending(self) -> bool:
        return bool(self._payloads)

    @property
    def expired(self) -> bool:
        return (self._deadline is not None
                and _time.monotonic() >= self._deadline)

    async def add(self, payloads: List[Any]) -> None:
        """Buffer one fragment's payloads; decodes + emits when the
        target size is reached (coalescing is buffering-only: enabled/
        disabled emits the same rows in the same order)."""
        if not payloads:
            return
        coalescing = coalescing_enabled()
        if not coalescing and not self.batch_always:
            await self._emit(list(payloads))
            return
        self._payloads.extend(payloads)
        if len(self._payloads) >= self.target:
            await self.flush()
        elif coalescing and self._deadline is None:
            # batch_always without coalescing: no linger deadline — the
            # buffer flushes at target size and at the runner's
            # checkpoint/stop/end boundaries, as pre-coalescer
            self._deadline = _time.monotonic() + self.linger

    async def maybe_flush(self) -> None:
        """Flush iff the linger deadline passed (called once per source
        poll round)."""
        if self.expired:
            await self.flush()

    async def flush(self) -> None:
        """Decode and emit everything buffered (called by the source on
        linger expiry and by the TaskRunner before checkpoints/stop)."""
        payloads, self._payloads = self._payloads, []
        self._deadline = None
        if payloads:
            await self._emit(payloads)

    async def _emit(self, payloads: List[Any]) -> None:
        if self.prof is None:
            batch = self.decode(payloads)
        else:
            frame = self.prof.begin(self.prof_op, "source_decode")
            try:
                batch = self.decode(payloads)
            finally:
                self.prof.end(frame)
        if batch is not None and len(batch):
            from ..obs import latency as _latency

            lat = _latency.active()
            if lat is not None:
                # latency sampling at the source boundary: stamp the
                # batch carrying the next 1-in-N sampled record with its
                # ingest wall-clock (side-channel annotation — the
                # schema signature above never sees it)
                stamp = lat.source_stamp(self.prof_op or "source",
                                         len(batch))
                if stamp is not None:
                    batch.lat_stamp = stamp
            await self.ctx.collect(batch)
