"""Task execution context: queues, watermark tracking, barrier alignment,
collection/partitioning, timers.

Analog of the reference's ``arroyo-worker/src/engine.rs`` context layer:
``WatermarkHolder`` (engine.rs:73-126), ``Collector::collect`` hash-partitioned
fan-out (engine.rs:183-240), ``CheckpointCounter`` (engine.rs:436-479),
``Context`` (engine.rs:128-427) and the timer table (engine.rs:252-259,
353-390) — re-shaped for batches: the collector partitions a whole columnar
batch by vectorized key-range routing instead of hashing one record at a time.
"""

from __future__ import annotations

import asyncio
import heapq
import time as _time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..types import (
    Batch,
    CheckpointBarrier,
    ControlMessage,
    ControlResp,
    Message,
    MessageKind,
    TaskInfo,
    Watermark,
    WatermarkKind,
    server_for_hash_array,
    MAX_TIMESTAMP,
)
from ..config import config


class WatermarkHolder:
    """Tracks the current watermark as the min across all inputs, with Idle
    inputs excluded (engine.rs:73-126).  Returns the effective watermark or
    None when no input has reported yet."""

    def __init__(self, n_inputs: int):
        self.watermarks: List[Optional[Watermark]] = [None] * n_inputs

    def set(self, idx: int, wm: Watermark) -> Optional[int]:
        """Record input ``idx``'s watermark; return the new combined event-time
        watermark (micros) if one is defined."""
        self.watermarks[idx] = wm
        return self.value()

    def value(self) -> Optional[int]:
        mins: List[int] = []
        for w in self.watermarks:
            if w is None:
                return None  # an input has never reported: undefined
            if not w.is_idle:
                mins.append(w.time)
        if not mins:
            return None  # all inputs idle: no event-time watermark
        return min(mins)

    def all_idle(self) -> bool:
        return all(w is not None and w.is_idle for w in self.watermarks)


class CheckpointCounter:
    """Barrier alignment across inputs (engine.rs:436-479): counts barriers
    per epoch; an input that delivered its barrier is 'blocked' until all
    inputs align.  Inputs that end (Stop/EndOfData) are excluded from
    alignment so a finished source doesn't deadlock checkpoints."""

    def __init__(self, n_inputs: int):
        self.n_inputs = n_inputs
        self.seen: Dict[int, set] = {}
        self.closed: set = set()

    def _aligned(self, epoch: int) -> bool:
        return len(self.seen.get(epoch, set()) | self.closed) >= self.n_inputs

    def observe(self, idx: int, epoch: int) -> bool:
        """Record barrier from input ``idx``; True when all live inputs aligned."""
        self.seen.setdefault(epoch, set()).add(idx)
        if self._aligned(epoch):
            del self.seen[epoch]
            return True
        return False

    def mark_closed(self, idx: int) -> List[int]:
        """Input ended: exclude it from alignment; returns epochs that are now
        complete (in order) so pending checkpoints can proceed."""
        self.closed.add(idx)
        ready = sorted(e for e in self.seen if self._aligned(e))
        for e in ready:
            del self.seen[e]
        return ready


@dataclass(order=True)
class _Timer:
    time: int
    key: Any = field(compare=False)
    payload: Any = field(compare=False)


class TimerHeap:
    """Host-side event-time timer service (the reference stores timers in a
    reserved TimeKeyMap table '[' — engine.rs:252-259; here a heap suffices
    since timers are snapshot into checkpoints explicitly)."""

    def __init__(self) -> None:
        self._heap: List[_Timer] = []
        self._set: Dict[Any, int] = {}

    def schedule(self, time: int, key: Any, payload: Any = None) -> None:
        prev = self._set.get(key)
        if prev is not None and prev <= time:
            return  # keep earliest
        self._set[key] = time
        heapq.heappush(self._heap, _Timer(int(time), key, payload))

    def cancel(self, key: Any) -> None:
        self._set.pop(key, None)

    def fire(self, watermark: int) -> List[Tuple[int, Any, Any]]:
        """Pop all timers with time <= watermark, in time order."""
        fired = []
        while self._heap and self._heap[0].time <= watermark:
            t = heapq.heappop(self._heap)
            if self._set.get(t.key) == t.time:
                del self._set[t.key]
                fired.append((t.time, t.key, t.payload))
        return fired

    def snapshot(self) -> List[Tuple[int, Any, Any]]:
        return [(t.time, t.key, t.payload) for t in self._heap
                if self._set.get(t.key) == t.time]

    def restore(self, entries: Sequence[Tuple[int, Any, Any]]) -> None:
        for time, key, payload in entries:
            self.schedule(time, key, payload)

    def __len__(self) -> int:
        return len(self._set)


class OutQueue:
    """One outgoing edge endpoint to a specific downstream subtask
    (engine.rs:141-170).  In-process: an asyncio.Queue of Message objects (no
    serialization, like the reference's local edges); remote edges wrap a
    network sender with the same interface."""

    def __init__(self, queue: Optional[asyncio.Queue] = None,
                 sender: Optional[Callable] = None):
        self.queue = queue if queue is not None else (
            asyncio.Queue(maxsize=config().queue_size) if sender is None else None)
        self.sender = sender

    async def send(self, msg: Message) -> None:
        if self.sender is not None:
            await self.sender(msg)
        else:
            await self.queue.put(msg)


class Collector:
    """Hash-partitioned fan-out of output batches (engine.rs:183-240).

    ``out_edges`` is a list of edge groups; each group is the full set of
    downstream subtask queues for one downstream operator.  Forward edges have
    exactly one queue in the group (1:1); shuffle edges have one queue per
    downstream subtask and batches are split by vectorized
    ``server_for_hash`` routing on key_hash — or, when every destination
    is co-located and the device shuffle is enabled
    (``parallel/shuffle.py``), by ONE on-device ``all_to_all`` exchange
    whose per-destination routing is bit-identical to the host path.
    """

    def __init__(self, edge_groups: List[List[OutQueue]],
                 metrics: Optional[Any] = None, op_id: str = "",
                 sanitizer: Optional[Any] = None, subtask: int = 0):
        from ..obs import profiler

        self.edge_groups = edge_groups
        self.metrics = metrics
        self.op_id = op_id
        # arroyosan: per-edge output-sharding stability (None unless
        # armed — the hook is one `is not None` test per shuffle batch).
        # The edge key carries the subtask index: stability is per
        # PRODUCING subtask (two subtasks may legitimately decide the
        # sticky device/host route differently if their data differs).
        self.sanitizer = sanitizer
        self.subtask = subtask
        # phase profiler: None unless armed at engine build — partition/
        # route CPU is then charged to `shuffle_prep`, enqueue awaits to
        # the overlapping `send_wait` (backpressure) wait phase
        self.prof = profiler.active()
        self._rr = [0] * len(edge_groups)  # round-robin cursor per group
        self._local_qs = [q.queue for g in edge_groups for q in g
                          if q.queue is not None]
        # lazily-decided per shuffle group: a DeviceShuffle when the
        # group is co-located (all local queues) and the device path is
        # enabled; None pins the host route for the edge's life
        self._dev_shuffle: Dict[int, Optional[Any]] = {}

    def _device_shuffle_for(self, gi: int, n: int) -> Optional[Any]:
        ds = self._dev_shuffle.get(gi, False)
        if ds is not False:
            return ds
        ds = None
        from ..parallel import shuffle as _shuffle

        if (_shuffle.device_shuffle_enabled(n)
                and all(q.queue is not None for q in self.edge_groups[gi])):
            ds = _shuffle.DeviceShuffle(n, op_id=self.op_id)
        self._dev_shuffle[gi] = ds
        return ds

    def _update_queue_gauges(self) -> None:
        # backpressure visibility (engine.rs QueueSizes -> prometheus
        # gauges the console graphs): capacity and remaining slots across
        # this subtask's outbound queues
        qs = self._local_qs
        if qs:
            self.metrics.tx_queue_size.set(sum(q.maxsize for q in qs))
            self.metrics.tx_queue_rem.set(
                sum(max(q.maxsize - q.qsize(), 0) for q in qs))

    async def collect(self, batch: Batch) -> None:
        if len(batch) == 0:
            return
        blocked = 0.0
        send = None
        prof = self.prof
        if self.metrics is not None or prof is not None:
            if self.metrics is not None:
                self.metrics.messages_sent.inc(len(batch))
                self._update_queue_gauges()

            async def send(q, msg):
                # time only the enqueue await: a full downstream queue
                # parks the coroutine here, so the accumulated wait is
                # genuine backpressure — the partition/select CPU between
                # sends is this operator's own fan-out cost, not a
                # consumer stall.  Metrics-off/profiler-off runs keep
                # the direct q.send awaits below: no closure, no clocks.
                # With the profiler armed the await is a `send_wait`
                # wait child, so the enclosing shuffle_prep/proc work
                # phases stay exclusive of any task interleaved here
                nonlocal blocked
                frame = (prof.begin(self.op_id, "send_wait", wait=True)
                         if prof is not None else None)
                t0 = _time.perf_counter()
                try:
                    await q.send(msg)
                finally:
                    if frame is not None:
                        prof.end(frame)
                blocked += _time.perf_counter() - t0

        pframe = (prof.begin(self.op_id, "shuffle_prep")
                  if prof is not None else None)
        try:
            for gi, group in enumerate(self.edge_groups):
                n = len(group)
                if n == 1:
                    q, m = group[0], Message.record(batch)
                    await (send(q, m) if send else q.send(m))
                elif batch.key_hash is None:
                    # unkeyed fan-out (forward rebalance): round-robin
                    # whole batches
                    q, m = group[self._rr[gi] % n], Message.record(batch)
                    await (send(q, m) if send else q.send(m))
                    self._rr[gi] += 1
                else:
                    ds = self._device_shuffle_for(gi, n)
                    parts = ds.route(batch) if ds is not None else None
                    san = self.sanitizer
                    if san is not None:
                        san.on_sharding(
                            (self.op_id, self.subtask, gi),
                            f"keys@{n}" if parts is not None
                            else f"host@{n}")
                    if parts is not None:
                        # co-located on-device shuffle: the exchange ran
                        # as one all_to_all; destinations receive their
                        # pre-partitioned rows (host order preserved)
                        for i, sub in parts:
                            q = group[i]
                            m = Message.record(sub)
                            await (send(q, m) if send else q.send(m))
                        continue
                    # one O(n) native pass: dest + stable order + bounds
                    from ..native import partition_route
                    from ..obs import perf as _perf
                    from ..parallel.shuffle import HOST_ROUTES

                    _perf.count(HOST_ROUTES)
                    _, order, bounds = partition_route(batch.key_hash, n)
                    for i in range(n):
                        lo, hi = bounds[i], bounds[i + 1]
                        if hi > lo:
                            q = group[i]
                            m = Message.record(batch.select(order[lo:hi]))
                            await (send(q, m) if send else q.send(m))
        finally:
            if pframe is not None:
                prof.end(pframe)
        if blocked > 1e-5 and self.metrics is not None:
            self.metrics.backpressure_time.inc(blocked)

    async def broadcast(self, msg: Message) -> None:
        """Watermarks/barriers/stop go to every downstream subtask."""
        for group in self.edge_groups:
            for q in group:
                await q.send(msg)


class Context:
    """Per-subtask execution context handed to operators (engine.rs:128-427)."""

    def __init__(
        self,
        task_info: TaskInfo,
        collector: Collector,
        n_inputs: int,
        state_store: Any = None,
        control_tx: Optional[asyncio.Queue] = None,
        restore_watermark: Optional[int] = None,
        metrics: Optional[Any] = None,
    ):
        self.task_info = task_info
        self.collector = collector
        self.metrics = metrics if metrics is not None else collector.metrics
        self.watermarks = WatermarkHolder(max(n_inputs, 1))
        self.counter = CheckpointCounter(max(n_inputs, 1))
        self.timers = TimerHeap()
        self.state = state_store
        self.control_tx = control_tx  # ControlResp -> worker control thread
        self.last_watermark: Optional[int] = restore_watermark
        self.n_inputs = n_inputs
        # latency observatory: None unless armed at engine build — the
        # emission/watermark hooks are then one `is not None` test
        from ..obs import latency as _latency

        self.lat = _latency.active()

    # -- emission ----------------------------------------------------------

    async def collect(self, batch: Batch) -> None:
        if self.lat is not None and batch.lat_stamp is None:
            # re-attach the current input batch's stamp to operator-built
            # batches (maps/filters/chain tails rebuild Batch objects
            # without the side-channel annotation); window fires carry
            # their own inherited stamp and skip this
            from ..obs import latency as _latency

            batch.lat_stamp = _latency.current()
        await self.collector.collect(batch)

    async def broadcast(self, msg: Message) -> None:
        await self.collector.broadcast(msg)

    # -- control resp ------------------------------------------------------

    async def report(self, resp: ControlResp) -> None:
        if self.control_tx is not None:
            await self.control_tx.put(resp)

    # -- watermark ---------------------------------------------------------

    def observe_watermark(self, input_idx: int, wm: Watermark) -> Optional[int]:
        """Returns the new combined watermark iff it advanced."""
        combined = self.watermarks.set(input_idx, wm)
        if combined is None:
            return None
        if self.last_watermark is None or combined > self.last_watermark:
            self.last_watermark = combined
            if self.lat is not None:
                # watermark lineage: the age of the watermark this
                # operator just advanced to — a consumer whose age keeps
                # growing relative to its producers is downstream of the
                # held stage
                self.lat.note_edge_watermark(
                    self.task_info.operator_id, combined)
            return combined
        return None

    @staticmethod
    def new_for_test(task_info: Optional[TaskInfo] = None, n_inputs: int = 1
                     ) -> Tuple["Context", asyncio.Queue]:
        """Operator test harness (engine.rs:316-343): a real Context wired to
        an in-memory out queue the test can drain."""
        from ..state.store import StateStore  # local import to avoid cycle

        q: asyncio.Queue = asyncio.Queue(maxsize=10_000)
        out = OutQueue(queue=q)
        ti = task_info or TaskInfo("test-job", "op-0", "test-op", 0, 1)
        store = StateStore.new_in_memory(ti)
        ctx = Context(ti, Collector([[out]]), n_inputs, state_store=store)
        return ctx, q
