"""TaskRunner — the generic per-subtask event loop.

This one class replaces everything the reference's proc-macros generate per
operator (/root/reference/arroyo-macro/src/lib.rs): the tokio task + Context
construction (:568-627), the select! loop with fair input fan-in and
barrier-alignment blocking (:511-566, 414-475), ``handle_control_message``
(:629-704), ``checkpoint()`` (:706-736) and watermark-driven timer firing
(:738-753).

Barrier alignment: when a barrier arrives on one input channel, that channel's
pump parks until barriers have arrived on *all* channels (the reference pushes
the blocked stream aside in InQReader; we park the pump coroutine on an
event), then state snapshots and the barrier is rebroadcast downstream.
"""

from __future__ import annotations

import asyncio
import logging
import time as _time
import traceback
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..obs import perf, profiler, tracing
from ..state.store import StateStore
from ..types import (
    CheckpointBarrier,
    CheckpointEvent,
    CheckpointEventType,
    ControlMessage,
    ControlResp,
    Message,
    MessageKind,
    StopMode,
    TaskInfo,
    Watermark,
    now_micros,
    MAX_TIMESTAMP,
)
from .context import Context
from .operator import Operator, SourceFinishType, SourceOperator

logger = logging.getLogger(__name__)


class _Pump:
    """Forwards one input channel into the merged queue; parks on barriers."""

    def __init__(self, idx: int, side: int, queue: asyncio.Queue,
                 merged: asyncio.Queue):
        self.idx = idx
        self.side = side
        self.queue = queue
        self.merged = merged
        self.resume = asyncio.Event()
        self.task: Optional[asyncio.Task] = None

    async def run(self) -> None:
        while True:
            msg: Message = await self.queue.get()
            await self.merged.put((self.idx, self.side, msg))
            if msg.kind == MessageKind.BARRIER:
                # block this input until alignment completes
                self.resume.clear()
                await self.resume.wait()
            if msg.is_end:
                return


class TaskRunner:
    def __init__(
        self,
        task_info: TaskInfo,
        operator: Operator,
        ctx: Context,
        inputs: List[Tuple[int, asyncio.Queue]],  # (side, queue)
        control_rx: asyncio.Queue,  # ControlMessage from worker
        control_tx: Optional[asyncio.Queue] = None,  # ControlResp to worker
        sanitizer: Optional[Any] = None,  # arroyosan runtime hooks
    ):
        self.task_info = task_info
        self.operator = operator
        # arroyosan (analysis/sanitizer.py): None unless ARROYO_SANITIZE
        # armed it at engine build — every hook site below guards on a
        # local `is not None`, so the disabled path costs nothing
        self.sanitizer = sanitizer
        operator.sanitizer = sanitizer
        self.ctx = ctx
        # a ChainedOperator's runner ctx is the HEAD member's (input
        # alignment, timers); downstream broadcasts (barriers, stop/eod,
        # idle forward) leave from the TAIL member's context
        self.out_ctx: Context = getattr(operator, "tail_ctx", None) or ctx
        self.inputs = inputs
        self.control_rx = control_rx
        self.control_tx = control_tx
        self.merged: asyncio.Queue = asyncio.Queue(maxsize=len(inputs) * 4 + 16)
        # phase profiler (obs/profiler.py): None unless armed at engine
        # build — every hook site guards on a local `is not None`
        self._prof = profiler.active()
        # latency observatory (obs/latency.py): same None-when-disarmed
        # contract.  A terminal task (no outgoing edges) is where sampled
        # stamps are turned into emit-minus-ingest observations; chained
        # terminal tasks observe at the chain-tail feed instead so a
        # window fire inside the chain is measured at its actual
        # emission, not at pane input (engine/chained.py).
        from ..obs import latency as _latency

        self._lat = _latency.active()
        self._lat_terminal = (self._lat is not None
                              and not self.out_ctx.collector.edge_groups
                              and not operator.own_batch_metrics)
        self.pumps: List[_Pump] = []
        self.finished = asyncio.Event()
        self.failed: Optional[BaseException] = None
        self._align_start: Dict[int, float] = {}  # epoch -> trace us

    # ------------------------------------------------------------------

    async def start(self) -> None:
        # kernel-time attribution: every timed_device dispatch inside this
        # coroutine's context accrues to this subtask's counter
        token = perf.set_active_task(
            perf.KernelAccumulator(self.task_info, self.ctx.metrics))
        run_start = tracing.now_us()
        try:
            await self._run()
        except asyncio.CancelledError:
            raise
        except BaseException as e:  # report task failure to the controller
            self.failed = e
            logger.error("task %s failed: %s\n%s", self.task_info.task_id, e,
                         traceback.format_exc())
            await self.ctx.report(ControlResp(
                kind="task_failed", operator_id=self.task_info.operator_id,
                task_index=self.task_info.task_index, error=str(e)))
            # drain downstream so a local run can't deadlock waiting on
            # inputs that will never end (the controller tears the job
            # down in distributed mode; end_of_data is the local analog)
            try:
                await self.out_ctx.broadcast(Message.end_of_data())
            except Exception:
                pass
        finally:
            tracing.record_span(
                "task.run", "task", run_start,
                tracing.now_us() - run_start, tid=self.task_info.task_id,
                args={"failed": self.failed is not None})
            perf.reset_active_task(token)
            self.finished.set()

    async def _run(self) -> None:
        # register tables, restore persisted timers, on_start — per
        # member for chained operators (Operator.open)
        await self.operator.open(self.ctx)
        await self.ctx.report(ControlResp(
            kind="task_started", operator_id=self.task_info.operator_id,
            task_index=self.task_info.task_index))

        if isinstance(self.operator, SourceOperator):
            await self._run_source()
        else:
            await self._run_processor()

        await self.ctx.report(ControlResp(
            kind="task_finished", operator_id=self.task_info.operator_id,
            task_index=self.task_info.task_index))

    # -- source ---------------------------------------------------------

    async def _run_source(self) -> None:
        finish = await self.operator.run(self.ctx)
        # drain the source-side coalescer before any end-of-stream
        # marker: buffered fragments must precede the final watermark /
        # stop downstream (and must be emitted at all — their resume
        # positions are already recorded in source state)
        await self.operator.flush_pending(self.ctx)
        if finish == SourceFinishType.FINAL:
            # final watermark flushes all windows downstream
            await self.out_ctx.broadcast(Message.wm(Watermark.event_time(int(MAX_TIMESTAMP))))
            await self.out_ctx.broadcast(Message.end_of_data())
        elif finish == SourceFinishType.GRACEFUL:
            await self.out_ctx.broadcast(Message.stop())
        else:
            pass  # immediate: just exit

    async def poll_source_control(self) -> Optional[ControlMessage]:
        """Non-blocking control poll used by sources between batches.  Handles
        checkpoint barriers inline (sources are where barriers enter the
        graph); returns Stop messages to the source loop."""
        try:
            cm: ControlMessage = self.control_rx.get_nowait()
        except asyncio.QueueEmpty:
            return None
        if cm.kind == "checkpoint":
            # source-side coalescer ordering: payloads buffered at the
            # source boundary carry resume positions the snapshot below
            # records — they must reach downstream BEFORE the barrier
            await self.operator.flush_pending(self.ctx)
            await self.run_checkpoint(cm.barrier)
            if cm.barrier.then_stop:
                # checkpoint-then-stop (arroyo-types lib.rs:746): the source
                # must stop producing after snapshotting
                return ControlMessage.stop(StopMode.IMMEDIATE)
            return cm
        if cm.kind == "commit":
            await self.operator.handle_commit(cm.epoch, self.ctx)
            return cm
        if cm.kind == "load_compacted":
            await self.operator.handle_load_compacted(cm.compacted, self.ctx)
            return cm
        return cm  # stop etc: source loop decides

    # -- processor -------------------------------------------------------

    async def _run_processor(self) -> None:
        for i, (side, q) in enumerate(self.inputs):
            pump = _Pump(i, side, q, self.merged)
            pump.task = asyncio.ensure_future(pump.run())
            self.pumps.append(pump)

        ended = 0
        stop_mode: Optional[StopMode] = None
        n_inputs = len(self.inputs)
        then_stop = False
        pending_barriers: Dict[int, CheckpointBarrier] = {}
        # persistent futures: recreated only after completion (hot loop —
        # avoids two ensure_future + one cancel per message)
        get_merged: Optional[asyncio.Future] = None
        get_control: Optional[asyncio.Future] = None
        metrics = self.ctx.metrics
        coal = self._make_coalescer()
        san = self.sanitizer
        tid = self.task_info.task_id
        prof = self._prof
        op_id = self.task_info.operator_id
        try:
            while ended < n_inputs:
                if get_merged is None or get_merged.done():
                    get_merged = asyncio.ensure_future(self.merged.get())
                if get_control is None or get_control.done():
                    get_control = asyncio.ensure_future(self.control_rx.get())
                timeout = None
                if coal is not None and coal.pending:
                    # bounded linger: wake up to flush even if no more
                    # input arrives
                    timeout = max(coal.deadline - _time.monotonic(), 0.0)
                wait_t0 = _time.perf_counter()
                done, _ = await asyncio.wait(
                    [get_merged, get_control],
                    return_when=asyncio.FIRST_COMPLETED, timeout=timeout)
                if metrics is not None or prof is not None:
                    # time this loop sat waiting for input (starvation —
                    # the upstream-is-slow half of backpressure analysis)
                    waited = _time.perf_counter() - wait_t0
                    if metrics is not None:
                        metrics.queue_wait.observe(waited)
                    if prof is not None:
                        # a wait bounded by the coalescer's linger
                        # deadline is latency the coalescer added, not
                        # upstream starvation — attribute it apart
                        prof.add(op_id, "coalesce_wait" if timeout
                                 is not None else "queue_wait",
                                 waited, wait=True)
                if (coal is not None and coal.pending
                        and _time.monotonic() >= coal.deadline):
                    # linger expired — flush whether or not new input
                    # arrived (a continuous sub-target trickle must not
                    # defer the flush until the size target is reached)
                    for cside, cbatch in coal.flush_all():
                        await self._process_record(cbatch, cside)
                if not done:
                    continue
                if get_control in done:
                    # arroyolint: disable=async-blocking -- future is in asyncio.wait's done set; .result() cannot block
                    cm = get_control.result()
                    if cm.kind == "commit":
                        await self.operator.handle_commit(cm.epoch, self.ctx)
                    elif cm.kind == "load_compacted":
                        await self.operator.handle_load_compacted(
                            cm.compacted, self.ctx)
                    elif cm.kind == "stop" and cm.stop_mode == StopMode.IMMEDIATE:
                        return
                if get_merged not in done:
                    continue
                # arroyolint: disable=async-blocking -- future is in asyncio.wait's done set; .result() cannot block
                idx, side, msg = get_merged.result()

                if msg.kind == MessageKind.RECORD:
                    if san is not None:
                        san.on_record((tid, idx), msg.batch)
                        san.on_record_during_alignment(tid, idx,
                                                       self.ctx.counter)
                    if metrics is not None:
                        metrics.messages_recv.inc(len(msg.batch))
                    if coal is not None:
                        for cside, cbatch in coal.add(side, msg.batch):
                            await self._process_record(cbatch, cside)
                    else:
                        await self._process_record(msg.batch, side)
                elif msg.kind == MessageKind.WATERMARK:
                    # buffered records arrived BEFORE this watermark on
                    # their channels: flush so they are never reordered
                    # past it (a window could otherwise fire without them)
                    if coal is not None and coal.pending:
                        for cside, cbatch in coal.flush_all():
                            await self._process_record(cbatch, cside)
                    if san is not None:
                        san.before_control(tid, "watermark", coal)
                        san.on_watermark((tid, idx), msg.watermark)
                    advanced = self.ctx.observe_watermark(idx, msg.watermark)
                    if advanced is not None:
                        await self._advance_watermark(advanced)
                    elif (msg.watermark.is_idle
                          and self.ctx.watermarks.all_idle()):
                        await self.out_ctx.broadcast(
                            Message.wm(Watermark.idle()))
                elif msg.kind == MessageKind.BARRIER:
                    # same ordering rule as watermarks: pre-barrier
                    # records must be in operator state before snapshot
                    if coal is not None and coal.pending:
                        for cside, cbatch in coal.flush_all():
                            await self._process_record(cbatch, cside)
                    b = msg.barrier
                    if san is not None:
                        san.before_control(tid, "barrier", coal)
                        san.on_barrier(tid, idx, b.epoch)
                    pending_barriers[b.epoch] = b
                    self._align_start.setdefault(b.epoch, tracing.now_us())
                    await self._report_event(b, CheckpointEventType.STARTED_ALIGNMENT)
                    if self.ctx.counter.observe(idx, b.epoch):
                        del pending_barriers[b.epoch]
                        await self.run_checkpoint(b)
                        for p in self.pumps:
                            p.resume.set()
                        if b.then_stop:
                            then_stop = True
                            break
                elif msg.is_end:
                    if coal is not None and coal.pending:
                        for cside, cbatch in coal.flush_all():
                            await self._process_record(cbatch, cside)
                    if san is not None:
                        san.before_control(tid, "end", coal)
                    ended += 1
                    if msg.kind == MessageKind.STOP:
                        stop_mode = StopMode.GRACEFUL
                    # a finished input can't deliver barriers: re-check
                    # alignment for epochs already in flight
                    for epoch in self.ctx.counter.mark_closed(idx):
                        b = pending_barriers.pop(epoch, None)
                        if b is not None:
                            await self.run_checkpoint(b)
                            for p in self.pumps:
                                p.resume.set()
                            if b.then_stop:
                                then_stop = True
                    if then_stop:
                        break
        finally:
            for f in (get_merged, get_control):
                if f is not None and not f.done():
                    f.cancel()
            for p in self.pumps:
                if p.task is not None:
                    p.task.cancel()
            # unblock upstreams possibly parked on a full queue (matters on
            # immediate stop, where this task exits while producers still run)
            for _, q in self.inputs:
                while not q.empty():
                    try:
                        q.get_nowait()
                    except asyncio.QueueEmpty:
                        break

        await self._await_pending_commit()
        await self.operator.on_close(self.ctx)
        if then_stop or stop_mode is not None:
            await self.out_ctx.broadcast(Message.stop())
        else:
            await self.out_ctx.broadcast(Message.end_of_data())

    def _make_coalescer(self):
        """Input-side adaptive micro-batch coalescer (see engine/
        coalesce.py); None when disabled via ARROYO_COALESCE=0."""
        from ..config import config
        from .coalesce import BatchCoalescer, coalescing_enabled

        if not coalescing_enabled():
            return None
        cfg = config()
        target = cfg.coalesce_target or cfg.target_batch_size
        hist = (self.ctx.metrics.coalesce_batches
                if self.ctx.metrics is not None else None)
        return BatchCoalescer(target, cfg.coalesce_linger_micros / 1e6,
                              hist, prof=self._prof,
                              prof_op=self.task_info.operator_id)

    async def _process_record(self, batch, side: int) -> None:
        """Run one (possibly coalesced) record batch through the
        operator with the task-level flight-recorder observations —
        unless the operator attributes per-member metrics itself
        (ChainedOperator)."""
        lat = self._lat
        if lat is not None:
            from ..obs import latency as _latency

            if self._lat_terminal and batch.lat_stamp is not None:
                # sink boundary: a sampled stamp becomes one
                # emit-minus-ingest observation
                lat.observe_sink(self.task_info, batch.lat_stamp)
            # park the input stamp for the duration of process_batch so
            # Context.collect re-attaches it to operator-built batches
            # (chain tails included — each member's Context reads it)
            _latency.set_current(batch.lat_stamp)
        try:
            await self._process_record_inner(batch, side)
        finally:
            if lat is not None:
                _latency.set_current(None)

    async def _process_record_inner(self, batch, side: int) -> None:
        metrics = self.ctx.metrics
        if metrics is None or self.operator.own_batch_metrics:
            # a ChainedOperator opens its own per-member `proc` phases
            await self.operator.process_batch(batch, self.ctx, side)
            return
        prof = self._prof
        if len(batch):
            # event-time lag at this operator: processing wall clock vs
            # the freshest event in the batch.  Sentinels are excluded by
            # testing the timestamp itself (unset/MIN and final-flush
            # MAX), not by bounding the lag — a historical replay's
            # months-of-backlog lag is exactly the signal the histogram
            # exists to carry
            ts = int(np.max(batch.timestamp))
            if 0 < ts < int(MAX_TIMESTAMP) - 1:
                metrics.event_time_lag.observe(
                    max((now_micros() - ts) / 1e6, 0.0))
        frame = (prof.begin(self.task_info.operator_id, "proc")
                 if prof is not None else None)
        t0 = _time.perf_counter()
        try:
            await self.operator.process_batch(batch, self.ctx, side)
        finally:
            if frame is not None:
                prof.end(frame)
        metrics.batch_latency.observe(_time.perf_counter() - t0)

    async def _await_pending_commit(self, timeout: float = 30.0) -> None:
        """A two-phase sink whose pre-commits were sealed by the final
        (possibly then_stop) checkpoint must not exit before the controller's
        Commit arrives — otherwise the last epoch's output is never
        finalized (the reference parks sink tasks until Commit,
        job_controller/mod.rs:326-371)."""
        has_pending = getattr(self.operator, "has_pending_commits", None)
        if has_pending is None or not has_pending(self.ctx):
            return
        try:
            while True:
                cm = await asyncio.wait_for(self.control_rx.get(),
                                            timeout=timeout)
                if cm.kind == "commit":
                    await self.operator.handle_commit(cm.epoch, self.ctx)
                    if not has_pending(self.ctx):
                        return
                elif cm.kind == "stop" and cm.stop_mode == StopMode.IMMEDIATE:
                    # abandon the wait: pre-commits re-commit on restore
                    return
        except asyncio.TimeoutError:
            logger.warning(
                "task %s closed with uncommitted pre-commits (no Commit "
                "within %.0fs); they will be re-committed on restore",
                self.task_info.task_id, timeout)

    async def _advance_watermark(self, wm: int) -> None:
        if (self.ctx.metrics is not None
                and 0 < wm < int(MAX_TIMESTAMP) - 1):
            # watermark lag at this operator: wall clock vs its (newly
            # advanced) input watermark; the MIN/unset and final-flush
            # MAX sentinels are not real event times, but an arbitrarily
            # large replay lag is
            self.ctx.metrics.watermark_lag.observe(
                max((now_micros() - wm) / 1e6, 0.0))
        # fire expired event-time timers first (macro lib.rs:738-753)
        prof = self._prof
        frame = (prof.begin(self.task_info.operator_id, "watermark")
                 if prof is not None else None)
        try:
            for time, key, payload in self.ctx.timers.fire(wm):
                await self.operator.handle_timer(time, key, payload, self.ctx)
            await self.operator.handle_watermark(wm, self.ctx)
        finally:
            if frame is not None:
                prof.end(frame)

    # -- checkpoint (macro lib.rs:706-736) -------------------------------

    async def run_checkpoint(self, barrier: CheckpointBarrier) -> None:
        tid = self.task_info.task_id
        align_start = self._align_start.pop(barrier.epoch, None)
        if align_start is not None:
            align_us = tracing.now_us() - align_start
            tracing.record_span("barrier.align", "checkpoint", align_start,
                                align_us, tid=tid,
                                args={"epoch": barrier.epoch})
            if self._lat is not None:
                # critical path: records queued behind this alignment
                # waited exactly this long (the profiler has no phase
                # for it — pumps park outside any frame)
                self._lat.note_stage("barrier_align", align_us / 1e6)
        await self._report_event(barrier, CheckpointEventType.STARTED_CHECKPOINTING)
        # snapshot state (per member for chained operators — the
        # controller's epoch tracker expects one completion per logical
        # (operator, subtask), and per-member metadata keeps chained
        # checkpoints restorable un-chained and vice versa)
        prof = self._prof
        frame = (prof.begin(self.task_info.operator_id, "checkpoint")
                 if prof is not None else None)
        try:
            metadatas = await self.operator.checkpoint_state(barrier,
                                                             self.ctx)
        finally:
            if frame is not None:
                prof.end(frame)
        if self.sanitizer is not None:
            # completeness: exactly one completion per distinct
            # (member, subtask) per epoch — a duplicate means two
            # snapshots raced for the same slot
            for md in metadatas:
                self.sanitizer.on_checkpoint_completed(
                    md.operator_id, md.subtask_index, md.epoch)
        await self._report_event(barrier, CheckpointEventType.FINISHED_SYNC)
        for metadata in metadatas:
            await self.ctx.report(ControlResp(
                kind="checkpoint_completed",
                operator_id=metadata.operator_id,
                task_index=metadata.subtask_index,
                subtask_metadata=metadata))
        # rebroadcast barrier downstream
        await self.out_ctx.broadcast(Message.barrier_msg(barrier))

    async def _report_event(self, b: CheckpointBarrier,
                            et: CheckpointEventType) -> None:
        await self.ctx.report(ControlResp(
            kind="checkpoint_event",
            operator_id=self.task_info.operator_id,
            task_index=self.task_info.task_index,
            checkpoint_event=CheckpointEvent(
                b.epoch, self.task_info.operator_id,
                self.task_info.task_index, now_micros(), et)))
