"""ChainedOperator — N fused operators executed by one TaskRunner.

The engine's chaining pass (graph/chaining.py) proves a linear run of
same-parallelism forward-edge operators; this class executes that run
inside a single task: a batch flows member-to-member as a **synchronous
await chain** — no intermediate asyncio queues, no Batch
re-materialization, one watermark/barrier alignment per chain.

Identity survives fusion:

* each member keeps its own ``Context`` — its own ``StateStore`` (so
  checkpoint state tables keep per-member names and restores from
  un-chained checkpoints work), its own ``TimerHeap``, its own
  ``TaskMetrics`` (flight-recorder rollups still attribute
  kernel-seconds/lag/latency to individual members), and its own
  ``KernelAccumulator`` installed around that member's processing;
* ``checkpoint_state`` snapshots every member in chain order and
  returns one metadata entry per member, so the controller's epoch
  tracker sees exactly the per-(operator, subtask) completions it would
  see un-chained.

Where adjacent members are RECORD-returning expression kernels, their
column functions are composed into a **single jitted dispatch** (XLA
fuses them into one kernel), eliminating per-hop padding and dispatch
overhead entirely; composition is row-preserving (RECORD maps are 1:1),
so interior members' message counters stay exact.
``ARROYO_CHAIN_FUSE_EXPR=0`` disables only the jit composition while
keeping the queue-hop elimination.

**Ingest-spine fusion (this PR):** runs of elementwise members —
predicates, record/UDF projections, key_bys — execute as ONE host
step (`_SpineStep`): each member's column fn runs eagerly pinned to
the CPU backend (ops/expr.py ``CompiledExpr.eval_host``), with no
padding, no jit and **zero accelerator dispatches**.  The batch on
both sides of these members is host-resident by construction (sources
decode to numpy; window state pre-aggregates on host before its
scatter), so the per-member pad→dispatch→readback round trip was pure
envelope — Flare's argument applied to the ingest path.  Combined
with parallelism-1 shuffle chaining (graph/chaining.py), a
source→project→key_by→window pipeline becomes one task whose
per-batch work is a single Python step plus the window's (deferred,
coalesced) state scatter.  ``ARROYO_CHAIN_FUSE_INGEST=0`` restores
the jitted per-member path bit-for-bit; ``ARROYO_CHAIN_FUSE_EXPR=0``
(the PR 4 escape) disables BOTH fused forms — jit composition and the
spine — so one knob always yields plain per-member execution.
"""

from __future__ import annotations

import logging
import os
import time as _time
import weakref
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..graph.logical import ColumnExpr, ExprReturnType
from ..obs import perf, profiler
from ..types import (
    Batch,
    CheckpointBarrier,
    Message,
    MessageKind,
    TaskInfo,
    Watermark,
    now_micros,
    MAX_TIMESTAMP,
)
from .context import Context
from .operator import Operator
from .operators_basic import ExpressionOperator, KeyByOperator, UdfOperator

logger = logging.getLogger(__name__)


def ingest_fusion_enabled() -> bool:
    """``ARROYO_CHAIN_FUSE_INGEST=0`` disables host-spine fusion (the
    eager CPU-pinned evaluation of elementwise chain members), keeping
    the jitted per-member / composed-expr path."""
    return os.environ.get("ARROYO_CHAIN_FUSE_INGEST", "1") not in (
        "0", "off", "false")


class _ChainLink:
    """Collector stand-in for a non-tail member: ``collect`` feeds the
    next member synchronously, ``broadcast`` routes watermarks through
    the remaining members' watermark pipeline."""

    metrics = None  # Collector-duck attribute (Context reads it)

    def __init__(self, chain: "ChainedOperator", nxt: int):
        self.chain = chain
        self.nxt = nxt

    async def collect(self, batch: Batch) -> None:
        if len(batch) == 0:
            return  # parity with Collector.collect: empties never cross
        m = self.chain.ctxs[self.nxt - 1].metrics
        if m is not None:
            m.messages_sent.inc(len(batch))
        await self.chain._feed(self.nxt, batch)

    async def broadcast(self, msg: Message) -> None:
        await self.chain._control(self.nxt, msg)


def _fusible(op: Operator) -> bool:
    return (isinstance(op, ExpressionOperator)
            and op.return_type == ExprReturnType.RECORD)


# composed-fn cache keyed by the FIRST member's fn (weak) then the ids of
# the rest: logical expression fns persist across engine rebuilds (bench
# warm runs, restarts), so the composed closure — and with it the jit
# cache entry — must too
_FUSED_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _compose_exprs(exprs: List[ColumnExpr]) -> ColumnExpr:
    """One ColumnExpr running the members' fns back to back inside a
    single jit.  Timestamp rewrites propagate exactly as the unfused
    eval_record_expr chain would; host (string) columns bypass jit in
    both forms and re-attach once at the end."""
    fns = [e.fn for e in exprs]
    # key by the fn OBJECTS (strong refs, hashable) — ids would be
    # reused after gc and could silently serve a stale composition
    key = tuple(fns[1:])
    try:
        cached = _FUSED_CACHE.setdefault(fns[0], {})
    except TypeError:  # non-weakref-able callable
        cached = {}
    fused = cached.get(key)
    if fused is None:
        def fused(cols, _fns=tuple(fns)):
            cur = dict(cols)
            ts = cur["__timestamp"]
            for f in _fns:
                out = dict(f(cur))
                ts = out.pop("__timestamp", ts)
                cur = {"__timestamp": ts, **out}
            return cur  # always carries __timestamp (rewrites included)

        used = set()
        for e in exprs:
            ecols = getattr(e.fn, "used_cols", None)
            if ecols is None:
                used = None
                break
            used.update(ecols)
        if used is not None:
            fused.used_cols = used  # superset is safe: it only widens
            # the set of batch columns coerced into the jit
        cached[key] = fused
    name = "+".join(e.name for e in exprs)
    return ColumnExpr(name, fused, ExprReturnType.RECORD,
                      sql="; ".join(e.sql for e in exprs if e.sql))


def _spineable(op: Operator) -> bool:
    """Members the host spine can execute: pure elementwise transforms
    with no state, timers, broadcasts or side effects."""
    return isinstance(op, (ExpressionOperator, UdfOperator,
                           KeyByOperator))


class _SpineStep(Operator):
    """One fused execution step running a run of elementwise chain
    members (predicates / record exprs / UDFs / key_bys) eagerly on the
    host — semantics member-for-member identical to the unfused path
    (same column layouts, same row drops, same key hashes), with zero
    accelerator dispatches.  Does its own per-member recv/sent/lag
    accounting (``own_member_counts``) because predicates change the
    row count mid-run."""

    own_member_counts = True

    def __init__(self, chain: "ChainedOperator", idxs: List[int]):
        members = [chain.members[i] for i in idxs]
        super().__init__(
            "spine(" + "+".join(m.name for m in members) + ")")
        self.chain = chain
        self.idxs = idxs
        self._plan: List[Tuple[int, str, Operator]] = []
        for mi, op in zip(idxs, members):
            if isinstance(op, KeyByOperator):
                kind = "key"
            elif isinstance(op, UdfOperator):
                kind = "udf"
            elif op.return_type == ExprReturnType.PREDICATE:
                kind = "pred"
            elif op.return_type == ExprReturnType.RECORD:
                kind = "record"
            else:
                kind = "opt"  # OPTIONAL_RECORD: record + __valid select
            self._plan.append((mi, kind, op))

    def _observe(self, mi: int, batch: Batch) -> None:
        """Mirror ChainedOperator._feed's per-member bookkeeping."""
        m = self.chain.ctxs[mi].metrics
        if m is None:
            return
        n = len(batch)
        if mi != 0:
            # the head member's recv is counted by the runner
            m.messages_recv.inc(n)
        if n:
            ts = int(np.max(batch.timestamp))
            if 0 < ts < int(MAX_TIMESTAMP) - 1:
                m.event_time_lag.observe(
                    max((now_micros() - ts) / 1e6, 0.0))

    async def process_batch(self, batch: Batch, ctx: Context,
                            side: int = 0) -> None:
        from ..ops.expr import (eval_host_expr, eval_predicate,
                                eval_record_expr)

        b = batch
        last = self._plan[-1][0]
        for mi, kind, op in self._plan:
            self._observe(mi, b)
            if kind == "pred":
                mask = eval_predicate(op.compiled, b, host=True)
                if not mask.any():
                    return  # legacy predicate: empty results never emit
                b = b.select(mask)
            elif kind == "record":
                b = eval_record_expr(op.compiled, b, host=True)
            elif kind == "opt":
                b = eval_record_expr(op.compiled, b, host=True)
                if "__valid" in b.columns:
                    vm = b.columns.pop("__valid").astype(bool)
                    b = b.select(vm)
            elif kind == "udf":
                b = eval_host_expr(op.fn, b)
            else:  # key
                b = b.with_key(list(op.key_cols))
            if mi != last:
                m = self.chain.ctxs[mi].metrics
                if m is not None:
                    # interior sent = rows this member emitted; the last
                    # member's sent is counted by its collector (link or
                    # tail Collector), exactly as unfused
                    m.messages_sent.inc(len(b))
        if len(b):
            await ctx.collect(b)


class ChainedOperator(Operator):
    """Executes chain members in order inside one task (see module
    docstring).  ``bind(ctxs)`` must be called with one Context per
    member before the runner starts; ``ctxs[0]`` doubles as the
    runner's context and ``tail_ctx`` carries the real output
    Collector."""

    own_batch_metrics = True  # per-member lag/latency recorded here

    def __init__(self, infos: List[TaskInfo], members: List[Operator]):
        super().__init__(
            "chain(" + "->".join(op.name for op in members) + ")")
        assert len(infos) == len(members) >= 2
        self.infos = infos
        self.members = members
        self.ctxs: List[Context] = []
        self.tail_ctx: Optional[Context] = None
        self._accs: List[perf.KernelAccumulator] = []
        # execution steps: (exec_operator, member_indices, exec_ctx_idx)
        self._steps: List[Tuple[Operator, List[int], int]] = []
        self._step_by_start: Dict[int, Tuple[Operator, List[int], int]] = {}
        self._lat_stack: List[float] = []  # child-inclusive seconds
        # latency observatory: when this chain ends the dataflow (tail
        # Collector has no outgoing edges), the feed into the tail
        # member is the sink boundary — observing there (not at chain
        # input) means a window fire inside the chain is measured at
        # its actual emission, watermark hold included
        self._lat: Optional[Any] = None
        self._lat_tail_start: Optional[int] = None

    # -- wiring ------------------------------------------------------------

    def make_link(self, member_index: int) -> _ChainLink:
        """The collector for member ``member_index`` (routes to the next
        member); the tail member uses the engine's real Collector."""
        return _ChainLink(self, member_index + 1)

    def bind(self, ctxs: List[Context]) -> None:
        assert len(ctxs) == len(self.members)
        self.ctxs = list(ctxs)
        self.tail_ctx = ctxs[-1]
        self._accs = [perf.KernelAccumulator(ti, c.metrics)
                      for ti, c in zip(self.infos, ctxs)]
        self._build_steps()
        from ..obs import latency as _latency

        self._lat = _latency.active()
        if (self._lat is not None
                and not self.tail_ctx.collector.edge_groups):
            self._lat_tail_start = self._steps[-1][1][0]

    def _build_steps(self) -> None:
        from ..ops.expr import _host_eval_device

        fuse = os.environ.get("ARROYO_CHAIN_FUSE_EXPR", "1") not in (
            "0", "off", "false")
        # FUSE_EXPR=0 is the "no fused execution of members at all"
        # escape: it must also force the spine off, or flipping the
        # documented knob would silently change nothing for spineable
        # members (they'd still run fused inside _SpineStep)
        spine = (fuse and ingest_fusion_enabled()
                 and _host_eval_device() is not None)
        self._steps = []
        i = 0
        while i < len(self.members):
            j = i
            if spine and _spineable(self.members[i]):
                # host spine: a maximal run of elementwise members runs
                # as one eager host step — no per-member dispatch at all
                while (j + 1 < len(self.members)
                       and _spineable(self.members[j + 1])):
                    j += 1
                step_op: Operator = _SpineStep(self, list(range(i, j + 1)))
            elif fuse and _fusible(self.members[i]):
                while (j + 1 < len(self.members)
                       and _fusible(self.members[j + 1])):
                    j += 1
                if j > i:
                    fused = _compose_exprs(
                        [self.members[k].expr for k in range(i, j + 1)])
                    step_op = ExpressionOperator(fused.name, fused)
                else:
                    step_op = self.members[i]
            else:
                step_op = self.members[i]
            # execute against the LAST covered member's context so
            # collect() routes to the member after the fused run
            self._steps.append((step_op, list(range(i, j + 1)), j))
            i = j + 1
        self._step_by_start = {step[1][0]: step for step in self._steps}

    # -- lifecycle ---------------------------------------------------------

    async def open(self, ctx: Context) -> None:
        for member, mctx in zip(self.members, self.ctxs):
            await Operator.open(member, mctx)

    async def on_close(self, ctx: Context) -> None:
        for member, mctx in zip(self.members, self.ctxs):
            await member.on_close(mctx)

    async def checkpoint_state(self, barrier: CheckpointBarrier,
                               ctx: Context) -> List[Any]:
        metas: List[Any] = []
        for member, mctx in zip(self.members, self.ctxs):
            metas.extend(await member.checkpoint_state(barrier, mctx))
        return metas

    async def handle_commit(self, epoch: int, ctx: Context) -> None:
        for member, mctx in zip(self.members, self.ctxs):
            await member.handle_commit(epoch, mctx)

    async def handle_load_compacted(self, payload: Any,
                                    ctx: Context) -> None:
        target = (payload.get("operator_id")
                  if isinstance(payload, dict) else None)
        for ti, member, mctx in zip(self.infos, self.members, self.ctxs):
            if not target or ti.operator_id == target:
                await member.handle_load_compacted(payload, mctx)

    # -- dataflow ----------------------------------------------------------

    async def process_batch(self, batch: Batch, ctx: Context,
                            side: int = 0) -> None:
        await self._feed(0, batch, side)

    async def _feed(self, start: int, batch: Batch, side: int = 0) -> None:
        step_op, idxs, ectx_idx = self._step_by_start[start]
        if (self._lat_tail_start is not None
                and start == self._lat_tail_start
                and batch.lat_stamp is not None):
            # sink boundary of a terminal chain: one emit-minus-ingest
            # observation per sampled batch reaching the tail member
            self._lat.observe_sink(self.infos[-1], batch.lat_stamp)
        if self.sanitizer is not None and start > 0:
            # interior chain edges keep the same per-edge schema
            # stability contract as real queues (the head edge is
            # checked by the runner)
            self.sanitizer.on_record(
                (self.infos[start].task_id, "chain"), batch)
        n = len(batch)
        if not getattr(step_op, "own_member_counts", False):
            # a _SpineStep counts per member itself (predicates change
            # the row count member to member)
            ts = int(np.max(batch.timestamp)) if n else 0
            now = now_micros()
            for mi in idxs:
                m = self.ctxs[mi].metrics
                if m is None:
                    continue
                if mi != 0:
                    # the head member's recv is counted by the runner;
                    # every other member counts here (fused interiors
                    # included — RECORD exprs are 1:1, so the
                    # pass-through count is exact)
                    m.messages_recv.inc(n)
                if 0 < ts < int(MAX_TIMESTAMP) - 1:
                    m.event_time_lag.observe(max((now - ts) / 1e6, 0.0))
            for mi in idxs[:-1]:
                m = self.ctxs[mi].metrics
                if m is not None:
                    m.messages_sent.inc(n)
        # exclusive latency: inclusive minus time spent in downstream
        # members this call recursed into (collect is synchronous)
        self._lat_stack.append(0.0)
        token = perf.set_active_task(self._accs[idxs[0]])
        prof = profiler.active()
        frame = (prof.begin(self.infos[idxs[0]].operator_id, "proc")
                 if prof is not None else None)
        t0 = _time.perf_counter()
        try:
            await step_op.process_batch(
                batch, self.ctxs[ectx_idx], side if start == 0 else 0)
        finally:
            if frame is not None:
                # nested member frames subtract automatically, so each
                # member's `proc` phase is exclusive like its latency
                prof.end(frame)
            perf.reset_active_task(token)
            inclusive = _time.perf_counter() - t0
            child = self._lat_stack.pop()
            if self._lat_stack:
                self._lat_stack[-1] += inclusive
            m0 = self.ctxs[idxs[0]].metrics
            if m0 is not None:
                m0.batch_latency.observe(max(inclusive - child, 0.0))

    # -- watermarks / timers ----------------------------------------------

    async def handle_timer(self, time: int, key: Any, payload: Any,
                           ctx: Context) -> None:
        # the runner fires the HEAD member's timer heap (ctx is ctxs[0])
        await self.members[0].handle_timer(time, key, payload,
                                           self.ctxs[0])

    async def handle_watermark(self, watermark: int, ctx: Context) -> None:
        # head member's watermark handling; its default broadcast rides
        # the chain link into _control -> the next member, and so on
        # until the tail broadcasts downstream for real
        await self.members[0].handle_watermark(watermark, self.ctxs[0])

    async def _control(self, i: int, msg: Message) -> None:
        if msg.kind == MessageKind.WATERMARK:
            await self._member_watermark(i, msg.watermark)
            return
        # members only ever broadcast watermarks mid-stream; anything
        # else (defensive) goes straight downstream
        logger.debug("chain %s: member broadcast of %s forwarded to tail",
                     self.name, msg.kind)
        await self.tail_ctx.broadcast(msg)

    async def _member_watermark(self, i: int, wm: Watermark) -> None:
        """The per-member slice of TaskRunner's watermark advancement:
        observe, fire that member's timers, then its handle_watermark
        (whose default broadcast continues down the chain)."""
        mctx = self.ctxs[i]
        if self.sanitizer is not None:
            self.sanitizer.on_watermark((self.infos[i].task_id, "chain"),
                                        wm)
        advanced = mctx.observe_watermark(0, wm)
        if advanced is not None:
            if (mctx.metrics is not None
                    and 0 < advanced < int(MAX_TIMESTAMP) - 1):
                mctx.metrics.watermark_lag.observe(
                    max((now_micros() - advanced) / 1e6, 0.0))
            prof = profiler.active()
            frame = (prof.begin(self.infos[i].operator_id, "watermark")
                     if prof is not None else None)
            try:
                for t, key, payload in mctx.timers.fire(advanced):
                    await self.members[i].handle_timer(t, key, payload, mctx)
                await self.members[i].handle_watermark(advanced, mctx)
            finally:
                if frame is not None:
                    prof.end(frame)
        elif wm.is_idle and mctx.watermarks.all_idle():
            await mctx.broadcast(Message.wm(Watermark.idle()))
