"""Logical operator -> physical operator construction.

The analog of the reference's ``Program::make_graph_function``
(/root/reference/arroyo-datastream/src/lib.rs:1216-1700): where the reference
emits Rust constructor source per operator variant for cargo to compile, we
instantiate Python operator objects whose hot paths are jitted at first batch.
"""

from __future__ import annotations

from typing import Callable, Dict

from ..connectors.registry import make_sink, make_source
from ..graph.logical import LogicalOperator, OpKind
from .operator import Operator
from .operators_basic import (
    AggregateOperator,
    CountOperator,
    ExpressionOperator,
    FlatMapOperator,
    FlattenOperator,
    GlobalKeyOperator,
    KeyByOperator,
    UdfOperator,
    UnionOperator,
    WatermarkOperator,
)

_BUILDERS: Dict[OpKind, Callable[[LogicalOperator], Operator]] = {}


def validate_before_build(program) -> None:
    """Plan-time gate run before any physical operator is constructed:
    graph-level invariants (keyed state behind shuffles, watermark/
    window consistency, join key schemas, no dangling nodes) are
    rejected here with structured diagnostics instead of surfacing as
    wrong results or a hung pipeline at runtime.  Escape hatch:
    ``ARROYO_PLAN_VALIDATE=0`` (triage only — a plan that fails here is
    broken)."""
    import os

    if os.environ.get("ARROYO_PLAN_VALIDATE", "1") in ("0", "off",
                                                       "false"):
        return
    from ..analysis.plan_validator import check_program

    check_program(program)  # raises PlanValidationError on errors


def register_builder(kind: OpKind):
    def deco(fn):
        _BUILDERS[kind] = fn
        return fn
    return deco


def build_operator(op: LogicalOperator) -> Operator:
    _ensure_window_ops()
    builder = _BUILDERS.get(op.kind)
    if builder is None:
        raise NotImplementedError(f"no physical operator for {op.kind}")
    return builder(op)


_BUILDERS[OpKind.CONNECTOR_SOURCE] = lambda op: make_source(
    op.spec.connector, op.spec.config)
_BUILDERS[OpKind.CONNECTOR_SINK] = lambda op: make_sink(
    op.spec.connector, op.spec.config)
_BUILDERS[OpKind.EXPRESSION] = lambda op: ExpressionOperator(op.name, op.expr)
_BUILDERS[OpKind.UDF] = lambda op: UdfOperator(op.name, op.expr)
_BUILDERS[OpKind.FLAT_MAP] = lambda op: FlatMapOperator(op.name, op.expr)
_BUILDERS[OpKind.FLATTEN] = lambda op: FlattenOperator(op.name)
_BUILDERS[OpKind.UNION] = lambda op: UnionOperator(op.name)
_BUILDERS[OpKind.WATERMARK] = lambda op: WatermarkOperator(op.name, op.spec)
_BUILDERS[OpKind.KEY_BY] = lambda op: KeyByOperator(op.name, op.key_cols)
_BUILDERS[OpKind.GLOBAL_KEY] = lambda op: GlobalKeyOperator(op.name)
_BUILDERS[OpKind.COUNT] = lambda op: CountOperator(op.name)
_BUILDERS[OpKind.AGGREGATE] = lambda op: AggregateOperator(op.name, op.spec)
# Updating-stream variants: expression/keying with the __op column flowing
# through (Operator::UpdatingOperator / UpdatingKeyOperator)
_BUILDERS[OpKind.UPDATING] = lambda op: ExpressionOperator(op.name, op.expr)
_BUILDERS[OpKind.UPDATING_KEY] = lambda op: KeyByOperator(op.name, op.key_cols)

_window_ops_loaded = False


def _ensure_window_ops() -> None:
    """Window/join operators live in engine.operators_window which registers
    its builders on import (deferred to avoid importing jax at graph-build
    time)."""
    global _window_ops_loaded
    if _window_ops_loaded:
        return
    _window_ops_loaded = True
    try:
        from . import operators_window  # noqa: F401
    except ImportError:
        pass
