"""Ahead-of-time pipeline compilation.

The reference's compile stage turns a logical ``Program`` into a pipeline
binary before any worker is scheduled (arroyo-controller/src/compiler.rs:
92-259 generates a cargo workspace and runs ``cargo build``; the
arroyo-compiler-service keeps a warm build_dir).  In the TPU design
"compile" is ``jax.jit`` tracing, which is shape-driven and therefore
happens per batch-size bucket at runtime — so the AOT stage's jobs become:

1. **Fail early** (`compile_program`): construct every physical operator
   from the logical graph — connector configs, compiled SQL expressions,
   window state, UDF wiring — so a bad pipeline dies in the controller's
   Compiling state, not on a worker mid-schedule.  This is the same
   contract as the reference's compile stage (a pipeline that compiles is
   schedulable).
2. **Persist compiled programs** (`enable_persistent_cache`): XLA
   executables go to a shared on-disk cache, so re-submissions and worker
   restarts reuse compilations instead of re-tracing (the analog of the
   compiler service's warm build_dir + artifact re-use via the program
   graph hash, compiler.rs:57-90).
3. **Export jittable steps** (`serialize_step`/`deserialize_step`): a
   traced step (e.g. the mesh window update) serializes to portable
   StableHLO bytes via ``jax.export`` and can be stored to the artifact
   store and re-loaded without the Python closure — the closest analog of
   shipping the pipeline binary to object storage (compiler.rs:247-259).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)


@dataclass
class CompileReport:
    """Outcome of the AOT build pass."""

    operators: Dict[str, str] = field(default_factory=dict)  # id -> class
    errors: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.errors


def compile_program(program) -> CompileReport:
    """Validate + physically build every operator of a logical program.

    Returns a report instead of raising: the controller FSM turns a
    non-ok report into a Failed transition with the collected messages
    (states/compiling.rs analog)."""
    from .build import build_operator

    report = CompileReport()
    for msg in program.validate():
        report.errors.append(msg)
    from ..analysis.plan_validator import plan_report

    report.errors.extend(
        d.render() for d in plan_report(program)["diagnostics"]
        if d.severity == "error")
    if report.errors:
        return report
    for node_id in program.topo_order():
        node = program.node(node_id)
        try:
            op = node.operator
            phys = build_operator(op)
            report.operators[node.operator_id] = type(phys).__name__
        except Exception as e:  # config/expression/connector errors
            report.errors.append(f"{node.operator_id}: {e}")
    return report


def enable_persistent_cache(cache_dir: Optional[str] = None,
                            suffix: Optional[str] = None) -> str:
    """Point jax at an on-disk compilation cache (idempotent).  Returns
    the directory in use.

    The default directory is keyed by the host's CPU model: XLA:CPU AOT
    blobs embed machine features, and loading a blob compiled on a
    different CPU generation risks SIGILL (observed via a shared /tmp
    across heterogeneous hosts)."""
    import jax

    d = (cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR")
         or os.environ.get("ARROYO_COMPILE_CACHE"))
    if d is None:
        import hashlib
        import json
        import platform

        try:  # CPU model distinguishes generations; platform alone doesn't
            with open("/proc/cpuinfo") as f:
                info = f.read()
            # x86 exposes 'model name'; ARM exposes 'CPU part'/'Features'
            # instead — hash whichever identifying lines exist
            model = "".join(
                ln for ln in info.splitlines()
                if ln.startswith(("model name", "CPU part", "Features",
                                  "flags")))[:2048]
        except OSError:
            model = ""
        # full environment signature: virtualized hosts report identical
        # generic model strings across different VMs, and XLA:CPU AOT
        # blobs embed target OPTIONS beyond CPU features (observed: a
        # shared /tmp carried +prefer-no-scatter blobs from a previous
        # round's machine into one whose host lacks them — XLA warns of
        # possible SIGILL).  cpu_count, XLA_FLAGS, jax version, and the
        # tunnel-plugin presence all change the blob contract.
        signature = json.dumps({
            "machine": platform.machine(), "model": model,
            "cpus": os.cpu_count(),
            "xla_flags": os.environ.get("XLA_FLAGS", ""),
            "jax": jax.__version__,
            "tunnel": bool(os.environ.get("PALLAS_AXON_POOL_IPS")),
        }, sort_keys=True)
        key = hashlib.md5(signature.encode()).hexdigest()[:10]
        d = f"/tmp/arroyo_jax_cache_{key}"
        if suffix:
            # segregate by resolved backend so flag contexts never share
            d += f"_{suffix}"
        # marker-file check: if the dir exists but was written under a
        # DIFFERENT signature (hash collision, format change), refuse to
        # reuse it rather than risk loading foreign AOT blobs
        try:
            os.makedirs(d, exist_ok=True)
            marker = os.path.join(d, "ENV_SIGNATURE.json")
            if os.path.exists(marker):
                with open(marker) as f:
                    if f.read() != signature:
                        import shutil

                        shutil.rmtree(d, ignore_errors=True)
                        os.makedirs(d, exist_ok=True)
            with open(marker, "w") as f:
                f.write(signature)
        except OSError:
            pass
    try:
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    except Exception as e:  # pragma: no cover - older jax
        logger.warning("persistent compile cache unavailable: %s", e)
    return d


# ---------------------------------------------------------------------------
# Step export (StableHLO serialization)
# ---------------------------------------------------------------------------


def serialize_step(fn: Callable, example_args: Sequence[Any]) -> bytes:
    """Trace ``fn`` at the example arguments' shapes and serialize the
    result as portable StableHLO bytes (jax.export)."""
    import jax
    from jax import export as jax_export

    exported = jax_export.export(jax.jit(fn))(*example_args)
    return bytes(exported.serialize())


def deserialize_step(data: bytes) -> Callable:
    """Rehydrate a serialized step into a callable (no Python source
    needed — the artifact alone is executable, like the reference's
    shipped pipeline binary)."""
    from jax import export as jax_export

    exported = jax_export.deserialize(data)
    return exported.call


def store_step(url: str, name: str, data: bytes) -> str:
    """Write a serialized step to the artifact store (compiler.rs:247-259
    pushes pipeline binaries the same way).  Returns the artifact path."""
    from ..utils.storage import StorageProvider

    store = StorageProvider.for_url(url)
    path = f"artifacts/{name}.stablehlo"
    store.put(path, data)
    return f"{url.rstrip('/')}/{path}"


def load_step(url: str, name: str) -> Callable:
    from ..utils.storage import StorageProvider

    store = StorageProvider.for_url(url)
    return deserialize_step(store.get(f"artifacts/{name}.stablehlo"))
