"""Per-job controller state machine — analog of the reference's typed FSM
(/root/reference/arroyo-controller/src/states/mod.rs:162-237, 503-549):

Created -> Compiling -> Scheduling -> Running
    -> {CheckpointStopping, Stopping, Recovering, Rescaling, Finishing}
    -> {Stopped, Finished, Failed}

with bounded restarts (10) and the healthy-after-2-minutes reset policy
(states/running.rs:17-21)."""

from __future__ import annotations

import time
from enum import Enum
from typing import Callable, List, Optional


class JobState(Enum):
    CREATED = "Created"
    COMPILING = "Compiling"
    SCHEDULING = "Scheduling"
    RUNNING = "Running"
    CHECKPOINT_STOPPING = "CheckpointStopping"
    STOPPING = "Stopping"
    RECOVERING = "Recovering"
    RESCALING = "Rescaling"
    FINISHING = "Finishing"
    STOPPED = "Stopped"
    FINISHED = "Finished"
    FAILED = "Failed"

    @property
    def terminal(self) -> bool:
        return self in (JobState.STOPPED, JobState.FINISHED, JobState.FAILED)


VALID_TRANSITIONS = {
    JobState.CREATED: {JobState.COMPILING, JobState.FAILED},
    JobState.COMPILING: {JobState.SCHEDULING, JobState.FAILED},
    JobState.SCHEDULING: {JobState.RUNNING, JobState.FAILED,
                          JobState.STOPPING, JobState.RECOVERING},
    JobState.RUNNING: {JobState.CHECKPOINT_STOPPING, JobState.STOPPING,
                       JobState.RECOVERING, JobState.RESCALING,
                       JobState.FINISHING, JobState.FINISHED,
                       JobState.FAILED},
    JobState.CHECKPOINT_STOPPING: {JobState.STOPPING, JobState.STOPPED,
                                   JobState.FAILED},
    JobState.STOPPING: {JobState.STOPPED, JobState.FAILED},
    JobState.RECOVERING: {JobState.SCHEDULING, JobState.FAILED},
    JobState.RESCALING: {JobState.SCHEDULING, JobState.RECOVERING,
                         JobState.FAILED},
    JobState.FINISHING: {JobState.FINISHED, JobState.FAILED},
}

MAX_RESTARTS = 10  # states/running.rs:17-21
HEALTHY_AFTER_SECS = 120.0


class StateMachine:
    def __init__(self, job_id: str,
                 on_transition: Optional[Callable[[JobState, JobState], None]] = None):
        self.job_id = job_id
        self.state = JobState.CREATED
        self.restarts = 0
        self.running_since: Optional[float] = None
        self.history: List[tuple] = [(time.time(), JobState.CREATED)]
        self.failure_message: Optional[str] = None
        self.on_transition = on_transition

    def transition(self, to: JobState) -> None:
        if self.state.terminal:
            raise ValueError(f"job {self.job_id} is terminal ({self.state})")
        if to not in VALID_TRANSITIONS.get(self.state, set()):
            raise ValueError(
                f"invalid transition {self.state.value} -> {to.value}")
        prev = self.state
        self.state = to
        self.history.append((time.time(), to))
        if to == JobState.RUNNING:
            # healthy-run restart counter reset
            if (self.running_since is not None
                    and time.time() - self.running_since > HEALTHY_AFTER_SECS):
                self.restarts = 0
            self.running_since = time.time()
        if self.on_transition:
            self.on_transition(prev, to)

    def try_recover(self, error: str) -> bool:
        """Returns True if a restart is allowed; transitions accordingly."""
        self.restarts += 1
        if self.restarts > MAX_RESTARTS:
            self.fail(f"too many restarts ({self.restarts}): {error}")
            return False
        self.transition(JobState.RECOVERING)
        return True

    def fail(self, message: str) -> None:
        self.failure_message = message
        prev = self.state
        self.state = JobState.FAILED
        self.history.append((time.time(), JobState.FAILED))
        if self.on_transition:
            self.on_transition(prev, JobState.FAILED)
