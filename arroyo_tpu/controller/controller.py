"""ControllerServer: the control plane — job submission, scheduling,
supervision, checkpoint coordination, recovery.

Folds together the reference's controller pieces:
* gRPC service + job registry (arroyo-controller/src/lib.rs)
* Scheduling state: slots = max operator parallelism, round-robin slot
  packing, wait-for-registration (states/scheduling.rs:44-290)
* JobController supervision: 30s heartbeat timeout, periodic checkpoints,
  epoch bookkeeping, two-phase commit, cleanup (job_controller/mod.rs)
* CheckpointState aggregation of per-subtask events into a job-level
  checkpoint record (checkpointer.rs:67-410)
"""

from __future__ import annotations

import asyncio
import json
import logging
import cloudpickle as pickle
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from ..config import config
from ..graph.logical import Program
from ..rpc.transport import RpcClient, RpcServer
from ..state.backend import ParquetBackend
from ..types import now_micros
from .scheduler import InProcessScheduler, Scheduler
from .state_machine import JobState, StateMachine

logger = logging.getLogger(__name__)


@dataclass
class WorkerInfo:
    worker_id: str
    rpc_address: str
    data_address: str
    slots: int
    last_heartbeat: float = field(default_factory=time.monotonic)
    client: Optional[RpcClient] = None
    finished: bool = False
    # flight-recorder rollup scraped from the heartbeat payload:
    # {operator_id: {metric_key: value}}, plus the previous sample so
    # job-level rate math has a delta to work with
    metric_snapshot: Optional[Dict[str, Dict[str, float]]] = None
    snapshot_time: float = 0.0
    prev_snapshot: Optional[Dict[str, Dict[str, float]]] = None
    prev_time: float = 0.0


@dataclass
class CheckpointTracker:
    """Aggregates per-subtask checkpoint completions for one epoch
    (CheckpointState, checkpointer.rs:186-410)."""

    epoch: int
    n_subtasks: int
    completed: Set[Tuple[str, int]] = field(default_factory=set)
    has_committing: bool = False
    started: float = field(default_factory=time.monotonic)

    @property
    def done(self) -> bool:
        return len(self.completed) >= self.n_subtasks


class Job:
    def __init__(self, job_id: str, program: Program,
                 checkpoint_url: str, parallelism: int):
        self.job_id = job_id
        self.program = program
        self.checkpoint_url = checkpoint_url
        self.parallelism = parallelism
        self.fsm = StateMachine(job_id)
        self.workers: Dict[str, WorkerInfo] = {}
        self.epoch = 0
        self.min_epoch = 0
        self.trackers: Dict[int, CheckpointTracker] = {}
        self.last_successful_epoch: Optional[int] = None
        self.n_subtasks = sum(n.parallelism for n in program.nodes())
        self.finished_tasks: Set[Tuple[str, int]] = set()
        self.failure: Optional[str] = None
        self.supervisor: Optional[asyncio.Task] = None
        self.stop_requested = False
        # absolute wall deadline (time.time()) after which the
        # supervisor stops the job — preview pipelines (reference
        # pipelines.rs ttl_micros); persisted so a restarted controller
        # still reaps resumed previews
        self.ttl_deadline: Optional[float] = None
        # latency SLO (obs/latency.py): seeded from config env, REST PUT
        # can replace it live; the evaluator keeps the burn-rate ring
        from ..obs.latency import Slo, SloEvaluator

        self.slo = Slo.from_config()
        self.slo_eval = SloEvaluator(job_id, self.slo)

    def set_slo(self, slo) -> None:
        """Replace the job's SLO live (REST PUT): the evaluator keeps
        its sample/event history — only the targets change."""
        self.slo = slo
        self.slo_eval.slo = slo

    @property
    def slots_needed(self) -> int:
        return max(n.parallelism for n in self.program.nodes())


class ControllerServer:
    # class-level so test doubles built via __new__ still have it
    _metrics_decode_warned = False

    def __init__(self, scheduler: Optional[Scheduler] = None,
                 host: str = "127.0.0.1",
                 db_path: Optional[str] = None):
        import os

        if scheduler is None:
            if os.environ.get("SCHEDULER"):
                from .scheduler import scheduler_from_env

                scheduler = scheduler_from_env()
            else:
                scheduler = InProcessScheduler()
        self.scheduler = scheduler
        self.host = host
        self.rpc = RpcServer()
        # arroyosan: the controller-side half of checkpoint-completeness
        # (workers check their own runners; only the controller sees the
        # whole job).  None unless ARROYO_SANITIZE is armed.
        from ..analysis.sanitizer import maybe_sanitizer

        self.sanitizer = maybe_sanitizer("controller")
        self.jobs: Dict[str, Job] = {}
        # per-job autoscalers (arroyo_tpu/autoscale): one per accepted
        # job so the decision ledger + REST surface always exist; the
        # loop itself only runs while the job's autoscaler is enabled
        self.autoscalers: Dict[str, Any] = {}
        self.addr: Optional[str] = None
        self.sink_subscribers: Dict[str, List[asyncio.Queue]] = {}
        # durable job state (states/mod.rs:577-628 analog): every
        # non-terminal job in the sqlite store is resumed on start()
        db_path = db_path or os.environ.get("CONTROLLER_DB")
        if db_path:
            from .store import ControllerStore

            self.store: Optional[ControllerStore] = ControllerStore(db_path)
        else:
            self.store = None

    # -- lifecycle ---------------------------------------------------------

    async def start(self, port: int = 0) -> str:
        import os

        self.rpc.add_service("ControllerGrpc", {
            "RegisterWorker": self._register_worker,
            "Heartbeat": self._heartbeat,
            "TaskStarted": self._task_started,
            "TaskCheckpointEvent": self._task_ckpt_event,
            "TaskCheckpointCompleted": self._task_ckpt_completed,
            "TaskFinished": self._task_finished,
            "TaskFailed": self._task_failed,
            "WorkerFinished": self._worker_finished,
            "WorkerError": self._worker_error,
            "SendSinkData": self._send_sink_data,
        }, stream_methods={"SubscribeToOutput": self._subscribe_output})
        p = await self.rpc.start(self.host, port)
        # the address workers DIAL: a 0.0.0.0 bind is not dialable, so
        # deployments advertise the service address instead
        self.addr = os.environ.get(
            "CONTROLLER_ADVERTISE_ADDR",
            f"{'127.0.0.1' if self.host == '0.0.0.0' else self.host}:{p}")
        if self.store is not None:
            await self._resume_persisted()
        return self.addr

    async def _resume_persisted(self) -> None:
        """Adopt every non-terminal job from the durable store: reap
        orphaned workers from the previous controller incarnation, then
        re-drive each job's FSM with restore=True so it resumes from its
        last completed checkpoint (states/mod.rs:577-628)."""
        for row in self.store.resumable():
            if row.stop_requested:
                # a stop was in flight when the controller died; without
                # live workers there is nothing left to checkpoint-stop —
                # the job's last completed checkpoint already exists
                self.store.set_state(row.job_id, JobState.STOPPED.value)
                continue
            try:
                program = pickle.loads(row.program)
            except Exception as e:
                logger.error("job %s: stored program unreadable: %s",
                             row.job_id, e)
                self.store.set_state(row.job_id, JobState.FAILED.value,
                                     f"stored program unreadable: {e}")
                continue
            await self.scheduler.reap(row.job_id,
                                      self.store.workers(row.job_id))
            self.store.set_workers(row.job_id, [])
            if (row.ttl_deadline is not None
                    and time.time() > row.ttl_deadline):
                # an expired preview from the previous incarnation: its
                # workers are already reaped below via the worker table —
                # settle it instead of resuming
                await self.scheduler.reap(row.job_id,
                                          self.store.workers(row.job_id))
                self.store.set_workers(row.job_id, [])
                self.store.set_state(row.job_id, JobState.STOPPED.value)
                continue
            job = Job(row.job_id, program, row.checkpoint_url,
                      max(n.parallelism for n in program.nodes()))
            job.epoch = row.epoch
            job.min_epoch = row.min_epoch
            job.ttl_deadline = row.ttl_deadline
            self._attach_store(job, row.n_workers)
            self.jobs[row.job_id] = job
            self._attach_autoscaler(row.job_id)
            self._restore_autoscaler(row.job_id, row.autoscale)
            logger.info("resuming job %s from durable store (stored "
                        "state %s, epoch %d)", row.job_id, row.state,
                        row.epoch)
            job.supervisor = asyncio.ensure_future(
                self._drive(job, row.n_workers, restore=True))

    def _attach_store(self, job: Job, n_workers: int) -> None:
        """Persist FSM transitions + progress for this job."""
        if self.store is None:
            return
        store = self.store

        def on_transition(prev: JobState, to: JobState) -> None:
            store.set_state(job.job_id, to.value, job.fsm.failure_message)

        job.fsm.on_transition = on_transition

    async def stop(self) -> None:
        for job in self.jobs.values():
            if job.supervisor:
                job.supervisor.cancel()
            await self._close_worker_clients(job)
        for scaler in self.autoscalers.values():
            scaler.stop()
        await self.rpc.stop()
        if self.store is not None:
            self.store.close()

    @staticmethod
    async def _close_worker_clients(job: "Job") -> None:
        """Close per-worker grpc channels before dropping WorkerInfo refs.
        An unclosed aio channel's completion-queue dealloc joins its poller
        thread from whatever thread GC happens to run on — after the owning
        event loop is gone that join can block forever, so the channel must
        be closed while the loop is still alive."""
        for w in list(job.workers.values()):
            if w.client is not None:
                try:
                    await w.client.close()
                except Exception:
                    pass
                w.client = None

    def _attach_autoscaler(self, job_id: str) -> None:
        """One JobAutoscaler per accepted job (ledger + REST surface);
        the evaluation loop starts only when enabled — by default via
        ARROYO_AUTOSCALE_DEFAULT, or later through the REST PUT.
        ARROYO_AUTOSCALE=0 keeps the subsystem entirely out."""
        cfg = config()
        if not cfg.autoscale_enabled:
            return
        from ..autoscale.supervisor import JobAutoscaler

        prev = self.autoscalers.get(job_id)
        if prev is not None:
            # a resubmitted job_id must not leak the old loop: two live
            # loops would race rescale_job against each other
            prev.stop()
        scaler = JobAutoscaler(self, job_id)
        self.autoscalers[job_id] = scaler
        if cfg.autoscale_default_on:
            scaler.set_enabled(True)
        # keep the store in sync: a resubmitted job_id must not inherit
        # the previous incarnation's persisted spec on the next restart
        # (the resume path overwrites this again from the stored row)
        self.persist_autoscaler(job_id)

    def persist_autoscaler(self, job_id: str) -> None:
        """Persist the per-job autoscaler spec (enabled + policy) so a
        restarted controller resumes it with the job (the REST PUT calls
        this after every change)."""
        if self.store is None:
            return
        scaler = self.autoscalers.get(job_id)
        if scaler is not None:
            self.store.set_autoscale(job_id, json.dumps({
                "enabled": scaler.enabled,
                "policy": scaler.policy.cfg.to_json()}))

    def _restore_autoscaler(self, job_id: str,
                            spec_json: Optional[str]) -> None:
        """Re-arm a resumed job's autoscaler from its stored spec."""
        scaler = self.autoscalers.get(job_id)
        if not spec_json or scaler is None:
            return
        try:
            spec = json.loads(spec_json)
        except Exception:
            # a corrupt spec must not block the job resume itself
            logger.warning("job %s: stored autoscaler spec unreadable",
                           job_id, exc_info=True)
            return
        if spec.get("policy"):
            try:
                from ..autoscale.policy import (BacklogDrainPolicy,
                                                PolicyConfig)

                cfg = PolicyConfig(**spec["policy"])
                # same range gate as the REST merge path: a stored
                # interval_secs=0 would busy-spin the controller loop
                cfg._check_ranges()
                scaler.policy = BacklogDrainPolicy(cfg)
            except Exception:
                logger.warning("job %s: stored autoscaler policy "
                               "invalid; keeping defaults", job_id,
                               exc_info=True)
        # unconditional, and applied even when the policy was unusable:
        # a persisted enabled:false must override an
        # ARROYO_AUTOSCALE_DEFAULT=1 enable from the attach — the
        # operator explicitly turned this job's autoscaler off
        scaler.set_enabled(bool(spec.get("enabled")))
        self.persist_autoscaler(job_id)

    # -- job API (what arroyo-api calls via gRPC/DB) ----------------------

    async def submit_job(self, program: Program, job_id: Optional[str] = None,
                         checkpoint_url: Optional[str] = None,
                         n_workers: int = 1,
                         restore: bool = False,
                         ttl_secs: Optional[float] = None) -> str:
        job_id = job_id or f"job-{uuid.uuid4().hex[:8]}"
        # factor-window rewrite BEFORE slot assignment: the controller's
        # assignments are keyed by operator id, so the factor nodes must
        # exist here, not only in each worker's engine-side (idempotent)
        # re-application
        from ..graph.factor_windows import apply_factor_windows

        apply_factor_windows(program)
        job = Job(job_id, program,
                  checkpoint_url or config().checkpoint_url,
                  max(n.parallelism for n in program.nodes()))
        if ttl_secs is not None:
            job.ttl_deadline = time.time() + float(ttl_secs)
        self.jobs[job_id] = job
        if self.store is not None:
            self.store.upsert_job(job_id, pickle.dumps(program),
                                  job.checkpoint_url, n_workers,
                                  JobState.CREATED.value,
                                  ttl_deadline=job.ttl_deadline)
            self._attach_store(job, n_workers)
        self._attach_autoscaler(job_id)
        job.supervisor = asyncio.ensure_future(
            self._drive(job, n_workers, restore))
        return job_id

    async def stop_job(self, job_id: str, checkpoint: bool = True) -> None:
        job = self.jobs[job_id]
        job.stop_requested = True
        if self.store is not None:
            self.store.set_stop_requested(job_id)
        if job.fsm.state == JobState.RUNNING:
            if checkpoint:
                job.fsm.transition(JobState.CHECKPOINT_STOPPING)
                await self._trigger_checkpoint(job, then_stop=True)
            else:
                job.fsm.transition(JobState.STOPPING)
                await self._broadcast_workers(job, "StopExecution",
                                              {"job_id": job_id,
                                               "stop_mode": "graceful"})

    async def rescale_job(self, job_id: str,
                          overrides: Dict[str, int]) -> None:
        """Rescaling path (states/rescaling.rs): checkpoint-stop, update
        parallelism, reschedule with state re-sharded by key range.

        A chain is the unit of parallelism: overrides addressed to any
        chained operator are expanded to the whole chain (otherwise the
        rescale would split the chain and lose the fusion).  So is a
        factor-window group: the factor -> derived FORWARD edges carry
        keyed co-partitioning, which a parallelism split would break."""
        from ..graph.chaining import expand_overrides
        from ..graph.factor_windows import (
            expand_overrides as expand_factor_overrides,
        )

        job = self.jobs[job_id]
        # fixpoint: factor expansion can add members whose CHAINS then
        # need the override too (a derived window chaining with its
        # post-projection), and vice versa — iterate until stable
        # (override sets only grow, bounded by the node count)
        prev: Dict[str, int] = {}
        while overrides != prev:
            prev = overrides
            overrides = expand_overrides(job.program, overrides)
            overrides = expand_factor_overrides(job.program, overrides)
        # worker count from the controller's own registry, BEFORE the
        # stop: schedulers' live listings are empty once workers exit
        n_workers = max(len(job.workers), 1)
        job.fsm.transition(JobState.RESCALING)
        await self._trigger_checkpoint(job, then_stop=True)
        stop_ok = await self._await_workers_finished(job, timeout=30)
        # the stop must ALSO have produced a completed checkpoint at the
        # stop epoch: a broadcast-failure fallback (plain graceful stop)
        # or a finished-before-finalize race would otherwise restore an
        # OLDER epoch under the new topology -> duplicate output
        stop_ok = stop_ok and job.last_successful_epoch == job.epoch
        if not stop_ok:
            # the stop-checkpoint did not complete: DON'T restore from an
            # older epoch with the new topology (rewound sources would
            # duplicate output past the restore point) — abort the rescale
            # and recover the job at its CURRENT parallelism
            logger.warning("rescale of %s aborted: stop-checkpoint "
                           "incomplete", job_id)
            if job.fsm.try_recover("rescale stop-checkpoint incomplete"):
                await self._restart_workers(job, n_workers, force_stop=True)
            raise TimeoutError(
                f"rescale of {job_id} aborted (stop-checkpoint incomplete); "
                "job recovered at its previous parallelism")
        # fresh workers sized for the NEW parallelism (the old ones were
        # checkpoint-stopped above); restore re-shards state by key range
        job.program.update_parallelism(overrides)
        job.n_subtasks = sum(n.parallelism for n in job.program.nodes())
        if self.store is not None:
            self.store.set_program(job.job_id, pickle.dumps(job.program),
                                   n_workers)
        await self._restart_workers(job, n_workers, force_stop=False)
        # the rescale's restore point is now the only epoch the new
        # topology can resume from — prune retention behind it so the
        # stop-checkpoint of every rescale doesn't grow storage unbounded
        await self._prune_checkpoints(job)

    def job_state(self, job_id: str) -> JobState:
        return self.jobs[job_id].fsm.state

    async def wait_for_state(self, job_id: str, *states: JobState,
                             timeout: float = 60.0) -> JobState:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            s = self.jobs[job_id].fsm.state
            if s in states or s.terminal:
                return s
            await asyncio.sleep(0.05)
        raise TimeoutError(
            f"job {job_id} did not reach {states} (now "
            f"{self.jobs[job_id].fsm.state})")

    # -- driving the FSM ---------------------------------------------------

    async def _drive(self, job: Job, n_workers: int, restore: bool) -> None:
        try:
            job.fsm.transition(JobState.COMPILING)
            # AOT build pass (engine/aot.py): construct every physical
            # operator so a bad pipeline fails HERE, not on a worker
            # (states/compiling.rs contract); runs off-loop — expression
            # compilation can trace
            from ..engine.aot import compile_program

            report = await asyncio.get_event_loop().run_in_executor(
                None, compile_program, job.program)
            if not report.ok:
                job.fsm.fail("; ".join(report.errors))
                return
            job.fsm.transition(JobState.SCHEDULING)
            await self.scheduler.start_workers(
                job.job_id, self.addr, n_workers,
                max(1, (job.slots_needed + n_workers - 1) // n_workers))
            self._persist_workers(job)
            await self._schedule(job, n_workers, restore)
            job.fsm.transition(JobState.RUNNING)
            await self._supervise(job)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            logger.exception("job %s driver failed", job.job_id)
            if not job.fsm.state.terminal:
                job.fsm.fail(str(e))

    async def _schedule(self, job: Job, n_workers: int, restore: bool) -> None:
        # wait for registrations to satisfy the slot requirement
        # (scheduling.rs:255-290; reference timeout 10min, ours shorter)
        deadline = time.monotonic() + 60
        while sum(w.slots for w in job.workers.values()) < job.slots_needed:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"workers did not register enough slots for {job.job_id}")
            await asyncio.sleep(0.05)

        restore_epoch = None
        if restore:
            restore_epoch = self._find_restore_epoch(job)

        assignments = self._compute_assignments(job)
        tasks_payload = [
            {"operator_id": op, "subtask_index": idx, "worker_id": w}
            for (op, idx), w in assignments.items()]
        addrs = {w.worker_id: w.data_address for w in job.workers.values()}
        program_bytes = pickle.dumps(job.program)
        for w in job.workers.values():
            await w.client.call("StartExecution", {
                "job_id": job.job_id,
                "program": program_bytes,
                "tasks": tasks_payload,
                "restore_epoch": restore_epoch,
                "worker_data_addrs": addrs,
                "checkpoint_url": job.checkpoint_url,
            }, timeout=30)
        if restore_epoch is not None:
            job.epoch = restore_epoch
            job.last_successful_epoch = restore_epoch

    def _compute_assignments(self, job: Job) -> Dict[Tuple[str, int], str]:
        """Round-robin slot packing (scheduling.rs:52-75)."""
        slots: List[str] = []
        for w in sorted(job.workers.values(), key=lambda w: w.worker_id):
            slots.extend([w.worker_id] * w.slots)
        out: Dict[Tuple[str, int], str] = {}
        for node in job.program.nodes():
            for idx in range(node.parallelism):
                out[(node.operator_id, idx)] = slots[idx % len(slots)]
        return out

    def _find_restore_epoch(self, job: Job) -> Optional[int]:
        """Last checkpoint whose job-level metadata marks it complete."""
        backend = ParquetBackend.for_url(job.checkpoint_url)
        best = None
        for f in backend.storage.list(f"{job.job_id}/checkpoints"):
            if f.endswith("/metadata.json") and "checkpoint-" in f:
                part = f.split("checkpoint-")[1].split("/")[0]
                try:
                    meta = json.loads(backend.storage.get(f))
                    if meta.get("complete"):
                        ep = int(part)
                        best = ep if best is None or ep > best else best
                except Exception:
                    continue
        return best

    async def _supervise(self, job: Job) -> None:
        """JobController::progress (job_controller/mod.rs:460-584)."""
        cfg = config()
        last_ckpt = time.monotonic()
        last_slo = 0.0
        while True:
            await asyncio.sleep(0.1)
            state = job.fsm.state
            if state.terminal:
                return
            # task failure -> recovery; checked BEFORE the all-finished
            # check so a failed task draining downstream (end_of_data on
            # failure) can't race the job into FINISHED with partial output
            if state == JobState.RUNNING and job.failure is not None:
                err = job.failure
                job.failure = None
                await self._recover(job, err)
                continue
            # all workers finished?
            if job.workers and all(w.finished for w in job.workers.values()):
                if state == JobState.RUNNING:
                    job.fsm.transition(JobState.FINISHED)
                elif state in (JobState.CHECKPOINT_STOPPING,
                               JobState.STOPPING):
                    job.fsm.transition(JobState.STOPPED)
                elif state in (JobState.RESCALING, JobState.SCHEDULING,
                               JobState.RECOVERING):
                    # mid-rescale/recovery: the OLD workers drained; keep
                    # supervising — fresh workers are about to register
                    # (returning here orphaned post-rescale jobs)
                    continue
                return
            if state != JobState.RUNNING:
                continue
            # ttl reap (preview pipelines): enforced HERE so a durable
            # controller restart keeps the deadline armed
            if (job.ttl_deadline is not None
                    and time.time() > job.ttl_deadline
                    and not job.stop_requested):
                logger.info("job %s ttl expired; stopping", job.job_id)
                try:
                    await self.stop_job(job.job_id, checkpoint=False)
                except Exception:
                    logger.warning("ttl stop of %s failed", job.job_id,
                                   exc_info=True)
                continue
            # heartbeat timeout (30s)
            now = time.monotonic()
            for w in job.workers.values():
                if (not w.finished
                        and now - w.last_heartbeat > cfg.heartbeat_timeout_secs):
                    await self._recover(
                        job, f"worker {w.worker_id} heartbeat timeout")
                    break
            # SLO burn evaluation (obs/latency.py): judge the rollup's
            # headline p99/staleness against the job's declared targets
            # about once a second — violations land in the evaluator's
            # event ring + metrics, and the burn rate feeds the
            # autoscaler's latency signal
            if job.slo.configured() and now - last_slo >= 1.0:
                last_slo = now
                try:
                    lat = self.latency_shape(self.job_rollup(job.job_id))
                    job.slo_eval.evaluate(lat["p99_ms"], lat["staleness_ms"])
                except Exception:
                    logger.warning("slo evaluation for %s failed",
                                   job.job_id, exc_info=True)
            # periodic checkpoints
            if now - last_ckpt >= cfg.checkpoint_interval_secs:
                last_ckpt = now
                await self._trigger_checkpoint(job)

    async def _recover(self, job: Job, error: str) -> None:
        """Running -> Recovering -> Scheduling -> Running (states/mod.rs
        :196-202, recovering.rs)."""
        logger.warning("job %s recovering: %s", job.job_id, error)
        if not job.fsm.try_recover(error):
            await self.scheduler.stop_workers(job.job_id, force=True)
            return
        n_workers = max(len(job.workers), 1)
        await self._broadcast_workers(job, "StopExecution", {
            "job_id": job.job_id, "stop_mode": "immediate"}, ignore_errors=True)
        await self._restart_workers(job, n_workers, force_stop=True)

    async def _restart_workers(self, job: Job, n_workers: int,
                               force_stop: bool) -> None:
        """Shared stop -> clear -> Scheduling -> start -> schedule -> Running
        tail of recovery and rescale (single source for slot sizing)."""
        await self.scheduler.stop_workers(job.job_id, force=force_stop)
        await self._close_worker_clients(job)
        job.workers.clear()
        job.finished_tasks.clear()
        job.trackers.clear()
        job.fsm.transition(JobState.SCHEDULING)
        await self.scheduler.start_workers(
            job.job_id, self.addr, n_workers,
            max(1, (job.slots_needed + n_workers - 1) // n_workers))
        self._persist_workers(job)
        await self._schedule(job, n_workers, restore=True)
        job.fsm.transition(JobState.RUNNING)

    async def _trigger_checkpoint(self, job: Job,
                                  then_stop: bool = False) -> None:
        job.epoch += 1
        # incomplete epochs that missed a worker can never finish; prune
        # them so trackers don't accumulate over a long-running job
        for e in [e for e in job.trackers
                  if e <= job.epoch - 8 and not job.trackers[e].done]:
            del job.trackers[e]
        job.trackers[job.epoch] = CheckpointTracker(job.epoch, job.n_subtasks)
        payload = {
            "job_id": job.job_id, "epoch": job.epoch,
            "min_epoch": job.min_epoch, "timestamp": now_micros(),
            "then_stop": then_stop, "is_commit": False}
        if not then_stop:
            # a worker stalled in a long jit compile must not fail the
            # driver: a periodic epoch that can't reach every worker simply
            # never completes and a later one supersedes it; heartbeat
            # timeout catches real deaths
            await self._broadcast_workers(job, "Checkpoint", payload,
                                          ignore_errors=True)
            return
        try:
            await self._broadcast_workers(job, "Checkpoint", payload)
        except Exception as e:
            # a stop-checkpoint that can't reach every worker must still
            # stop the job: fall back to a plain graceful stop (the final
            # state is simply not snapshotted, as with stop(checkpoint
            # =False))
            logger.warning(
                "job %s stop-checkpoint broadcast failed (%s); falling "
                "back to graceful stop", job.job_id, e)
            await self._broadcast_workers(
                job, "StopExecution",
                {"job_id": job.job_id, "stop_mode": "graceful"},
                ignore_errors=True)

    def _persist_workers(self, job: Job) -> None:
        """Record the scheduler's external worker ids so a restarted
        controller can reap this incarnation's orphans."""
        if self.store is None:
            return
        try:
            self.store.set_workers(job.job_id,
                                   self.scheduler.workers_for_job(job.job_id))
        except NotImplementedError:
            pass

    async def _broadcast_workers(self, job: Job, method: str, payload: Dict,
                                 ignore_errors: bool = False) -> None:
        for w in job.workers.values():
            if w.finished:
                continue
            try:
                await w.client.call(method, payload)
            except Exception as e:
                if not ignore_errors:
                    raise
                logger.debug("broadcast %s to %s failed: %s", method,
                             w.worker_id, e)

    async def _await_workers_finished(self, job: Job,
                                      timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(w.finished for w in job.workers.values()):
                return True
            await asyncio.sleep(0.05)
        return False

    # -- ControllerGrpc handlers ------------------------------------------

    async def _register_worker(self, req: Dict) -> Dict:
        job = self.jobs.get(req["job_id"])
        if job is None:
            return {"error": "unknown job"}
        w = WorkerInfo(req["worker_id"], req["rpc_address"],
                       req["data_address"], req["slots"])
        w.client = RpcClient(w.rpc_address, "WorkerGrpc")
        job.workers[w.worker_id] = w
        return {}

    async def _heartbeat(self, req: Dict) -> Dict:
        job = self.jobs.get(req["job_id"])
        if job and req["worker_id"] in job.workers:
            w = job.workers[req["worker_id"]]
            w.last_heartbeat = time.monotonic()
            metrics = req.get("metrics")
            if isinstance(metrics, (bytes, bytearray)) and metrics:
                # msgpack over the wire (see rpc.proto HeartbeatReq)
                try:
                    from ..rpc.transport import _deser_msgpack

                    metrics = _deser_msgpack(bytes(metrics))
                except Exception:
                    # keep accepting heartbeats, but a persistent decode
                    # failure (worker/controller version skew) would
                    # silently blank every job rollup — say so once
                    if not self._metrics_decode_warned:
                        self._metrics_decode_warned = True
                        logger.warning(
                            "undecodable heartbeat metrics payload from "
                            "worker %s; job rollups will be empty",
                            req["worker_id"], exc_info=True)
                    metrics = None
            if metrics:
                w.prev_snapshot, w.prev_time = (w.metric_snapshot,
                                                w.snapshot_time)
                w.metric_snapshot, w.snapshot_time = (metrics,
                                                      time.monotonic())
        return {}

    # -- job-level metric aggregation -------------------------------------

    @staticmethod
    def _rollup_op(agg: Dict[str, Any], cur: Dict[str, float],
                   prev: Optional[Dict[str, float]], dt: float) -> None:
        """Fold one worker's per-operator summary into the job rollup.
        Counters/sums add across workers; rates come from the worker's own
        two newest heartbeat samples."""

        def get(src, key):
            # prometheus_client exposes counters with a _total suffix
            return src.get(key, src.get(key + "_total", 0.0)) if src else 0.0

        for key in ("messages_recv", "messages_sent", "bytes_recv",
                    "bytes_sent", "kernel_seconds", "backpressure_seconds"):
            agg[key] = agg.get(key, 0.0) + get(cur, key)
        for key in ("tx_queue_size", "tx_queue_rem"):
            agg[key] = agg.get(key, 0.0) + cur.get(key, 0.0)
        for k, v in (cur or {}).items():
            # phase profiler ride-alongs (obs/profiler.py): phase/wait
            # seconds and stall counts sum across workers; the event-loop
            # lag quantile gauges take the worst worker — one stalled
            # loop is the signal, averaging would hide it
            if k.startswith(("phase_seconds.", "wait_seconds.")) \
                    or k.startswith("event_loop_stalls") \
                    or k.startswith(("critical_path.", "device_bytes.")) \
                    or k == "e2e_latency.count":
                agg[k] = agg.get(k, 0.0) + v
            elif k.startswith("event_loop_lag") \
                    or k.startswith("e2e_latency.") \
                    or k in ("wm_age_ms", "latency_sample_n"):
                # latency quantiles / watermark ages: the worst worker
                # is the signal, summing would fabricate latencies
                agg[k] = max(agg.get(k, 0.0), v)
        # per-subtask queue pairs → worst-subtask backpressure (same
        # rationale as the lag families below: the summed gauges dilute
        # one saturated subtask among idle siblings)
        for k in cur:
            if k.startswith("tx_queue_size@"):
                size = cur[k]
                rem = cur.get("tx_queue_rem@" + k.rsplit("@", 1)[1], 0.0)
                if size > 0:
                    agg["_bp_worst"] = max(agg.get("_bp_worst", 0.0),
                                           1.0 - rem / size)
        if prev is not None and dt > 0:
            agg["records_per_sec"] = agg.get("records_per_sec", 0.0) + max(
                get(cur, "messages_sent") - get(prev, "messages_sent"),
                0.0) / dt
        # lag/latency: average over the newest heartbeat window (delta of
        # the histogram _sum/_count pair); the lifetime average only on
        # the very first sample.  A window with no new samples contributes
        # nothing — falling back to the lifetime average there would pin
        # a startup backlog's lag on the rollup forever after the
        # operator goes idle.
        for short, fam in (("event_time_lag", "event_time_lag_seconds"),
                           ("watermark_lag", "watermark_lag_seconds"),
                           ("batch_latency", "batch_processing_seconds"),
                           ("queue_wait", "queue_wait_seconds"),
                           ("checkpoint_duration",
                            "checkpoint_duration_seconds")):
            # worst across subtasks AND workers: a single lagging subtask
            # is the signal, averaging it away would hide it.  Workers
            # ship per-subtask pairs (`fam_sum@idx`) for the lag families
            # so co-located subtasks don't get averaged together; the
            # worker-summed flat pair is the fallback (checkpoint
            # histograms, legacy payloads, tests)
            sub_pairs = [(k, fam + "_count@" + k.rsplit("@", 1)[1])
                         for k in cur if k.startswith(fam + "_sum@")]
            for sk, ck in sub_pairs or [(fam + "_sum", fam + "_count")]:
                s, c = cur.get(sk, 0.0), cur.get(ck, 0.0)
                if prev is not None:
                    s -= prev.get(sk, 0.0)
                    c -= prev.get(ck, 0.0)
                if c > 0:
                    agg[short] = max(agg.get(short, 0.0), s / c)

    @staticmethod
    def _finalize_rollup(agg: Dict[str, Any],
                         age_secs: Optional[float]) -> None:
        qsize = agg.get("tx_queue_size", 0.0)
        # aggregate ratio as the floor (flat/legacy payloads), worst
        # subtask on top when the per-subtask pairs were shipped
        agg["backpressure"] = max(
            1.0 - agg.get("tx_queue_rem", 0.0) / qsize
            if qsize > 0 else 0.0,
            agg.pop("_bp_worst", 0.0))
        agg["age_secs"] = age_secs

    @classmethod
    def rollup_from_summary(
            cls, summary: Dict[str, Dict[str, float]]) -> List[Dict[str, Any]]:
        """Job-rollup-shaped aggregation of one in-process registry
        summary — the REST fallback for embedded/LocalRunner jobs the
        controller never scheduled, kept here so the fold + finalize
        logic has a single home."""
        ops = []
        for op, cur in sorted(summary.items()):
            # one in-process registry == one contributing worker
            agg: Dict[str, Any] = {"operator_id": op, "workers": 1}
            cls._rollup_op(agg, cur, None, 0.0)
            cls._finalize_rollup(agg, 0.0)  # live scrape: zero age
            ops.append(agg)
        return ops

    def job_rollup(self, job_id: str) -> List[Dict[str, Any]]:
        """Controller-aggregated per-operator rollup for one job, built
        from worker heartbeat snapshots (records/s, lag, backpressure per
        operator — what the console's DAG overlay and the REST metrics
        routes serve)."""
        job = self.jobs.get(job_id)
        if job is None:
            return []
        ops: Dict[str, Dict[str, Any]] = {}
        now = time.monotonic()
        stale_after = config().heartbeat_timeout_secs
        oldest: Optional[float] = None
        for w in job.workers.values():
            if not w.metric_snapshot:
                continue
            # finished or heartbeat-dead workers no longer describe the
            # running job: max()-ing their last (possibly backpressured)
            # snapshot in would pin the rollup hot until recovery
            if w.finished or now - w.last_heartbeat > stale_after:
                continue
            oldest = (w.snapshot_time if oldest is None
                      else min(oldest, w.snapshot_time))
            dt = w.snapshot_time - w.prev_time
            for op, cur in w.metric_snapshot.items():
                agg = ops.setdefault(op, {"operator_id": op, "workers": 0})
                agg["workers"] += 1
                self._rollup_op(
                    agg, cur,
                    (w.prev_snapshot or {}).get(op) if w.prev_snapshot
                    else None, dt)
        for agg in ops.values():
            # age of the OLDEST contributing snapshot — the newest would
            # hide one worker's staleness behind a livelier sibling's
            self._finalize_rollup(
                agg, round(now - oldest, 1) if oldest else None)
        return sorted(ops.values(), key=lambda g: g["operator_id"])

    @staticmethod
    def profile_shape(rows: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Reshape job-rollup rows into the profile view the REST
        ``profile_rollups`` route and the console DAG hover serve:
        per-operator phase/wait second maps plus host/device seconds
        (device = the always-on kernel dispatch counter), and the
        worker-level event-loop watchdog numbers aggregated under the
        ``__worker__`` pseudo-operator."""
        ops: List[Dict[str, Any]] = []
        worker: Dict[str, Any] = {}
        for row in rows:
            op = row.get("operator_id", "")
            phases = {k[len("phase_seconds."):]: round(v, 6)
                      for k, v in row.items()
                      if k.startswith("phase_seconds.")}
            waits = {k[len("wait_seconds."):]: round(v, 6)
                     for k, v in row.items()
                     if k.startswith("wait_seconds.")}
            if op == "__worker__":
                worker = {
                    "event_loop_lag_p50_secs": row.get(
                        "event_loop_lag_seconds_p50", 0.0),
                    "event_loop_lag_p99_secs": row.get(
                        "event_loop_lag_seconds_p99", 0.0),
                    "event_loop_stalls": row.get(
                        "event_loop_stalls_total",
                        row.get("event_loop_stalls", 0.0)),
                }
                continue
            if not phases and not waits:
                continue
            # host vs device split from the profiler's OWN phase table:
            # dispatch/device_execute are the kernel-bound spans, every
            # other phase is pure host envelope.  (kernel_seconds is the
            # same non-blocking dispatch wall as the `dispatch` phase —
            # re-reading it as "device" would count that span twice; it
            # only serves as the fallback when no dispatch phase was
            # recorded, e.g. a legacy worker without the profiler's
            # timed_device hook.)
            device = sum(phases.get(p, 0.0)
                         for p in ("dispatch", "device_execute"))
            if device == 0.0:
                device = row.get("kernel_seconds", 0.0)
            host = sum(phases.values()) - sum(
                phases.get(p, 0.0) for p in ("dispatch",
                                             "device_execute"))
            ops.append({
                "operator_id": op,
                "phases": phases,
                "waits": waits,
                "host_seconds": round(host, 6),
                "device_seconds": round(device, 6),
                # of this operator's measured time, how much was host
                # envelope vs kernel-bound dispatch — the per-node
                # coloring the console DAG uses
                "host_share": round(host / (host + device), 4)
                if host + device > 0 else None,
            })
        total = sum(o["host_seconds"] for o in ops)
        for o in ops:
            o["job_share"] = (round(o["host_seconds"] / total, 4)
                              if total > 0 else 0.0)
        return {"operators": ops, "worker": worker}

    @staticmethod
    def latency_shape(rows: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Reshape job-rollup rows into the latency view (REST
        ``/v1/jobs/{id}/latency`` and the console latency panel):
        per-sink e2e quantiles, per-operator watermark ages, the
        worker-level critical-path stage decomposition and the
        device-memory ledger, plus the headline p99/staleness the SLO
        evaluator judges."""
        sinks: Dict[str, Dict[str, float]] = {}
        wm_ages: Dict[str, float] = {}
        critical: Dict[str, float] = {}
        device: Dict[str, int] = {}
        sample_n = 0
        for row in rows:
            op = row.get("operator_id", "")
            if op == "__worker__":
                for k, v in row.items():
                    if k.startswith("critical_path."):
                        critical[k[len("critical_path."):]] = round(v, 6)
                    elif k.startswith("device_bytes."):
                        device[k[len("device_bytes."):]] = int(v)
                sample_n = int(row.get("latency_sample_n", 0))
            if "e2e_latency.p99_ms" in row:
                sinks[op] = {
                    "p50_ms": round(row.get("e2e_latency.p50_ms", 0.0), 3),
                    "p99_ms": round(row.get("e2e_latency.p99_ms", 0.0), 3),
                    "last_ms": round(row.get("e2e_latency.last_ms", 0.0), 3),
                    "count": int(row.get("e2e_latency.count", 0)),
                }
            if "wm_age_ms" in row:
                wm_ages[op] = round(row["wm_age_ms"], 3)
        total = sum(critical.values())
        dominant = (max(critical, key=critical.__getitem__)
                    if critical else None)
        p99 = max((q["p99_ms"] for q in sinks.values()), default=None)
        stale = max(wm_ages.values(), default=None)
        return {
            "sample_n": sample_n,
            "sinks": sinks,
            "watermark_age_ms": wm_ages,
            "critical_path": {
                "stages": critical,
                "total_secs": round(total, 6),
                "dominant": dominant,
                "dominant_share": (round(critical[dominant] / total, 4)
                                   if dominant and total > 0 else 0.0),
            },
            "device_state_bytes": device,
            "p99_ms": p99,
            "staleness_ms": stale,
        }

    def job_latency(self, job_id: str) -> Optional[Dict[str, Any]]:
        """Latency view + SLO verdict for one controller-owned job
        (None when the job is unknown — REST falls back to the local
        in-process registry there)."""
        job = self.jobs.get(job_id)
        if job is None:
            return None
        out = self.latency_shape(self.job_rollup(job_id))
        out["slo"] = job.slo_eval.to_json()
        return out

    def job_profile_rollup(self, job_id: str) -> Dict[str, Any]:
        """Phase-profile view of one job's heartbeat rollups (empty
        ``operators`` when no worker has a profiler armed)."""
        return self.profile_shape(self.job_rollup(job_id))

    async def _task_started(self, req: Dict) -> Dict:
        return {}

    async def _task_ckpt_event(self, req: Dict) -> Dict:
        return {}

    async def _task_ckpt_completed(self, req: Dict) -> Dict:
        job = self.jobs.get(req["job_id"])
        if job is None:
            return {}
        tracker = job.trackers.get(req["epoch"])
        if tracker is None:
            tracker = job.trackers.setdefault(
                req["epoch"], CheckpointTracker(req["epoch"], job.n_subtasks))
        san = getattr(self, "sanitizer", None)  # doubles skip __init__
        if san is not None:
            key = (req["operator_id"], req["subtask"])
            san.event("ckpt-done", f"{key[0]}-{key[1]}",
                      {"epoch": req["epoch"], "via": "controller"})
            if key in tracker.completed:
                # trackers are cleared on restart/rescale, so a
                # duplicate inside one tracker's life means two
                # snapshots raced for the same (member, subtask, epoch)
                san.violation(
                    "duplicate-checkpoint",
                    f"{key[0]}-{key[1]} reported checkpoint epoch "
                    f"{req['epoch']} twice within one job run")
        tracker.completed.add((req["operator_id"], req["subtask"]))
        tracker.has_committing |= bool(req.get("has_committing_data"))
        if tracker.done:
            await self._finalize_checkpoint(job, tracker)
        return {}

    async def _finalize_checkpoint(self, job: Job,
                                   tracker: CheckpointTracker) -> None:
        backend = ParquetBackend.for_url(job.checkpoint_url)
        backend.storage.put(
            f"{job.job_id}/checkpoints/checkpoint-{tracker.epoch:07d}/"
            "metadata.json",
            json.dumps({
                "complete": True, "epoch": tracker.epoch,
                "n_subtasks": tracker.n_subtasks,
                "time": now_micros(),
            }).encode())
        job.last_successful_epoch = tracker.epoch
        del job.trackers[tracker.epoch]
        if self.store is not None:
            self.store.set_progress(job.job_id, job.epoch, job.min_epoch,
                                    job.last_successful_epoch)
        # two-phase commit for sinks with commit behavior
        if tracker.has_committing:
            await self._broadcast_workers(
                job, "Commit", {"job_id": job.job_id, "epoch": tracker.epoch},
                ignore_errors=True)
        # compaction every COMPACT_EVERY epochs (mod.rs:30-31, 388-394):
        # merge per-subtask gen-0 files into key-range-partitioned gen-1
        # files, then tell workers to hot-swap (LoadCompactedData)
        compact_every = config().compact_every
        if (compact_every and tracker.epoch % compact_every == 0
                and hasattr(backend, "compact_operator")):
            loop = asyncio.get_running_loop()
            ckpt_dir = backend.checkpoint_dir(job.job_id, tracker.epoch) + "/"
            op_ids = set()
            for f in backend.storage.list(ckpt_dir):
                part = f[len(ckpt_dir):].split("/", 1)[0]
                if part.startswith("operator-"):
                    op_ids.add(part[len("operator-"):])
            for op_id in sorted(op_ids):
                # sync parquet I/O off the controller's event loop
                result = await loop.run_in_executor(
                    None, backend.compact_operator, job.job_id, op_id,
                    tracker.epoch)
                if result["to_load"]:
                    await self._broadcast_workers(
                        job, "LoadCompactedData",
                        {"job_id": job.job_id, "epoch": tracker.epoch,
                         "operator_id": op_id, "files": result["to_load"],
                         "dropped": result["to_drop"]},
                        ignore_errors=True)
        # epoch cleanup: keep the last N checkpoints (mod.rs:30, 388-394)
        await self._prune_checkpoints(job, backend=backend)

    async def _prune_checkpoints(self, job: Job, backend=None) -> None:
        """Prune to the last ``checkpoint_retention`` completed epochs.
        Runs after every successful checkpoint AND after every rescale
        restore point (state/backend cleanup_before does the listing and
        deletes, which can hit object storage — so off the event loop)."""
        if job.last_successful_epoch is None:
            return
        keep = config().checkpoint_retention
        min_epoch = max(job.last_successful_epoch - keep + 1, 0)
        if min_epoch <= job.min_epoch:
            return
        job.min_epoch = min_epoch
        if backend is None:
            backend = ParquetBackend.for_url(job.checkpoint_url)
        if self.store is not None:
            self.store.set_progress(job.job_id, job.epoch, job.min_epoch,
                                    job.last_successful_epoch)
        try:
            await asyncio.get_running_loop().run_in_executor(
                None, backend.cleanup_before, job.job_id, min_epoch)
        except Exception:
            # retention is best-effort: a storage hiccup must not fail
            # a checkpoint finalize or a completed rescale
            logger.warning("checkpoint pruning for %s failed", job.job_id,
                           exc_info=True)

    async def _task_finished(self, req: Dict) -> Dict:
        job = self.jobs.get(req["job_id"])
        if job:
            job.finished_tasks.add((req["operator_id"], req["subtask"]))
        return {}

    async def _task_failed(self, req: Dict) -> Dict:
        job = self.jobs.get(req["job_id"])
        if job:
            job.failure = (f"{req['operator_id']}-{req['subtask']}: "
                           f"{req.get('error', '')}")
        return {}

    async def _worker_finished(self, req: Dict) -> Dict:
        job = self.jobs.get(req["job_id"])
        if job and req["worker_id"] in job.workers:
            job.workers[req["worker_id"]].finished = True
        return {}

    async def _worker_error(self, req: Dict) -> Dict:
        job = self.jobs.get(req["job_id"])
        if job:
            job.failure = req.get("error", "worker error")
        return {}

    async def _send_sink_data(self, req: Dict) -> Dict:
        for q in self.sink_subscribers.get(req["job_id"], []):
            await q.put(req)
        return {}

    async def _subscribe_output(self, req: Dict):
        q: asyncio.Queue = asyncio.Queue()
        self.sink_subscribers.setdefault(req["job_id"], []).append(q)
        try:
            while True:
                item = await q.get()
                yield item
                if item.get("done"):
                    return
        finally:
            self.sink_subscribers[req["job_id"]].remove(q)


def main() -> None:
    """``python -m arroyo_tpu.controller.controller``: standalone
    controller (deploy/ role 'controller'; the API talks to it over
    gRPC from another pod)."""
    import os

    from ..obs.logging_setup import init_logging

    async def serve() -> None:
        init_logging("controller")
        ctrl = ControllerServer(host=os.environ.get("CONTROLLER_HOST",
                                                    "0.0.0.0"))
        await ctrl.start(port=int(os.environ.get("CONTROLLER_PORT",
                                                 "9190")))
        logger.info("controller grpc at %s (advertised: set "
                    "CONTROLLER_ADVERTISE_ADDR for cross-pod dialing)",
                    ctrl.addr)
        await asyncio.Event().wait()

    asyncio.run(serve())


if __name__ == "__main__":
    main()
