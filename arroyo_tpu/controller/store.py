"""Durable controller job state.

The reference persists per-job status in Postgres and, on controller
boot, resumes every job's state machine from the stored rows
(arroyo-controller/src/states/mod.rs:577-628).  Here sqlite replaces
Postgres — the same substitution the API layer makes — and the stored
program is the cloudpickled logical :class:`Program`, so a restarted
controller can re-compile, re-schedule, and restore each job from its
last completed checkpoint without the submitting client.

Also persisted: the scheduler's external worker ids (e.g. ``pid-1234``
for the process scheduler), so a restarted controller can reap orphaned
workers from its previous incarnation before starting fresh ones.
"""

from __future__ import annotations

import sqlite3
import time
from dataclasses import dataclass
from typing import List, Optional

TERMINAL_STATES = ("Stopped", "Finished", "Failed")


@dataclass
class StoredJob:
    job_id: str
    program: bytes
    checkpoint_url: str
    n_workers: int
    state: str
    epoch: int
    min_epoch: int
    last_successful_epoch: Optional[int]
    stop_requested: bool
    ttl_deadline: Optional[float] = None
    # JSON {"enabled": bool, "policy": {...}} — the autoscaler survives
    # a controller restart like the job itself does
    autoscale: Optional[str] = None


class ControllerStore:
    def __init__(self, path: str):
        self.path = path
        self.db = sqlite3.connect(path)
        self.db.execute("""
            CREATE TABLE IF NOT EXISTS jobs (
                job_id TEXT PRIMARY KEY,
                program BLOB NOT NULL,
                checkpoint_url TEXT NOT NULL,
                n_workers INTEGER NOT NULL,
                state TEXT NOT NULL,
                epoch INTEGER NOT NULL DEFAULT 0,
                min_epoch INTEGER NOT NULL DEFAULT 0,
                last_successful_epoch INTEGER,
                stop_requested INTEGER NOT NULL DEFAULT 0,
                failure TEXT,
                updated_at REAL NOT NULL,
                ttl_deadline REAL
            )""")
        try:  # stores created before the ttl column
            self.db.execute("ALTER TABLE jobs ADD COLUMN ttl_deadline REAL")
        except sqlite3.OperationalError:
            pass
        try:  # stores created before the autoscaler column
            self.db.execute("ALTER TABLE jobs ADD COLUMN autoscale TEXT")
        except sqlite3.OperationalError:
            pass
        self.db.execute("""
            CREATE TABLE IF NOT EXISTS job_workers (
                job_id TEXT NOT NULL,
                ext_id TEXT NOT NULL,
                PRIMARY KEY (job_id, ext_id)
            )""")
        self.db.commit()

    def close(self) -> None:
        self.db.close()

    # -- job rows ----------------------------------------------------------

    def upsert_job(self, job_id: str, program: bytes, checkpoint_url: str,
                   n_workers: int, state: str,
                   ttl_deadline: Optional[float] = None) -> None:
        self.db.execute(
            "INSERT INTO jobs (job_id, program, checkpoint_url, n_workers,"
            " state, updated_at, ttl_deadline) VALUES (?, ?, ?, ?, ?, ?, ?)"
            " ON CONFLICT(job_id) DO UPDATE SET program=excluded.program,"
            " checkpoint_url=excluded.checkpoint_url,"
            " n_workers=excluded.n_workers, state=excluded.state,"
            " updated_at=excluded.updated_at,"
            " ttl_deadline=excluded.ttl_deadline",
            (job_id, program, checkpoint_url, n_workers, state, time.time(),
             ttl_deadline))
        self.db.commit()

    def set_state(self, job_id: str, state: str,
                  failure: Optional[str] = None) -> None:
        self.db.execute(
            "UPDATE jobs SET state=?, failure=?, updated_at=? WHERE "
            "job_id=?", (state, failure, time.time(), job_id))
        self.db.commit()

    def set_progress(self, job_id: str, epoch: int, min_epoch: int,
                     last_successful_epoch: Optional[int]) -> None:
        self.db.execute(
            "UPDATE jobs SET epoch=?, min_epoch=?, last_successful_epoch=?,"
            " updated_at=? WHERE job_id=?",
            (epoch, min_epoch, last_successful_epoch, time.time(), job_id))
        self.db.commit()

    def set_program(self, job_id: str, program: bytes,
                    n_workers: Optional[int] = None) -> None:
        if n_workers is None:
            self.db.execute(
                "UPDATE jobs SET program=?, updated_at=? WHERE job_id=?",
                (program, time.time(), job_id))
        else:
            self.db.execute(
                "UPDATE jobs SET program=?, n_workers=?, updated_at=? "
                "WHERE job_id=?",
                (program, n_workers, time.time(), job_id))
        self.db.commit()

    def set_autoscale(self, job_id: str, spec_json: Optional[str]) -> None:
        self.db.execute(
            "UPDATE jobs SET autoscale=?, updated_at=? WHERE job_id=?",
            (spec_json, time.time(), job_id))
        self.db.commit()

    def set_stop_requested(self, job_id: str) -> None:
        self.db.execute(
            "UPDATE jobs SET stop_requested=1, updated_at=? WHERE job_id=?",
            (time.time(), job_id))
        self.db.commit()

    def resumable(self) -> List[StoredJob]:
        """Jobs a fresh controller must adopt: every non-terminal row."""
        rows = self.db.execute(
            "SELECT job_id, program, checkpoint_url, n_workers, state,"
            " epoch, min_epoch, last_successful_epoch, stop_requested,"
            " ttl_deadline, autoscale"
            " FROM jobs WHERE state NOT IN (?, ?, ?)",
            TERMINAL_STATES).fetchall()
        return [StoredJob(r[0], r[1], r[2], r[3], r[4], r[5], r[6], r[7],
                          bool(r[8]), r[9], r[10]) for r in rows]

    # -- scheduler external worker ids ------------------------------------

    def set_workers(self, job_id: str, ext_ids: List[str]) -> None:
        self.db.execute("DELETE FROM job_workers WHERE job_id=?", (job_id,))
        self.db.executemany(
            "INSERT OR IGNORE INTO job_workers (job_id, ext_id) VALUES "
            "(?, ?)", [(job_id, e) for e in ext_ids])
        self.db.commit()

    def workers(self, job_id: str) -> List[str]:
        return [r[0] for r in self.db.execute(
            "SELECT ext_id FROM job_workers WHERE job_id=?",
            (job_id,)).fetchall()]
