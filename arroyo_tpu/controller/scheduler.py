"""Schedulers: how worker processes get started
(/root/reference/arroyo-controller/src/schedulers/mod.rs trait Scheduler
:47-68 — start_workers, stop_workers, workers_for_job).

* :class:`InProcessScheduler` — workers as asyncio tasks in the controller
  process (still real gRPC + TCP over loopback); the test/dev default, the
  analog of the reference's single-process mode.
* :class:`ProcessScheduler` — spawns ``python -m arroyo_tpu.worker.server``
  subprocesses (schedulers/mod.rs:77-233).
* :class:`KubernetesScheduler` — pod-per-worker on k8s/GKE TPU pools
  (kubernetes.rs analog; slots map to TPU chips per SURVEY §2 #34).
* :class:`NodeScheduler` — workers placed on a pool of
  ``arroyo_tpu.node`` daemons (schedulers/mod.rs:316-664 analog).
* :class:`NomadScheduler` — worker-per-Nomad-batch-job (nomad.rs analog).
"""

from __future__ import annotations

import asyncio
import logging
import os
import subprocess
import sys
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)


class Scheduler:
    async def start_workers(self, job_id: str, controller_addr: str,
                            n_workers: int, slots_per_worker: int) -> None:
        raise NotImplementedError

    async def stop_workers(self, job_id: str, force: bool = False) -> None:
        raise NotImplementedError

    def workers_for_job(self, job_id: str) -> List[str]:
        raise NotImplementedError

    async def reap(self, job_id: str, ext_ids: List[str]) -> None:
        """Kill workers left over from a PREVIOUS controller incarnation
        (identified by their persisted external ids).  Default no-op:
        in-process workers die with the controller, and the k8s/nomad
        reconcilers re-own replica sets by job label on start_workers."""


class InProcessScheduler(Scheduler):
    def __init__(self) -> None:
        self._tasks: Dict[str, List[asyncio.Task]] = {}
        self._servers: Dict[str, List] = {}

    async def start_workers(self, job_id, controller_addr, n_workers,
                            slots_per_worker):
        from ..worker.server import WorkerServer

        tasks, servers = [], []
        for _ in range(n_workers):
            w = WorkerServer(controller_addr, job_id, slots_per_worker)

            async def run(w=w):
                await w.start()
                await w.wait_done()

            tasks.append(asyncio.ensure_future(run()))
            servers.append(w)
        self._tasks[job_id] = self._tasks.get(job_id, []) + tasks
        self._servers[job_id] = self._servers.get(job_id, []) + servers

    async def stop_workers(self, job_id, force=False):
        for w in self._servers.pop(job_id, []):
            try:
                await w.shutdown()
            except Exception:
                pass
        for t in self._tasks.pop(job_id, []):
            t.cancel()

    def workers_for_job(self, job_id):
        return [w.worker_id for w in self._servers.get(job_id, [])]


class ProcessScheduler(Scheduler):
    """One OS process per worker (16 slots/node default in the reference)."""

    def __init__(self) -> None:
        self._procs: Dict[str, List[subprocess.Popen]] = {}

    async def start_workers(self, job_id, controller_addr, n_workers,
                            slots_per_worker):
        from ..worker.spawn import spawn_worker_process

        procs = [spawn_worker_process(job_id, controller_addr,
                                      slots_per_worker)
                 for _ in range(n_workers)]
        self._procs[job_id] = self._procs.get(job_id, []) + procs

    async def stop_workers(self, job_id, force=False):
        for p in self._procs.pop(job_id, []):
            if force:
                p.kill()
            else:
                p.terminate()
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()

    def workers_for_job(self, job_id):
        return [f"pid-{p.pid}" for p in self._procs.get(job_id, [])
                if p.poll() is None]

    async def reap(self, job_id, ext_ids):
        """SIGKILL orphaned worker pids from a crashed controller — but
        only when the pid still runs OUR worker entrypoint (pids recycle;
        killing a stranger would be a disaster)."""
        import os
        import signal

        for ext in ext_ids:
            if not ext.startswith("pid-"):
                continue
            try:
                pid = int(ext.split("-", 1)[1])
                # arroyolint: disable=async-blocking -- tiny procfs read on the rarely-run reap path
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    cmdline = f.read()
                if b"arroyo_tpu.worker.server" in cmdline:
                    os.kill(pid, signal.SIGKILL)
            except (OSError, ValueError):
                continue  # already gone


class KubernetesApiClient:
    """Minimal in-cluster Kubernetes API client (no external deps): reads
    the service-account token and talks to the API server over HTTPS.
    Tests inject a fake with the same three methods."""

    SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

    def __init__(self, api_server: Optional[str] = None,
                 token: Optional[str] = None,
                 namespace: Optional[str] = None):
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        self.api_server = api_server or (f"https://{host}:{port}"
                                         if host else None)
        self.token = token or self._read(f"{self.SA_DIR}/token")
        self.namespace = namespace or self._read(
            f"{self.SA_DIR}/namespace") or "default"

    @staticmethod
    def _read(path: str) -> Optional[str]:
        try:
            with open(path) as f:
                return f.read().strip()
        except OSError:
            return None

    def _request(self, method: str, path: str, body=None) -> dict:
        import json as _json
        import ssl
        import urllib.request

        if not self.api_server:
            raise RuntimeError(
                "not running in a Kubernetes cluster "
                "(KUBERNETES_SERVICE_HOST unset) and no api_server given")
        req = urllib.request.Request(
            self.api_server + path, method=method,
            data=_json.dumps(body).encode() if body is not None else None,
            headers={"Authorization": f"Bearer {self.token}",
                     "Content-Type": "application/json"})
        ctx = ssl.create_default_context(
            cafile=f"{self.SA_DIR}/ca.crt"
            if os.path.exists(f"{self.SA_DIR}/ca.crt") else None)
        with urllib.request.urlopen(req, context=ctx, timeout=30) as r:
            return _json.loads(r.read() or b"{}")

    def create_replicaset(self, manifest: dict) -> dict:
        ns = manifest["metadata"]["namespace"]
        return self._request(
            "POST", f"/apis/apps/v1/namespaces/{ns}/replicasets", manifest)

    def delete_replicasets(self, namespace: str, label_selector: str) -> dict:
        return self._request(
            "DELETE",
            f"/apis/apps/v1/namespaces/{namespace}/replicasets"
            f"?labelSelector={label_selector}&propagationPolicy=Background")

    def list_pods(self, namespace: str, label_selector: str) -> dict:
        return self._request(
            "GET", f"/api/v1/namespaces/{namespace}/pods"
            f"?labelSelector={label_selector}")


class KubernetesScheduler(Scheduler):
    """Pod-per-worker scheduling on Kubernetes / GKE TPU pools
    (kubernetes.rs:28-243 analog).

    One ReplicaSet per (job, run) with ``replicas = n_workers`` worker
    pods, each advertising the controller-assigned ``slots_per_worker``
    task slots (``K8S_WORKER_SLOTS`` is the default when the controller
    does not specify).  On TPU node pools, slots map to chips: set
    ``K8S_WORKER_TPU_CHIPS`` and the pod requests ``google.com/tpu``
    resources so the GKE TPU scheduler places one worker per TPU host.
    Env-templated like every other knob in the system (the reference's
    K8S_* env family, arroyo-types lib.rs:78-129)."""

    CLUSTER_LABEL = "cluster"
    JOB_ID_LABEL = "job_id"
    RUN_ID_LABEL = "run_id"

    def __init__(self, client=None):
        import json as _json

        self.client = client  # lazily constructed in-cluster if None
        self.namespace = os.environ.get("K8S_NAMESPACE", "default")
        self.name = os.environ.get("K8S_WORKER_NAME", "arroyo-tpu") + "-worker"
        self.image = os.environ.get(
            "K8S_WORKER_IMAGE", "arroyo-tpu-worker:latest")
        self.image_pull_policy = os.environ.get(
            "K8S_WORKER_IMAGE_PULL_POLICY", "IfNotPresent")
        self.service_account = os.environ.get(
            "K8S_WORKER_SERVICE_ACCOUNT_NAME", "default")
        self.labels = _json.loads(os.environ.get("K8S_WORKER_LABELS", "{}"))
        self.annotations = _json.loads(
            os.environ.get("K8S_WORKER_ANNOTATIONS", "{}"))
        self.tpu_chips = int(os.environ.get("K8S_WORKER_TPU_CHIPS", "0"))
        self.slots_per_pod = int(os.environ.get(
            "K8S_WORKER_SLOTS", str(self.tpu_chips or 4)))
        default_res = {"requests": {"cpu": "400m", "memory": "200Mi"}}
        if self.tpu_chips:
            default_res["limits"] = {"google.com/tpu": str(self.tpu_chips)}
        self.resources = _json.loads(os.environ.get(
            "K8S_WORKER_RESOURCES", _json.dumps(default_res)))
        self.node_selector = _json.loads(os.environ.get(
            "K8S_WORKER_NODE_SELECTOR", "{}"))
        self._jobs: Dict[str, str] = {}  # job_id -> label selector
        self._runs: Dict[str, int] = {}  # job_id -> run counter
        # per-incarnation suffix: a restarted CONTROLLER resets the
        # counter, and its run 1 must not collide with a still-terminating
        # ReplicaSet from the previous incarnation's run 1
        import uuid as _uuid

        self._incarnation = _uuid.uuid4().hex[:6]

    def _get_client(self):
        if self.client is None:
            self.client = KubernetesApiClient()
        return self.client

    def make_replicaset(self, job_id: str, controller_addr: str,
                        n_workers: int, slots_per_worker: int,
                        run_id: str = "0") -> dict:
        labels = {
            self.CLUSTER_LABEL: self.name,
            self.JOB_ID_LABEL: job_id,
            self.RUN_ID_LABEL: run_id,
            **self.labels,
        }
        slots = slots_per_worker or self.slots_per_pod
        if self.tpu_chips and slots != self.tpu_chips:
            logger.warning(
                "worker advertises %d slots but pods request %d TPU chips"
                " — slots should equal chips on TPU pools",
                slots, self.tpu_chips)
        env = [
            {"name": "PROD", "value": "true"},
            {"name": "TASK_SLOTS", "value": str(slots)},
            {"name": "JOB_ID", "value": job_id},
            {"name": "RUN_ID", "value": run_id},
            {"name": "CONTROLLER_ADDR", "value": controller_addr},
        ]
        if self.tpu_chips:
            # the mesh path shards keyed state over the pod's chips
            env.append({"name": "ARROYO_MESH", "value": "auto"})
        name = (f"{self.name}-"
                f"{job_id.lower().replace('_', '-')}-{run_id}")
        return {
            "apiVersion": "apps/v1",
            "kind": "ReplicaSet",
            "metadata": {
                "name": name,
                "namespace": self.namespace,
                "labels": labels,
                "annotations": dict(self.annotations),
            },
            "spec": {
                "replicas": n_workers,
                "selector": {"matchLabels": {
                    self.JOB_ID_LABEL: job_id,
                    self.RUN_ID_LABEL: run_id,
                }},
                "template": {
                    "metadata": {"labels": labels,
                                 "annotations": dict(self.annotations)},
                    "spec": {
                        "nodeSelector": dict(self.node_selector),
                        "serviceAccountName": self.service_account,
                        "containers": [{
                            "name": "worker",
                            "image": self.image,
                            "imagePullPolicy": self.image_pull_policy,
                            "command": ["python", "-m",
                                        "arroyo_tpu.worker.server"],
                            "resources": self.resources,
                            "env": env,
                            "ports": [
                                {"containerPort": 6900, "name": "rpc"},
                                {"containerPort": 6901, "name": "admin"},
                            ],
                        }],
                    },
                },
            },
        }

    async def start_workers(self, job_id, controller_addr, n_workers,
                            slots_per_worker):
        # run_id increments per (re)start so a restarted job never
        # collides with a still-terminating ReplicaSet of the same name
        # (the reference passes the DB run_id the same way)
        self._runs[job_id] = self._runs.get(job_id, 0) + 1
        rs = self.make_replicaset(
            job_id, controller_addr, n_workers, slots_per_worker,
            run_id=f"{self._runs[job_id]}-{self._incarnation}")
        sel = (f"{self.JOB_ID_LABEL}={job_id},"
               f"{self.RUN_ID_LABEL}="
               f"{rs['metadata']['labels'][self.RUN_ID_LABEL]}")
        self._jobs[job_id] = sel
        await asyncio.get_event_loop().run_in_executor(
            None, self._get_client().create_replicaset, rs)

    async def stop_workers(self, job_id, force=False):
        sel = self._jobs.pop(job_id, f"{self.JOB_ID_LABEL}={job_id}")
        client = self._get_client()
        await asyncio.get_event_loop().run_in_executor(
            None, client.delete_replicasets, self.namespace, sel)

    def workers_for_job(self, job_id):
        sel = self._jobs.get(job_id, f"{self.JOB_ID_LABEL}={job_id}")
        pods = self._get_client().list_pods(self.namespace, sel)
        return [p["metadata"]["name"] for p in pods.get("items", [])
                if p.get("status", {}).get("phase") in ("Running", "Pending")]


class NomadApiClient:
    """Minimal Nomad HTTP API client (no external deps), mirroring the
    three calls the reference scheduler makes (nomad.rs:38-103): submit a
    job, list jobs by prefix (with Meta), and stop a job.  Tests inject a
    fake with the same three methods."""

    def __init__(self, endpoint: Optional[str] = None):
        self.endpoint = endpoint or os.environ.get(
            "NOMAD_ENDPOINT", "http://localhost:4646")

    def _request(self, method: str, path: str, body=None) -> object:
        import json as _json
        import urllib.request

        req = urllib.request.Request(
            self.endpoint + path, method=method,
            data=_json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return _json.loads(r.read() or b"{}")

    def submit_job(self, job: dict) -> dict:
        return self._request("POST", "/v1/jobs", job)

    def list_jobs(self, prefix: str) -> list:
        return self._request("GET", f"/v1/jobs?meta=true&prefix={prefix}")

    def delete_job(self, name: str) -> dict:
        return self._request("DELETE", f"/v1/job/{name}")


class NomadScheduler(Scheduler):
    """Worker-per-Nomad-job scheduling (nomad.rs:18-278 analog).

    Each worker is a ``batch`` Nomad job named ``{job_id}-{run}-{worker}``
    with restart/reschedule disabled — failure handling belongs to the
    controller FSM, not Nomad (nomad.rs:155-162).  ``workers_for_job``
    lists by name prefix and skips dead jobs (nomad.rs:63-103).  Slots per
    Nomad node and per-slot resources follow the reference's constants,
    overridable via NOMAD_* env vars."""

    def __init__(self, client=None):
        self.client = client or NomadApiClient()
        self.datacenter = os.environ.get("NOMAD_DC", "dc1")
        self.cpu_per_slot = int(os.environ.get("NOMAD_CPU_PER_SLOT", "3400"))
        self.mem_per_slot = int(os.environ.get(
            "NOMAD_MEMORY_PER_SLOT_MB", "4000"))
        self._runs: Dict[str, int] = {}

    def make_job(self, job_id: str, run_id: int, worker_id: int,
                 controller_addr: str, slots: int) -> dict:
        env = {
            "PROD": "true",
            "TASK_SLOTS": str(slots),
            "WORKER_ID": str(worker_id),
            "NODE_ID": "1",
            "JOB_ID": job_id,
            "RUN_ID": str(run_id),
            "CONTROLLER_ADDR": controller_addr,
        }
        return {"Job": {
            "ID": f"{job_id}-{run_id}-{worker_id}",
            "Name": f"{job_id}-{run_id}-{worker_id}",
            "Type": "batch",
            "Datacenters": [self.datacenter],
            "Meta": {
                "job_id": job_id,
                "worker_id": str(worker_id),
                "run_id": str(run_id),
            },
            "TaskGroups": [{
                "Name": "worker",
                "Count": 1,
                # the controller owns failure handling (nomad.rs:155-162);
                # in the Nomad JSON API these policies live on the
                # TaskGroup, not the Job
                "RestartPolicy": {"Attempts": 0, "Mode": "fail"},
                "ReschedulePolicy": {"Attempts": 0, "Unlimited": False},
                "Tasks": [{
                    "Name": "worker",
                    "Driver": "exec",
                    "Config": {
                        "command": "python",
                        "args": ["-m", "arroyo_tpu.worker.server"],
                    },
                    "Env": env,
                    "Resources": {
                        "CPU": self.cpu_per_slot * slots,
                        "MemoryMB": self.mem_per_slot * slots,
                    },
                }],
            }],
        }}

    async def start_workers(self, job_id, controller_addr, n_workers,
                            slots_per_worker):
        import random

        run_id = self._runs[job_id] = self._runs.get(job_id, 0) + 1
        loop = asyncio.get_event_loop()
        for _ in range(n_workers):
            worker_id = random.getrandbits(32)
            job = self.make_job(job_id, run_id, worker_id, controller_addr,
                                slots_per_worker)
            await loop.run_in_executor(None, self.client.submit_job, job)

    def _live_jobs(self, job_id: str) -> List[dict]:
        run = self._runs.get(job_id)
        prefix = f"{job_id}-{run}-" if run is not None else f"{job_id}-"
        jobs = self.client.list_jobs(prefix)
        return [j for j in jobs if j.get("Status") != "dead"]

    async def stop_workers(self, job_id, force=False):
        loop = asyncio.get_event_loop()
        # the listing is a blocking HTTP call too: keep it off the loop
        live = await loop.run_in_executor(None, self._live_jobs, job_id)
        for j in live:
            name = j.get("Name") or j.get("ID")
            try:
                await loop.run_in_executor(None, self.client.delete_job, name)
            except Exception:
                logger.warning("failed to stop nomad job %s", name)

    def workers_for_job(self, job_id):
        return [j["Meta"]["worker_id"] for j in self._live_jobs(job_id)
                if j.get("Meta", {}).get("worker_id")]


class NodeScheduler(Scheduler):
    """Schedule workers onto a pool of node daemons
    (schedulers/mod.rs:316-664 NodeScheduler analog; daemons are
    arroyo_tpu.node.daemon processes).  The pool is env-configured:
    ``NODE_ADDRS=host1:9290,host2:9290`` (the reference's nodes register
    dynamically; a static pool keeps the control plane one-directional).
    Workers are round-robined across nodes."""

    def __init__(self, node_addrs: Optional[List[str]] = None):
        addrs = node_addrs or [
            a.strip() for a in os.environ.get("NODE_ADDRS", "").split(",")
            if a.strip()]
        if not addrs:
            raise ValueError("NodeScheduler needs NODE_ADDRS")
        self.node_addrs = addrs
        self._rr = 0
        # job_id -> list of (node_addr, worker_id)
        self._workers: Dict[str, List] = {}

    def _client(self, addr: str):
        from ..rpc.transport import RpcClient

        return RpcClient(addr, "NodeGrpc")

    async def start_workers(self, job_id, controller_addr, n_workers,
                            slots_per_worker):
        placed = self._workers.setdefault(job_id, [])
        for _ in range(n_workers):
            addr = self.node_addrs[self._rr % len(self.node_addrs)]
            self._rr += 1
            client = self._client(addr)
            try:
                resp = await client.call("StartWorker", {
                    "job_id": job_id,
                    "controller_addr": controller_addr,
                    "slots": slots_per_worker,
                })
            finally:
                await client.close()
            placed.append((addr, resp["worker_id"]))

    async def stop_workers(self, job_id, force=False):
        for addr, wid in self._workers.pop(job_id, []):
            client = self._client(addr)
            try:
                await client.call("StopWorker",
                                  {"worker_id": wid, "force": force})
            except Exception:
                logger.warning("StopWorker %s on %s failed", wid, addr)
            finally:
                await client.close()

    def workers_for_job(self, job_id):
        return [wid for _addr, wid in self._workers.get(job_id, [])]


def scheduler_from_env() -> Scheduler:
    """SCHEDULER env selection (schedulers/mod.rs:70-76 analog):
    'process' (default), 'kubernetes'/'k8s', or 'embedded'."""
    mode = os.environ.get("SCHEDULER", "process").lower()
    if mode in ("kubernetes", "k8s"):
        return KubernetesScheduler()
    if mode in ("embedded", "inprocess"):
        return InProcessScheduler()
    if mode == "node":
        return NodeScheduler()
    if mode == "nomad":
        return NomadScheduler()
    if mode in ("process", ""):
        return ProcessScheduler()
    # a typo must fail fast, not silently spawn subprocesses in the
    # controller container
    raise ValueError(f"unknown SCHEDULER {mode!r}; "
                     "expected process | kubernetes | embedded | node | nomad")
