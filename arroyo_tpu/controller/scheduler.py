"""Schedulers: how worker processes get started
(/root/reference/arroyo-controller/src/schedulers/mod.rs trait Scheduler
:47-68 — start_workers, stop_workers, workers_for_job).

* :class:`InProcessScheduler` — workers as asyncio tasks in the controller
  process (still real gRPC + TCP over loopback); the test/dev default, the
  analog of the reference's single-process mode.
* :class:`ProcessScheduler` — spawns ``python -m arroyo_tpu.worker.server``
  subprocesses (schedulers/mod.rs:77-233).
* Kubernetes/TPU-pod scheduling (kubernetes.rs analog): round 2 — slots map
  to TPU chips per SURVEY §2 #34.
"""

from __future__ import annotations

import asyncio
import logging
import os
import subprocess
import sys
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)


class Scheduler:
    async def start_workers(self, job_id: str, controller_addr: str,
                            n_workers: int, slots_per_worker: int) -> None:
        raise NotImplementedError

    async def stop_workers(self, job_id: str, force: bool = False) -> None:
        raise NotImplementedError

    def workers_for_job(self, job_id: str) -> List[str]:
        raise NotImplementedError


class InProcessScheduler(Scheduler):
    def __init__(self) -> None:
        self._tasks: Dict[str, List[asyncio.Task]] = {}
        self._servers: Dict[str, List] = {}

    async def start_workers(self, job_id, controller_addr, n_workers,
                            slots_per_worker):
        from ..worker.server import WorkerServer

        tasks, servers = [], []
        for _ in range(n_workers):
            w = WorkerServer(controller_addr, job_id, slots_per_worker)

            async def run(w=w):
                await w.start()
                await w.wait_done()

            tasks.append(asyncio.ensure_future(run()))
            servers.append(w)
        self._tasks[job_id] = self._tasks.get(job_id, []) + tasks
        self._servers[job_id] = self._servers.get(job_id, []) + servers

    async def stop_workers(self, job_id, force=False):
        for w in self._servers.pop(job_id, []):
            try:
                await w.shutdown()
            except Exception:
                pass
        for t in self._tasks.pop(job_id, []):
            t.cancel()

    def workers_for_job(self, job_id):
        return [w.worker_id for w in self._servers.get(job_id, [])]


class ProcessScheduler(Scheduler):
    """One OS process per worker (16 slots/node default in the reference)."""

    def __init__(self) -> None:
        self._procs: Dict[str, List[subprocess.Popen]] = {}

    async def start_workers(self, job_id, controller_addr, n_workers,
                            slots_per_worker):
        # workers must import this package regardless of their cwd
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        procs = []
        for _ in range(n_workers):
            env = dict(os.environ)
            env.update({
                "CONTROLLER_ADDR": controller_addr,
                "JOB_ID": job_id,
                "TASK_SLOTS": str(slots_per_worker),
                "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu"),
                "PYTHONPATH": (pkg_root + os.pathsep + env["PYTHONPATH"]
                               if env.get("PYTHONPATH") else pkg_root),
            })
            if env["JAX_PLATFORMS"] == "cpu":
                # a CPU worker must not wake the axon TPU-tunnel plugin
                # (its sitecustomize runs at interpreter start and can
                # stall the process on tunnel handshakes)
                env.pop("PALLAS_AXON_POOL_IPS", None)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "arroyo_tpu.worker.server"], env=env))
        self._procs[job_id] = self._procs.get(job_id, []) + procs

    async def stop_workers(self, job_id, force=False):
        for p in self._procs.pop(job_id, []):
            if force:
                p.kill()
            else:
                p.terminate()
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()

    def workers_for_job(self, job_id):
        return [f"pid-{p.pid}" for p in self._procs.get(job_id, [])
                if p.poll() is None]
