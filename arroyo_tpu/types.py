"""Core data model for the TPU-native streaming engine.

This is the analog of the reference's ``arroyo-types`` crate
(/root/reference/arroyo-types/src/lib.rs): the message taxonomy
(Record/Barrier/Watermark/Stop/EndOfData, lib.rs:280-286), watermarks
(lib.rs:273-277), checkpoint barriers (lib.rs:741-747), task metadata and the
key-range partitioning functions ``server_for_hash``/``range_for_server``
(lib.rs:822-836) whose semantics are reproduced exactly so that state sharding
and rescale-by-key-range behave identically.

The central difference from the reference: the unit of dataflow is not a single
``Record<K, T>`` but a columnar :class:`Batch` of records (numpy arrays on the
host, staged to device inside jitted operator kernels).  Event time is int64
microseconds since the unix epoch, matching Arrow's timestamp(us).
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, Iterator, Mapping, Optional, Sequence, Tuple, Union

import numpy as np

U64_MAX = np.uint64(0xFFFF_FFFF_FFFF_FFFF)

# Reserved timestamp value used as the "final" watermark on close, mirroring the
# reference's u64::MAX final watermark (arroyo-worker/src/operators/mod.rs:179-186).
MAX_TIMESTAMP = np.int64(2**63 - 1)
MIN_TIMESTAMP = np.int64(-(2**63))


def now_micros() -> int:
    """Current wall-clock time in microseconds (event-time domain)."""
    return _time.time_ns() // 1_000


# ---------------------------------------------------------------------------
# Key-range partitioning (arroyo-types/src/lib.rs:822-836 semantics)
# ---------------------------------------------------------------------------


def server_for_hash(x: int, n: int) -> int:
    """Map a u64 key hash to one of ``n`` contiguous key ranges.

    Matches the reference exactly: ``range_size = u64::MAX / n``;
    ``min(n - 1, x / range_size)``.
    """
    range_size = int(U64_MAX) // n
    return min(n - 1, int(x) // range_size)


def server_for_hash_array(x: np.ndarray, n: int) -> np.ndarray:
    """Vectorized :func:`server_for_hash` over a uint64 array."""
    range_size = np.uint64(int(U64_MAX) // n)
    idx = (x.astype(np.uint64) // range_size).astype(np.int64)
    return np.minimum(idx, n - 1)


def route_shift_for(parallelism: int) -> int:
    """Key-hash bits a mesh route step must skip at operator
    parallelism ``P``: subtask key ranges (:func:`server_for_hash`)
    consume the top ``ceil(log2(P))`` bits, so device routing has to
    start below them or every subtask's key slice funnels onto
    ~``nk/P`` devices (the PR 9 funneling class).

    This is the single source of truth for BOTH the engine wiring
    (``BinAggOperator.on_start`` -> ``MeshKeyedBinState.set_route_shift``)
    and the shardcheck static model (``analysis/shardcheck.py``) — the
    two may never drift apart independently, and the smoke drift gate
    cross-checks the combined prediction against the live
    ``reshard_transfers`` counter.
    """
    p = int(parallelism)
    return (p - 1).bit_length() if p > 1 else 0


def range_for_server(i: int, n: int) -> Tuple[int, int]:
    """Inclusive [start, end] u64 key range owned by shard ``i`` of ``n``."""
    range_size = int(U64_MAX) // n
    start = range_size * i
    end = int(U64_MAX) if i + 1 == n else start + range_size - 1
    return (start, end)


def ranges_overlap(a: Tuple[int, int], b: Tuple[int, int]) -> bool:
    return a[0] <= b[1] and b[0] <= a[1]


# ---------------------------------------------------------------------------
# Hashing: stable vectorized 64-bit key hashing
# ---------------------------------------------------------------------------

_SPLITMIX_C1 = np.uint64(0xBF58476D1CE4E5B9)
_SPLITMIX_C2 = np.uint64(0x94D049BB133111EB)
_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _py_hash_u64(x: np.ndarray) -> np.ndarray:
    """numpy splitmix64 — the reference implementation the native library
    must match bit-for-bit (tests enforce parity)."""
    with np.errstate(over="ignore"):
        z = x.astype(np.uint64) + _GOLDEN
        z = (z ^ (z >> np.uint64(30))) * _SPLITMIX_C1
        z = (z ^ (z >> np.uint64(27))) * _SPLITMIX_C2
        return z ^ (z >> np.uint64(31))


def hash_u64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer over an integer array -> uint64 hashes.

    Used to spread integer keys uniformly over the u64 ring so that
    key-range sharding balances (the reference relies on ahash for the same
    property; exact hash values need only be internally consistent).
    Dispatches to the C++ host library when loaded.
    """
    from . import native

    if native.HAVE_NATIVE:
        return native.hash_u64(np.asarray(x))
    return _py_hash_u64(np.asarray(x))


def hash_any_column(col: np.ndarray) -> np.ndarray:
    """Hash an arbitrary column (ints, floats, strings/objects) to uint64."""
    if np.issubdtype(col.dtype, np.integer):
        return hash_u64(col)
    if np.issubdtype(col.dtype, np.floating):
        return hash_u64(col.astype(np.float64).view(np.uint64))
    # Strings / objects: pandas' stable vectorized hash.
    import pandas as pd

    return pd.util.hash_array(np.asarray(col, dtype=object), categorize=False)


def hash_columns(cols: Sequence[np.ndarray]) -> np.ndarray:
    """Combine multiple column hashes into one composite uint64 key hash."""
    from . import native

    assert cols, "need at least one key column"
    acc = hash_any_column(cols[0])
    if native.HAVE_NATIVE:
        for c in cols[1:]:
            acc = native.hash_combine(acc, hash_any_column(c))
        return acc
    with np.errstate(over="ignore"):
        for c in cols[1:]:
            acc = _py_hash_u64(acc * np.uint64(31) + hash_any_column(c))
    return acc


# ---------------------------------------------------------------------------
# Batch: the columnar record envelope
# ---------------------------------------------------------------------------


@dataclass
class Batch:
    """A columnar batch of records flowing along one dataflow edge.

    ``timestamp`` is int64 event-time micros (one per row); ``key_hash`` is the
    uint64 hash of the key columns (present iff the edge is keyed);
    ``columns`` maps column name -> numpy array (object dtype for strings).

    This replaces the reference's per-record ``Record{timestamp, key, value}``
    (arroyo-types/src/lib.rs:295-299) with a batch the device kernels can
    consume directly.
    """

    timestamp: np.ndarray  # int64[n] micros
    columns: Dict[str, np.ndarray]
    key_hash: Optional[np.ndarray] = None  # uint64[n]
    key_cols: Tuple[str, ...] = ()
    # Latency-observatory ingest stamp (obs/latency.py): wall-clock micros of
    # the oldest sampled record this batch carries, or None when sampling is
    # off / the batch holds no sample.  A side-channel annotation rather than
    # a hidden column so the coalescer/sanitizer/data-plane schema signatures
    # (which read only columns/key_cols/key_hash) provably never flip when
    # sampling arms mid-stream.
    lat_stamp: Optional[int] = None

    def __post_init__(self) -> None:
        self.timestamp = np.asarray(self.timestamp, dtype=np.int64)

    def __len__(self) -> int:
        return int(self.timestamp.shape[0])

    @property
    def num_rows(self) -> int:
        return len(self)

    def column(self, name: str) -> np.ndarray:
        return self.columns[name]

    def with_key(self, key_cols: Sequence[str]) -> "Batch":
        """Return a batch keyed by ``key_cols`` (computes key_hash)."""
        kh = hash_columns([self.columns[c] for c in key_cols])
        return Batch(self.timestamp, dict(self.columns), kh, tuple(key_cols),
                     lat_stamp=self.lat_stamp)

    def select(self, mask_or_idx: np.ndarray) -> "Batch":
        """Row subset by boolean mask or integer index array."""
        cols = {k: v[mask_or_idx] for k, v in self.columns.items()}
        kh = self.key_hash[mask_or_idx] if self.key_hash is not None else None
        return Batch(self.timestamp[mask_or_idx], cols, kh, self.key_cols,
                     lat_stamp=self.lat_stamp)

    def project(self, names: Sequence[str]) -> "Batch":
        cols = {n: self.columns[n] for n in names}
        return Batch(self.timestamp, cols, self.key_hash, self.key_cols,
                     lat_stamp=self.lat_stamp)

    @staticmethod
    def concat(batches: Sequence["Batch"]) -> "Batch":
        assert batches
        if len(batches) == 1:
            return batches[0]
        ts = np.concatenate([b.timestamp for b in batches])
        names = batches[0].columns.keys()
        cols = {n: np.concatenate([b.columns[n] for b in batches]) for n in names}
        kh = None
        if batches[0].key_hash is not None:
            kh = np.concatenate([b.key_hash for b in batches])
        # Oldest sampled ingest wins: coalescer linger is charged to latency.
        stamps = [b.lat_stamp for b in batches if b.lat_stamp is not None]
        return Batch(ts, cols, kh, batches[0].key_cols,
                     lat_stamp=min(stamps) if stamps else None)

    @staticmethod
    def empty_like(other: "Batch") -> "Batch":
        return other.select(np.zeros(0, dtype=np.int64))

    def schema(self) -> Dict[str, np.dtype]:
        return {k: v.dtype for k, v in self.columns.items()}

    # Arrow interop (used by parquet sinks / checkpoints / network IPC).
    def arrow_arrays(self) -> Dict[str, Any]:
        """Column name -> pyarrow array, the single home of the
        numpy->arrow conversion rules (checkpoints and the wire encoder
        must never diverge on them)."""
        import pyarrow as pa

        arrays = {"__timestamp": pa.array(self.timestamp, type=pa.int64())}
        for k, v in self.columns.items():
            arrays[k] = pa.array(v.tolist() if v.dtype == object else v)
        return arrays

    def to_arrow(self):
        import pyarrow as pa

        return pa.table(self.arrow_arrays())

    @staticmethod
    def from_arrow(table) -> "Batch":
        cols = {}
        ts = None
        for name in table.column_names:
            arr = table.column(name).combine_chunks().to_numpy(zero_copy_only=False)
            if name == "__timestamp":
                ts = arr.astype(np.int64)
            else:
                cols[name] = arr
        assert ts is not None, "arrow table missing __timestamp"
        return Batch(ts, cols)


# ---------------------------------------------------------------------------
# Watermarks, barriers, control messages
# ---------------------------------------------------------------------------


class WatermarkKind(Enum):
    EVENT_TIME = "event_time"
    IDLE = "idle"


@dataclass(frozen=True)
class Watermark:
    """Watermark::{EventTime(t), Idle} (arroyo-types/src/lib.rs:273-277)."""

    kind: WatermarkKind
    time: int = 0  # micros; meaningful iff kind == EVENT_TIME

    @staticmethod
    def event_time(t: int) -> "Watermark":
        return Watermark(WatermarkKind.EVENT_TIME, int(t))

    @staticmethod
    def idle() -> "Watermark":
        return Watermark(WatermarkKind.IDLE)

    @property
    def is_idle(self) -> bool:
        return self.kind == WatermarkKind.IDLE


@dataclass(frozen=True)
class CheckpointBarrier:
    """CheckpointBarrier{epoch, min_epoch, timestamp, then_stop}
    (arroyo-types/src/lib.rs:741-747)."""

    epoch: int
    min_epoch: int
    timestamp: int  # micros
    then_stop: bool = False


class MessageKind(Enum):
    RECORD = "record"
    WATERMARK = "watermark"
    BARRIER = "barrier"
    STOP = "stop"
    END_OF_DATA = "end_of_data"


@dataclass
class Message:
    """Message::{Record, Barrier, Watermark, Stop, EndOfData}
    (arroyo-types/src/lib.rs:280-286), batch-first."""

    kind: MessageKind
    batch: Optional[Batch] = None
    watermark: Optional[Watermark] = None
    barrier: Optional[CheckpointBarrier] = None

    @staticmethod
    def record(batch: Batch) -> "Message":
        return Message(MessageKind.RECORD, batch=batch)

    @staticmethod
    def wm(w: Watermark) -> "Message":
        return Message(MessageKind.WATERMARK, watermark=w)

    @staticmethod
    def barrier_msg(b: CheckpointBarrier) -> "Message":
        return Message(MessageKind.BARRIER, barrier=b)

    @staticmethod
    def stop() -> "Message":
        return Message(MessageKind.STOP)

    @staticmethod
    def end_of_data() -> "Message":
        return Message(MessageKind.END_OF_DATA)

    @property
    def is_end(self) -> bool:
        return self.kind in (MessageKind.STOP, MessageKind.END_OF_DATA)


# ---------------------------------------------------------------------------
# Updating / retraction data model (arroyo-types/src/lib.rs:315-507)
# ---------------------------------------------------------------------------


class UpdateOp(Enum):
    """Row-level operation for updating streams (Debezium c/u/d model)."""

    CREATE = 0
    UPDATE = 1
    DELETE = 2


UPDATE_OP_COLUMN = "__op"  # int8 column carrying UpdateOp on updating edges
RETRACT_OLD_PREFIX = "__old__"  # old-value columns for UPDATE rows


# ---------------------------------------------------------------------------
# Task metadata
# ---------------------------------------------------------------------------


@dataclass
class TaskInfo:
    """TaskInfo (arroyo-types/src/lib.rs:558-586): identity + key range of one
    parallel subtask of one operator."""

    job_id: str
    operator_id: str
    operator_name: str
    task_index: int
    parallelism: int

    @property
    def key_range(self) -> Tuple[int, int]:
        return range_for_server(self.task_index, self.parallelism)

    def owns_hash(self, h: int) -> bool:
        lo, hi = self.key_range
        return lo <= int(h) <= hi

    @property
    def task_id(self) -> str:
        return f"{self.operator_id}-{self.task_index}"


# ---------------------------------------------------------------------------
# Control plane messages (arroyo-rpc/src/lib.rs:26-100 analogs)
# ---------------------------------------------------------------------------


class StopMode(Enum):
    GRACEFUL = "graceful"  # propagate Stop through the dataflow
    IMMEDIATE = "immediate"  # stop now


@dataclass
class ControlMessage:
    """Controller/worker -> task control messages (ControlMessage enum,
    arroyo-rpc/src/lib.rs:26-47)."""

    kind: str  # 'checkpoint' | 'stop' | 'commit' | 'load_compacted' | 'no_op'
    barrier: Optional[CheckpointBarrier] = None
    stop_mode: Optional[StopMode] = None
    epoch: Optional[int] = None
    compacted: Optional[Any] = None

    @staticmethod
    def checkpoint(barrier: CheckpointBarrier) -> "ControlMessage":
        return ControlMessage("checkpoint", barrier=barrier)

    @staticmethod
    def stop(mode: StopMode = StopMode.GRACEFUL) -> "ControlMessage":
        return ControlMessage("stop", stop_mode=mode)

    @staticmethod
    def commit(epoch: int) -> "ControlMessage":
        return ControlMessage("commit", epoch=epoch)


class CheckpointEventType(Enum):
    """Per-subtask checkpoint lifecycle events (rpc.proto:34-45)."""

    STARTED_ALIGNMENT = "started_alignment"
    STARTED_CHECKPOINTING = "started_checkpointing"
    FINISHED_OPERATOR_SETUP = "finished_operator_setup"
    FINISHED_SYNC = "finished_sync"
    FINISHED_COMMIT = "finished_commit"


@dataclass
class CheckpointEvent:
    checkpoint_epoch: int
    operator_id: str
    subtask_index: int
    time: int
    event_type: CheckpointEventType


@dataclass
class SubtaskCheckpointMetadata:
    epoch: int
    operator_id: str
    subtask_index: int
    start_time: int
    finish_time: int
    bytes: int
    tables: Dict[str, "TableCheckpointMetadata"] = field(default_factory=dict)
    watermark: Optional[int] = None
    committing_data: Optional[Dict[str, Any]] = None


@dataclass
class TableCheckpointMetadata:
    table: str
    files: Tuple[str, ...] = ()
    min_key_hash: int = 0
    max_key_hash: int = int(U64_MAX)


@dataclass
class ControlResp:
    """Task -> controller responses (ControlResp, arroyo-rpc/src/lib.rs:60-100)."""

    kind: str  # 'checkpoint_event'|'checkpoint_completed'|'task_started'|'task_finished'|'task_failed'|'error'
    operator_id: str = ""
    task_index: int = 0
    checkpoint_event: Optional[CheckpointEvent] = None
    subtask_metadata: Optional[SubtaskCheckpointMetadata] = None
    error: Optional[str] = None
