"""Wire formats: bytes <-> rows <-> columnar Batch.

Analog of the reference's serde layer
(/root/reference/arroyo-worker/src/formats.rs:11-131): JSON deserialization
with confluent-schema-registry framing (5-byte header strip), unstructured
("raw json into a single `value` column") mode, raw string format, and a
``DataSerializer`` that renders batches back to bytes for sinks — including
the ``include_schema`` envelope and Debezium-style updating envelopes
(arroyo-types/src/lib.rs:315-507 retraction model).

Everything is batch-oriented: a connector hands a list of raw payloads to
``Format.deserialize`` and gets one columnar :class:`~arroyo_tpu.types.Batch`
back, ready for the jitted device operators.
"""

from __future__ import annotations

import functools
import json
import math
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .types import Batch, now_micros


def fast_decode_enabled() -> bool:
    """``ARROYO_FAST_DECODE=0`` disables every vectorized serde fast
    path — decode *and* encode — so the formats reproduce the
    row-at-a-time legacy path bit-for-bit (the full escape hatch the
    fast-vs-legacy smoke gate and parity tests pin).  Read per call so
    tests can toggle it without rebuilding format instances."""
    return os.environ.get("ARROYO_FAST_DECODE", "1") not in ("0", "off",
                                                             "false")

# Debezium operation codes -> our UpdateOp-style ops.  The reference models
# these as UpdatingData::{Append,Update,Retract} (arroyo-types/src/lib.rs:359-420).
_DEBEZIUM_OPS = {"c": "append", "r": "append", "u": "update", "d": "retract"}

# Reserved column carrying the updating-op for retraction streams; matches
# the planner's convention for UpdatingData flows.
OP_COLUMN = "__op"


def rows_to_columns(rows: Sequence[Dict[str, Any]]) -> Dict[str, np.ndarray]:
    """Pivot a list of JSON-ish dict rows into typed numpy columns.

    Columns with missing fields become float64 with NaN (all-numeric) or
    object columns keeping the Nones; fully-present columns coerce to
    bool/int64/float64 and otherwise stay ``object`` (string) columns,
    mirroring arrow's permissive JSON reader.
    """
    names: Dict[str, None] = {}
    # arroyolint: disable=row-loop -- THE pinned legacy pivot: the fast decode paths fall back to exactly this on schema drift / ARROYO_FAST_DECODE=0
    for r in rows:
        for k in r:
            names.setdefault(k)
    cols: Dict[str, np.ndarray] = {}
    for k in names:
        # arroyolint: disable=row-loop -- THE pinned legacy pivot: the fast decode paths fall back to exactly this on schema drift / ARROYO_FAST_DECODE=0
        vs = [r.get(k) for r in rows]
        # Dispatch on the *JSON* types, never by attempted coercion: a column
        # of digit strings ("01234") must stay a string column.
        present = [v for v in vs if v is not None]
        has_none = len(present) < len(vs)
        if not present:
            arr = np.array(vs, dtype=object)  # untyped: keep the Nones
        elif all(isinstance(v, bool) for v in present):
            # nullable bool stays a bool-typed (object) column so sinks
            # emit true/false consistently whether or not the batch had a
            # null; numeric consumers coerce via coerce_float
            arr = (np.array(vs, dtype=object) if has_none
                   else np.array(vs, dtype=bool))
        elif all(isinstance(v, int) and not isinstance(v, bool)
                 for v in present):
            if has_none:
                arr = np.array([np.nan if v is None else v for v in vs],
                               dtype=np.float64)
            else:
                try:
                    arr = np.array(vs, dtype=np.int64)
                except OverflowError:
                    arr = np.array(vs, dtype=object)
        elif all(isinstance(v, (int, float)) and not isinstance(v, bool)
                 for v in present):
            arr = np.array([np.nan if v is None else v for v in vs],
                           dtype=np.float64)
        else:
            arr = np.array(vs, dtype=object)
        cols[k] = arr
    return cols


def batch_from_rows(rows: Sequence[Dict[str, Any]],
                    timestamp_field: Optional[str] = None) -> Batch:
    """Build a Batch from dict rows; event time from ``timestamp_field``
    (int64 micros) or ingestion time."""
    cols = rows_to_columns(rows)
    if timestamp_field and timestamp_field in cols:
        ts = cols[timestamp_field].astype(np.int64)
    else:
        ts = np.full(len(rows), now_micros(), dtype=np.int64)
    return Batch(ts, cols)


def coerce_object_col(v: np.ndarray):
    """Lift an object-dtype nullable column into (typed values, validity).

    JSON rows with missing bools/ints produce object arrays; device code
    rejects object dtype, so Nones become the validity mask and the rest
    gets its natural dtype (None fills: False / NaN).  Columns whose
    non-null values aren't scalars (strings, lists) return unchanged with
    mask None — those stay on the host path.
    """
    # fast path: a string in front means a string column — skip the O(n)
    # scans (if a later row were numeric the column is mixed-type and the
    # host path is the correct destination anyway)
    for x in v[:64]:
        if x is not None:
            if isinstance(x, str):
                return v, None
            break
    mask = np.fromiter((x is not None for x in v), bool, len(v))
    present = [x for x in v if x is not None]
    if not present:
        return np.zeros(len(v), dtype=np.float32), mask
    # type decisions look at every value — mixed-type columns (number in
    # one row, string in another) must stay on the host path, not crash
    if all(isinstance(x, bool) for x in present):
        vals = np.fromiter((x if x is not None else False for x in v),
                           bool, len(v))
        return vals, (None if mask.all() else mask)
    if all(isinstance(x, (int, float)) and not isinstance(x, bool)
           for x in present):
        vals = np.array([np.nan if x is None else float(x) for x in v],
                        dtype=np.float64)
        return vals, (None if mask.all() else mask)
    return v, None


def nan_validity(v, m):
    """Combine an explicit validity mask with the engine's implicit NULL
    encodings: NaN rows in float columns and None rows in unmasked
    object columns.  Returns the combined mask, or None when every row
    is valid.  THE single definition — IS NULL, COUNT(col) indicators,
    UDAF null filters, and any other null-sensitive consumer must route
    through here so the modalities cannot drift."""
    import jax.numpy as jnp

    if isinstance(v, np.ndarray) and v.dtype == object:
        nn = np.array([x is not None and x == x for x in v], dtype=bool)
        return nn if m is None else (m & nn)
    if isinstance(v, np.ndarray) and v.dtype.kind == "f":
        # numpy fast path: host callers (join-key nonces, the
        # COUNT(DISTINCT) sort) must not bounce through the default
        # device — each readback is ~70 ms on a tunneled TPU
        nn = ~np.isnan(v)
        return nn if m is None else (m & nn)
    if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating):
        nn = ~jnp.isnan(v)
        return nn if m is None else (m & nn)
    return m


def coerce_float(arr: np.ndarray, dtype=np.float32) -> np.ndarray:
    """Numeric view of a column for aggregation inputs: None (in object
    columns from nullable JSON) becomes NaN instead of raising."""
    if arr.dtype == object:
        return np.array([np.nan if v is None else float(v) for v in arr],
                        dtype=dtype)
    return arr.astype(dtype)


def batch_to_rows(batch: Batch) -> List[Dict[str, Any]]:
    names = list(batch.columns)
    cols = [batch.columns[n] for n in names]
    # arroyolint: disable=row-loop -- the row-path escape: only envelope formats and inexpressible columns reach this materialization
    return [
        {n: _py(c[i]) for n, c in zip(names, cols)}
        for i in range(len(batch))
    ]


def _py(v: Any) -> Any:
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        f = float(v)
        return None if f != f else f
    if isinstance(v, np.bool_):
        return bool(v)
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return v


# ---------------------------------------------------------------------------
# Vectorized JSON egress (the decode fast path's mirror image)
# ---------------------------------------------------------------------------


def _float_cell(v: float, nan_literal: str) -> str:
    # json.dumps renders floats with float.__repr__ and the non-finite
    # literals below; NaN is the caller's choice because the two legacy
    # encoders disagree (JsonFormat nulls it via _py, the single_file
    # sink's default hook keeps the NaN literal)
    if v != v:
        return nan_literal
    if math.isinf(v):
        return "Infinity" if v > 0 else "-Infinity"
    return repr(v)


def _json_cells(col: np.ndarray, nan_literal: str) -> Optional[List[str]]:
    """One JSON-encoded text cell per row for a whole column, dispatched
    by dtype instead of per value.  ``None`` means the column holds
    something the vectorized encoders don't express (nested lists,
    dicts, arbitrary objects) and the caller must take the legacy
    row-at-a-time path."""
    kind = col.dtype.kind
    if kind in "iu":
        return col.astype(str).tolist()
    if kind == "f":
        return [_float_cell(v, nan_literal) for v in col.tolist()]
    if kind == "b":
        return np.where(col, "true", "false").tolist()
    if col.dtype == object or kind == "U":
        out: List[str] = []
        dumps = json.dumps
        # tolist() for BOTH kinds: a 'U' column would otherwise yield
        # np.str_ cells that walk the whole isinstance chain per cell
        for v in col.tolist():
            if v is None:
                out.append("null")
            elif type(v) is str:
                out.append(dumps(v))
            elif isinstance(v, (bool, np.bool_)):
                out.append("true" if v else "false")
            elif isinstance(v, (int, np.integer)):
                out.append(str(int(v)))
            elif isinstance(v, np.floating):
                # must precede the plain-float branch: np.float64
                # SUBCLASSES float, and repr(np.float64) renders
                # 'np.float64(x)' under numpy>=2 — corrupt JSON; the
                # legacy _py path also nulls np.floating NaN, which the
                # python-float branch's 'NaN' literal would not
                out.append(_float_cell(float(v), nan_literal))
            elif isinstance(v, float):
                # a python-float NaN in an object column survives _py
                # untouched, so legacy json.dumps emits the literal
                out.append(_float_cell(v, "NaN"))
            elif isinstance(v, np.str_):
                out.append(dumps(str(v)))
            elif isinstance(v, bytes):
                out.append(dumps(v.decode("utf-8", "replace")))
            else:
                return None  # nested lists/dicts: row path handles them
        return out
    return None  # datetimes etc: no vectorized encoder


@functools.lru_cache(maxsize=256)
def _row_template(names: tuple) -> str:
    """Schema-once render template: the per-row byte layout is fixed by
    the column names, so the object framing, key quoting and the legacy
    ``json.dumps`` separators are baked in exactly once per schema."""
    # arroyolint: disable=row-loop -- iterates column NAMES once per schema (lru_cache), never per row
    return "{" + ", ".join(
        json.dumps(n).replace("%", "%%") + ": %s" for n in names) + "}"


def encode_json_lines(batch: Batch,
                      nan_literal: str = "null") -> Optional[List[str]]:
    """Render a whole Batch to JSON-object text lines with zero per-row
    Python: one encoded-cell pass per column, one template substitution
    per row.  Returns ``None`` when a column isn't expressible — the
    caller falls back to its legacy per-row ``json.dumps`` loop (whose
    output this function otherwise matches byte for byte)."""
    names = tuple(batch.columns)
    if not names:
        return ["{}"] * len(batch)
    cells: List[List[str]] = []
    for n in names:
        c = _json_cells(batch.columns[n], nan_literal)
        if c is None:
            return None
        cells.append(c)
    template = _row_template(names)
    return [template % t for t in zip(*cells)]


# ---------------------------------------------------------------------------
# Formats
# ---------------------------------------------------------------------------


class _TransientColumnarError(ValueError):
    """Columnar fast-path failure caused by one batch's DATA (not the
    stream's structure): fall back for that batch without disabling the
    fast path."""


class Format:
    """bytes[] -> rows and rows -> bytes[].  Stateless and reusable."""

    name = "abstract"

    def deserialize(self, payloads: Sequence[bytes]) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def serialize(self, rows: Sequence[Dict[str, Any]]) -> List[bytes]:
        raise NotImplementedError

    # Convenience: straight to/from Batch.
    def batch(self, payloads: Sequence[bytes],
              timestamp_field: Optional[str] = None) -> Batch:
        return batch_from_rows(self.deserialize(payloads), timestamp_field)

    def serialize_batch(self, batch: Batch) -> List[bytes]:
        return self.serialize(batch_to_rows(batch))


@dataclass
class JsonFormat(Format):
    """JSON object per payload (formats.rs JsonFormat).

    - ``confluent_schema_registry``: strip the 5-byte magic+schema-id header
      the confluent serializers prepend (formats.rs:30-41).
    - ``unstructured``: don't parse fields; put the whole payload string in a
      single ``value`` column (formats.rs "raw json").
    - ``include_schema``: on serialize, wrap rows in a Kafka-Connect-style
      ``{"schema": ..., "payload": ...}`` envelope.
    - ``debezium``: payloads are Debezium envelopes; unwrap before/after into
      rows carrying an ``__op`` retraction column.
    """

    name: str = "json"
    confluent_schema_registry: bool = False
    unstructured: bool = False
    include_schema: bool = False
    debezium: bool = False

    def _strip(self, p: bytes) -> bytes:
        if self.confluent_schema_registry and len(p) >= 5 and p[0] == 0:
            return p[5:]
        return p

    def batch(self, payloads: Sequence[bytes],
              timestamp_field: Optional[str] = None) -> Batch:
        """Columnar fast path: plain JSON objects parse as one NDJSON
        block through pyarrow (~5x the per-row json.loads path — the
        kafka/json hot loop) with the stream's Arrow schema locked
        after the first batch; without pyarrow, one C-level bulk parse
        of the whole batch feeds the exact legacy pivot (~3x).
        Structural shapes the columnar reader can't express (debezium
        envelopes, unstructured, schema envelopes) and
        ``ARROYO_FAST_DECODE=0`` take the legacy row path."""
        if (self.debezium or self.unstructured or self.include_schema
                or not fast_decode_enabled()):
            return batch_from_rows(self.deserialize(payloads),
                                   timestamp_field)
        if getattr(self, "_arrow_ok", True):
            try:
                return self._batch_arrow(payloads, timestamp_field)
            except ImportError:
                # no pyarrow in this environment: never retry the import
                # on the hot path — the bulk path below takes over
                self._arrow_ok = False
            except _TransientColumnarError:
                # per-record data glitch (e.g. one payload missing the
                # timestamp field): row-path THIS batch only, keep the
                # fast path for the well-formed rest of the stream
                return batch_from_rows(self.deserialize(payloads),
                                       timestamp_field)
            except Exception:
                # payload shape the arrow reader can't express (nested
                # objects, arrays, mixed types): the bulk path pivots
                # through the legacy type rules, which express anything
                # the row path does — switch to it for this stream
                self._arrow_ok = False
        return self._batch_bulk(payloads, timestamp_field)

    def _join_payloads(self, payloads: Sequence[bytes], sep: bytes):
        """Frame a batch of payloads as ONE buffer for a single parser
        invocation — the shared framing home of the arrow and bulk fast
        paths (the two must never drift).  Hot path: a list of bytes
        with nothing to strip joins directly (a None/str mid-list
        raises TypeError there and falls to the general path).
        Returns ``(buf, count)``; ``(None, 0)`` when nothing remains."""
        if not self.confluent_schema_registry and isinstance(
                payloads, list) and payloads and \
                isinstance(payloads[0], bytes):
            try:
                return sep.join(payloads), len(payloads)
            except TypeError:
                pass  # mixed payload types: general path below
        # arroyolint: disable=row-loop -- mixed-type payload framing fallback; the bytes-only hot path is the single join above
        raw = [self._strip(p if isinstance(p, bytes) else str(p).encode())
               for p in payloads if p is not None]
        if not raw:
            return None, 0
        return sep.join(raw), len(raw)

    def _batch_bulk(self, payloads: Sequence[bytes],
                    timestamp_field: Optional[str]) -> Batch:
        """Vectorized fallback without pyarrow: ONE ``json.loads`` of
        the whole batch (payloads joined into a JSON array) replaces
        len(payloads) parser invocations; the pivot is the same
        :func:`rows_to_columns`, so null/bool/digit-string semantics
        are the legacy path's by construction.  After 3 consecutive
        failures the stream stops paying the doomed join+parse and
        stays on the row path."""
        if getattr(self, "_bulk_fails", 0) < 3:
            try:
                buf, _ = self._join_payloads(payloads, b",")
                objs = json.loads(b"[" + buf + b"]") if buf is not None \
                    else []
                self._bulk_fails = 0
                return batch_from_rows(self._normalize_objs(objs),
                                       timestamp_field)
            except Exception:
                # a payload the array join mis-frames (embedded control
                # chars, truncated docs): the row path is authoritative
                # — it surfaces the real error or succeeds
                self._bulk_fails = getattr(self, "_bulk_fails", 0) + 1
        return batch_from_rows(self.deserialize(payloads), timestamp_field)

    def _normalize_objs(self, objs: List[Any]) -> List[Dict[str, Any]]:
        """Parsed-object -> row normalization shared by the bulk fast
        path and (modulo parsing) ``deserialize``: arrays flatten to
        their dict elements, scalars wrap in a ``value`` column."""
        rows: List[Dict[str, Any]] = []
        for obj in objs:
            if isinstance(obj, dict):
                rows.append(obj)
            elif isinstance(obj, list):
                rows.extend(o for o in obj if isinstance(o, dict))
            else:
                rows.append({"value": obj})
        return rows

    def _batch_arrow(self, payloads: Sequence[bytes],
                     timestamp_field: Optional[str]) -> Batch:
        buf, n = self._join_payloads(payloads, b"\n")
        if buf is None:
            return Batch(np.zeros(0, dtype=np.int64), {})
        return self._batch_arrow_raw(buf, n, timestamp_field)

    def _batch_arrow_raw(self, buf: bytes, n_rows: int,
                         timestamp_field: Optional[str]) -> Batch:
        import io

        import pyarrow as pa
        import pyarrow.json as paj

        # schema-once: the first batch locks the stream's Arrow schema;
        # later batches parse against it explicitly (no per-batch type
        # inference, and the column set stays stable — a field absent
        # from one batch null-fills instead of vanishing, which keeps
        # the downstream coalescer/data-plane signatures from flapping).
        # Genuinely new fields still appear via unexpected-field
        # inference; a type conflict is schema drift: re-read with
        # inference and re-lock.
        locked = getattr(self, "_pa_schema", None)
        try:
            if locked is not None:
                tbl = paj.read_json(io.BytesIO(buf), parse_options=(
                    paj.ParseOptions(explicit_schema=locked)))
            else:
                tbl = paj.read_json(io.BytesIO(buf))
        except pa.ArrowInvalid:
            if locked is None:
                raise
            self._pa_schema = None
            tbl = paj.read_json(io.BytesIO(buf))
        self._pa_schema = tbl.schema
        if len(tbl) != n_rows:
            raise ValueError("row-count mismatch (multi-object payloads)")
        cols: Dict[str, np.ndarray] = {}
        for name in tbl.column_names:
            col = tbl.column(name).combine_chunks()
            t = col.type
            if pa.types.is_integer(t) and col.null_count == 0:
                cols[name] = col.to_numpy().astype(np.int64)
            elif pa.types.is_floating(t) or (
                    pa.types.is_integer(t) and col.null_count):
                cols[name] = col.to_numpy(zero_copy_only=False).astype(
                    np.float64)
            elif pa.types.is_boolean(t) and col.null_count == 0:
                cols[name] = col.to_numpy(zero_copy_only=False)
            elif (pa.types.is_string(t) or pa.types.is_large_string(t)
                  or pa.types.is_null(t) or pa.types.is_boolean(t)):
                out = np.empty(len(col), dtype=object)
                out[:] = col.to_pylist()
                cols[name] = out
            else:  # struct/list/timestamp payloads: row path handles them
                raise ValueError(f"non-scalar column {name}: {t}")
        if timestamp_field and timestamp_field in cols:
            tcol = cols[timestamp_field]
            if tcol.dtype.kind == "f" and not np.isfinite(tcol).all():
                # a payload missing the timestamp field surfaced as a
                # null -> NaN, and astype(int64) on NaN is undefined
                # behavior (platform-dependent garbage event times); the
                # row path handles missing fields explicitly.  This is a
                # per-record data glitch, not a structural payload shape
                # — it must NOT latch the fast path off for the stream.
                raise _TransientColumnarError(
                    f"null {timestamp_field!r} in columnar JSON batch")
            ts = tcol.astype(np.int64)
        else:
            ts = np.full(n_rows, now_micros(), dtype=np.int64)
        return Batch(ts, cols)

    def deserialize(self, payloads: Sequence[bytes]) -> List[Dict[str, Any]]:
        rows: List[Dict[str, Any]] = []
        for p in payloads:
            if p is None:
                continue
            raw = self._strip(p if isinstance(p, bytes) else str(p).encode())
            if self.unstructured:
                rows.append({"value": raw.decode("utf-8", "replace")})
                continue
            obj = json.loads(raw)
            if self.debezium:
                rows.extend(self._unwrap_debezium(obj))
            elif isinstance(obj, dict) and self.include_schema and \
                    "payload" in obj and "schema" in obj:
                rows.append(obj["payload"])
            elif isinstance(obj, list):
                rows.extend(o for o in obj if isinstance(o, dict))
            elif isinstance(obj, dict):
                rows.append(obj)
            else:
                rows.append({"value": obj})
        return rows

    def _unwrap_debezium(self, obj: Dict[str, Any]) -> List[Dict[str, Any]]:
        env = obj.get("payload", obj)
        op = _DEBEZIUM_OPS.get(env.get("op", "c"), "append")
        out: List[Dict[str, Any]] = []
        if op == "update":
            # update = retract(before) + append(after), the reference's
            # UpdatingData::Update {old, new} (arroyo-types/src/lib.rs:364-372)
            if env.get("before") is not None:
                out.append({**env["before"], OP_COLUMN: "retract"})
            if env.get("after") is not None:
                out.append({**env["after"], OP_COLUMN: "append"})
        elif op == "retract":
            if env.get("before") is not None:
                out.append({**env["before"], OP_COLUMN: "retract"})
        else:
            if env.get("after") is not None:
                out.append({**env["after"], OP_COLUMN: "append"})
        return out

    def serialize(self, rows: Sequence[Dict[str, Any]]) -> List[bytes]:
        out = []
        for r in rows:
            if self.debezium:
                op = r.get(OP_COLUMN, "append")
                body = {k: v for k, v in r.items() if k != OP_COLUMN}
                env = {"before": body if op == "retract" else None,
                       "after": None if op == "retract" else body,
                       "op": "d" if op == "retract" else "c"}
                out.append(json.dumps(env, default=_py).encode())
            elif self.include_schema:
                env = {"schema": json_schema_for_rows([r]), "payload": r}
                out.append(json.dumps(env, default=_py).encode())
            else:
                out.append(json.dumps(r, default=_py).encode())
        return out

    def serialize_batch(self, batch: Batch) -> List[bytes]:
        """Vectorized egress: one encoded-cell pass per column plus a
        schema-once row template replace the per-row dict build and
        ``json.dumps`` (~2x, byte-identical output).  Envelope modes
        (debezium / include_schema) and ``ARROYO_FAST_DECODE=0`` keep
        the legacy row path; so does any column the cell encoders
        can't express."""
        if (self.debezium or self.include_schema
                or not fast_decode_enabled()):
            return self.serialize(batch_to_rows(batch))
        lines = encode_json_lines(batch)
        if lines is None:
            return self.serialize(batch_to_rows(batch))
        # arroyolint: disable=row-loop -- one C-level encode per outgoing payload; the JSON render itself is vectorized (encode_json_lines)
        return [line.encode() for line in lines]


@dataclass
class RawStringFormat(Format):
    """One UTF-8 string per payload in/out of a single ``value`` column
    (formats.rs RawStringFormat)."""

    name: str = "raw_string"

    def deserialize(self, payloads: Sequence[bytes]) -> List[Dict[str, Any]]:
        return [{"value": (p if isinstance(p, str)
                           else p.decode("utf-8", "replace"))}
                for p in payloads if p is not None]

    def serialize(self, rows: Sequence[Dict[str, Any]]) -> List[bytes]:
        out = []
        for r in rows:
            v = r.get("value")
            if v is None and len(r) == 1:
                v = next(iter(r.values()))
            elif v is None:
                v = json.dumps(r, default=_py)
            out.append(str(v).encode())
        return out


def json_schema_for_rows(rows: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Infer a JSON-schema-shaped descriptor from sample rows — the analog of
    the reference's DataSerializer json-schema generation (formats.rs:90-131)
    and the API's schema inference (arroyo-api/src/json_schema.rs)."""
    props: Dict[str, Dict[str, Any]] = {}
    for r in rows:
        for k, v in r.items():
            t = _json_type(v)
            if k not in props:
                props[k] = {"type": t}
            elif props[k]["type"] != t and v is not None:
                props[k]["type"] = "string"  # widen on conflict
    return {"type": "object", "properties": props}


def _json_type(v: Any) -> str:
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, (int, np.integer)):
        return "integer"
    if isinstance(v, (float, np.floating)):
        return "number"
    if v is None:
        return "null"
    if isinstance(v, (list, np.ndarray)):
        return "array"
    if isinstance(v, dict):
        return "object"
    return "string"


def make_format(name: str, **opts: Any) -> Format:
    """Format factory keyed by the connector config's ``format`` field."""
    if name in ("json", "debezium_json"):
        return JsonFormat(debezium=(name == "debezium_json"), **opts)
    if name in ("raw", "raw_string"):
        return RawStringFormat()
    if name == "avro":
        return AvroFormat(**opts)
    raise ValueError(f"unknown format: {name!r}")


def columns_from_json_schema(schema: Dict[str, Any]) -> List[Dict[str, str]]:
    """JSON schema -> column list (the API's test_schema path,
    arroyo-api/src/json_schema.rs: schemas must flatten to typed
    columns).  Raises on non-object roots and unsupported types."""
    t0 = schema.get("type")
    if isinstance(t0, list):  # nullable object root/nested
        t0 = next((x for x in t0 if x != "null"), None)
    if t0 != "object":
        raise ValueError("schema root must be an object")
    kind_of = {"integer": "bigint", "number": "double", "string": "text",
               "boolean": "boolean"}
    cols = []
    for name, spec in (schema.get("properties") or {}).items():
        t = spec.get("type")
        if isinstance(t, list):  # nullable union like ["integer", "null"]
            t = next((x for x in t if x != "null"), None)
        if t == "object":
            for sub in columns_from_json_schema(spec):
                cols.append({"name": f"{name}.{sub['name']}",
                             "type": sub["type"]})
            continue
        if t not in kind_of:
            raise ValueError(f"unsupported type {t!r} for field {name!r}")
        fmt = spec.get("format", "")
        cols.append({"name": name,
                     "type": "timestamp" if "date-time" in fmt
                     else kind_of[t]})
    if not cols:
        raise ValueError("schema has no supported properties")
    return cols


# ---------------------------------------------------------------------------
# Avro (binary encoding, pure python)
# ---------------------------------------------------------------------------


def _zigzag_encode(n: int) -> bytes:
    """Avro long: zigzag + varint."""
    z = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag_decode(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    acc = 0
    while True:
        b = buf[pos]
        pos += 1
        acc |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1), pos


def avro_schema_for_rows(rows: Sequence[Dict[str, Any]],
                         name: str = "Record") -> Dict[str, Any]:
    """Infer an Avro record schema from sample rows (nullable unions for
    every field, mirroring json_schema_for_rows)."""
    fields: Dict[str, str] = {}
    for r in rows:
        for k, v in r.items():
            if isinstance(v, bool):
                t = "boolean"
            elif isinstance(v, (int, np.integer)):
                t = "long"
            elif isinstance(v, (float, np.floating)):
                t = "double"
            elif v is None:
                continue
            else:
                t = "string"
            prev = fields.get(k)
            fields[k] = t if prev in (None, t) else "string"
    return {"type": "record", "name": name,
            "fields": [{"name": k, "type": ["null", t]}
                       for k, t in fields.items()]}


class AvroFormat(Format):
    """Avro binary serde against a record schema.

    The reference leaves Avro as a TODO (formats.rs:11-131 handles json/raw
    only); this implements the single-record binary encoding with optional
    Confluent wire framing (magic 0 + 4-byte schema id), the layout Kafka
    schema-registry producers emit.  Schemas: every field is a nullable
    union ``["null", T]`` with T in {boolean, long, double, string, bytes}.
    """

    def __init__(self, schema: Optional[Dict[str, Any]] = None,
                 confluent_schema_registry: bool = False,
                 schema_id: int = 0,
                 schema_registry_url: Optional[str] = None,
                 subject: Optional[str] = None, **_ignored):
        if isinstance(schema, str):
            schema = json.loads(schema)
        self.schema = schema
        self.confluent = confluent_schema_registry or bool(
            schema_registry_url)
        self.schema_id = schema_id
        self.registry_url = schema_registry_url
        self.subject = subject
        # schema-json -> registered id (inferred schemas can change
        # batch to batch, so memoize per schema, not per instance)
        self._registered: Dict[str, int] = {}
        self._fts_by_id: Dict[int, List[Tuple[str, str]]] = {}

    def _registry(self):
        from .connectors.schema_registry import registry_client

        return registry_client(self.registry_url)

    SUPPORTED = {"boolean", "int", "long", "float", "double", "string",
                 "bytes"}

    def _field_types(self, schema=None) -> List[Tuple[str, str]]:
        schema = schema or self.schema
        if schema is None:
            raise ValueError("avro format needs a schema")
        out = []
        for f in schema["fields"]:
            t = f["type"]
            # the wire layout implemented here is exactly ["null", T]
            # unions (null = branch 0); anything else would be silently
            # mis-framed, so reject it loudly
            if not (isinstance(t, list) and len(t) == 2 and t[0] == "null"):
                raise ValueError(
                    f"avro field {f['name']!r}: only [\"null\", T] unions "
                    f"are supported (got {t!r})")
            t = t[1]
            if isinstance(t, dict):
                # logical types annotate an underlying type whose WIRE
                # encoding is authoritative (uuid -> string, decimal ->
                # bytes, timestamp-micros -> long)
                t = t.get("type", "string")
            if t not in self.SUPPORTED:
                raise ValueError(
                    f"avro field {f['name']!r}: unsupported type {t!r}")
            out.append((f["name"], t))
        return out

    # -- encode -------------------------------------------------------

    def _encode_value(self, t: str, v: Any) -> bytes:
        import struct

        if t == "boolean":
            return b"\x01" if v else b"\x00"
        if t in ("long", "int"):
            return _zigzag_encode(int(v))
        if t == "double":
            return struct.pack("<d", float(v))
        if t == "float":
            return struct.pack("<f", float(v))
        if t == "bytes":
            raw = bytes(v)
            return _zigzag_encode(len(raw)) + raw
        # string (default)
        raw = str(v).encode()
        return _zigzag_encode(len(raw)) + raw

    def serialize(self, rows: Sequence[Dict[str, Any]]) -> List[bytes]:
        # no configured schema: infer per call (Format contract says
        # stateless; a job needing a stable cross-batch schema must
        # configure one)
        schema = self.schema or avro_schema_for_rows(rows)
        fts = self._field_types(schema)
        out = []
        sid = self.schema_id
        if self.registry_url:
            # register (memoized per schema text — the inferred schema
            # can change batch to batch); the returned global id rides
            # the confluent wire header so any registry-aware consumer
            # can resolve the writer schema
            text = json.dumps(schema, sort_keys=True)
            if text not in self._registered:
                self._registered[text] = self._registry().register(
                    self.subject or f"{schema.get('name', 'record')}-value",
                    schema)
            sid = self._registered[text]
        header = (b"\x00" + sid.to_bytes(4, "big")
                  if self.confluent else b"")
        for r in rows:
            buf = bytearray(header)
            for name, t in fts:
                v = r.get(name)
                if v is None:
                    buf += _zigzag_encode(0)  # union branch 0 = null
                else:
                    buf += _zigzag_encode(1)  # union branch 1 = T
                    buf += self._encode_value(t, v)
            out.append(bytes(buf))
        return out

    # -- decode -------------------------------------------------------

    def _decode_value(self, t: str, buf: bytes, pos: int) -> Tuple[Any, int]:
        import struct

        if t == "boolean":
            return buf[pos] != 0, pos + 1
        if t in ("long", "int"):
            return _zigzag_decode(buf, pos)
        if t == "double":
            return struct.unpack_from("<d", buf, pos)[0], pos + 8
        if t == "float":
            return struct.unpack_from("<f", buf, pos)[0], pos + 4
        n, pos = _zigzag_decode(buf, pos)
        raw = buf[pos:pos + n]
        return (raw if t == "bytes" else raw.decode()), pos + n

    def deserialize(self, payloads: Sequence[bytes]) -> List[Dict[str, Any]]:
        own_fts = self._field_types() if self.schema is not None else None
        rows = []
        for p in payloads:
            # confluent framing guard (mirrors JsonFormat): only strip the
            # 5-byte header when it is actually present
            pos = 5 if (self.confluent and len(p) >= 5 and p[0] == 0) else 0
            if pos and self.registry_url:
                # resolve the WRITER schema from the header id — payloads
                # may be written under a different (evolved) schema than
                # the table DDL declares.  Per-payload, memoized by id, so
                # a framed payload's schema never leaks onto an unframed
                # neighbor in the same batch
                sid = int.from_bytes(p[1:5], "big")
                fts = self._fts_by_id.get(sid)
                if fts is None:
                    fts = self._field_types(self._registry().get_schema(sid))
                    self._fts_by_id[sid] = fts
            else:
                fts = own_fts
            if fts is None:
                raise ValueError(
                    "avro format needs a schema (or a schema_registry_url "
                    "with confluent framing)")
            row: Dict[str, Any] = {}
            for name, t in fts:
                branch, pos = _zigzag_decode(p, pos)
                if branch == 0:
                    row[name] = None
                else:
                    row[name], pos = self._decode_value(t, p, pos)
            rows.append(row)
        return rows
