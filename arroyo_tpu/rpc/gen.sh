#!/bin/sh
# Regenerate the protobuf message classes for the control-plane wire.
# (grpcio-tools is not required: services are bound by generic handlers
# in transport.py, so only message classes are generated.)
cd "$(dirname "$0")"
protoc --python_out=gen --proto_path=proto proto/rpc.proto
