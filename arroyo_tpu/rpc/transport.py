"""Protobuf-over-gRPC transport for the control plane.

The wire IS the schema at rpc/proto/rpc.proto (parity with the
reference's tonic services, arroyo-rpc/proto/rpc.proto): every request/
response is a protobuf message from the generated ``rpc_pb2``, carried
over grpc.aio.  grpcio-tools is not in the image, so instead of
generated stubs the services are bound with grpc *generic handlers*,
and the message classes come from ``protoc --python_out`` (gen.sh).

Handlers and callers keep the runtime's dict interface: dicts are
mapped to/from protobuf messages by field descriptor (including
repeated, map<,> and message-typed fields).  Services not declared in
the proto fall back to msgpack payloads (explicitly logged) so ad-hoc
test services still work.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator, Callable, Dict, Optional, Tuple

import grpc
import msgpack

from .gen import rpc_pb2

logger = logging.getLogger(__name__)

_FD = rpc_pb2.DESCRIPTOR


def _msg_cls(descriptor):
    return getattr(rpc_pb2, descriptor.name)


def _method_types(service: str, method: str):
    """(request class, response class, server_streaming) from the proto,
    or None when the service/method isn't declared there."""
    svc = _FD.services_by_name.get(service)
    if svc is None:
        return None
    m = svc.methods_by_name.get(method)
    if m is None:
        return None
    return _msg_cls(m.input_type), _msg_cls(m.output_type), m.server_streaming


def dict_to_proto(msg, d: Optional[Dict]) -> Any:
    """Fill protobuf message ``msg`` from a dict (None values = unset)."""
    for k, v in (d or {}).items():
        if v is None:
            continue
        f = msg.DESCRIPTOR.fields_by_name.get(k)
        if f is None:
            raise KeyError(
                f"{msg.DESCRIPTOR.name} has no field {k!r} "
                f"(have {sorted(msg.DESCRIPTOR.fields_by_name)})")
        if f.message_type is not None and f.message_type.GetOptions().map_entry:
            getattr(msg, k).update(v)
        elif f.is_repeated:
            if f.message_type is not None:
                for item in v:
                    dict_to_proto(getattr(msg, k).add(), item)
            else:
                getattr(msg, k).extend(_scalar(x) for x in v)
        elif f.message_type is not None:
            dict_to_proto(getattr(msg, k), v)
        else:
            setattr(msg, k, _scalar(v))
    return msg


def _scalar(v: Any) -> Any:
    # numpy ints/floats leak into payloads (epochs, watermarks); protobuf
    # setters want native python scalars
    if hasattr(v, "item") and not isinstance(v, (bytes, str)):
        return v.item()
    return v


def proto_to_dict(msg) -> Dict:
    """Dict view of a protobuf message: plain fields always present (with
    proto3 defaults), explicit-presence (optional) fields only when set."""
    out: Dict[str, Any] = {}
    for f in msg.DESCRIPTOR.fields:
        if f.message_type is not None and f.message_type.GetOptions().map_entry:
            out[f.name] = dict(getattr(msg, f.name))
        elif f.is_repeated:
            v = getattr(msg, f.name)
            out[f.name] = ([proto_to_dict(i) for i in v]
                           if f.message_type is not None else list(v))
        elif f.message_type is not None:
            if msg.HasField(f.name):
                out[f.name] = proto_to_dict(getattr(msg, f.name))
        elif f.has_presence:
            if msg.HasField(f.name):
                out[f.name] = getattr(msg, f.name)
        else:
            out[f.name] = getattr(msg, f.name)
    return out


def _ser_msgpack(obj: Any) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def _deser_msgpack(data: bytes) -> Any:
    return msgpack.unpackb(data, raw=False)


class RpcServer:
    """grpc.aio server hosting proto-declared services (protobuf wire)
    and, as a fallback, ad-hoc msgpack services."""

    def __init__(self) -> None:
        self._services: Dict[str, Dict[str, Callable]] = {}
        self._streams: Dict[str, Dict[str, Callable]] = {}
        self.server: Optional[grpc.aio.Server] = None
        self.port: Optional[int] = None

    def add_service(self, service: str, methods: Dict[str, Callable],
                    stream_methods: Optional[Dict[str, Callable]] = None
                    ) -> None:
        """methods: name -> async fn(request_dict) -> response_dict;
        stream_methods: name -> async gen fn(request_dict) -> yields dicts."""
        if service not in _FD.services_by_name:
            logger.warning("service %s not in rpc.proto: msgpack fallback",
                           service)
        self._services[service] = methods
        self._streams[service] = stream_methods or {}

    def _codecs(self, svc: str, method: str
                ) -> Tuple[Callable, Callable, Callable]:
        """(decode request bytes->dict, encode response dict->bytes) pair
        plus the stream encoder for this method."""
        types = _method_types(svc, method)
        if types is None:
            return (_deser_msgpack, _ser_msgpack, _ser_msgpack)
        req_cls, resp_cls, _ = types

        def dec(data: bytes) -> Dict:
            return proto_to_dict(req_cls.FromString(data))

        def enc(d: Optional[Dict]) -> bytes:
            return dict_to_proto(resp_cls(), d).SerializeToString()

        return dec, enc, enc

    async def start(self, host: str = "0.0.0.0", port: int = 0) -> int:
        self.server = grpc.aio.server()

        outer = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                # method path: /package.Service/Method
                path = handler_call_details.method
                try:
                    _, svc, method = path.split("/")
                except ValueError:
                    return None
                svc = svc.rsplit(".", 1)[-1]
                methods = outer._services.get(svc, {})
                streams = outer._streams.get(svc, {})
                if method in methods:
                    fn = methods[method]
                    dec, enc, _ = outer._codecs(svc, method)

                    async def unary(request, context):
                        try:
                            return enc(await fn(dec(request)))
                        except Exception as e:  # surface as grpc error
                            logger.exception("rpc %s failed", path)
                            await context.abort(
                                grpc.StatusCode.INTERNAL, str(e))

                    return grpc.unary_unary_rpc_method_handler(
                        unary, request_deserializer=lambda b: b,
                        response_serializer=lambda b: b)
                if method in streams:
                    gen = streams[method]
                    dec, _, enc_item = outer._codecs(svc, method)

                    async def streaming(request, context):
                        async for item in gen(dec(request)):
                            yield enc_item(item)

                    return grpc.unary_stream_rpc_method_handler(
                        streaming, request_deserializer=lambda b: b,
                        response_serializer=lambda b: b)
                return None

        self.server.add_generic_rpc_handlers((Handler(),))
        self.port = self.server.add_insecure_port(f"{host}:{port}")
        await self.server.start()
        return self.port

    async def stop(self, grace: float = 0.5) -> None:
        if self.server is not None:
            await self.server.stop(grace)


class RpcClient:
    """Client for one service on one endpoint (protobuf wire for
    proto-declared services, msgpack otherwise)."""

    def __init__(self, addr: str, service: str,
                 package: str = "arroyo_tpu.rpc"):
        self.addr = addr
        self.service = service
        self.package = package
        self.channel = grpc.aio.insecure_channel(addr)

    def _codecs(self, method: str) -> Tuple[Callable, Callable]:
        types = _method_types(self.service, method)
        if types is None:
            return _ser_msgpack, _deser_msgpack
        req_cls, resp_cls, _ = types

        def enc(d: Optional[Dict]) -> bytes:
            return dict_to_proto(req_cls(), d).SerializeToString()

        def dec(data: bytes) -> Dict:
            return proto_to_dict(resp_cls.FromString(data))

        return enc, dec

    async def call(self, method: str, request: Optional[Dict] = None,
                   timeout: float = 10.0) -> Any:
        path = f"/{self.package}.{self.service}/{method}"
        enc, dec = self._codecs(method)
        fn = self.channel.unary_unary(
            path, request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        resp = await fn(enc(request or {}), timeout=timeout)
        return dec(resp)

    async def stream(self, method: str, request: Optional[Dict] = None
                     ) -> AsyncIterator[Any]:
        path = f"/{self.package}.{self.service}/{method}"
        enc, dec = self._codecs(method)
        fn = self.channel.unary_stream(
            path, request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        async for item in fn(enc(request or {})):
            yield dec(item)

    async def close(self) -> None:
        await self.channel.close()

    async def wait_ready(self, timeout: float = 10.0) -> None:
        await asyncio.wait_for(self.channel.channel_ready(), timeout)
