"""msgpack-over-grpc transport for the control plane.

The reference uses tonic-generated stubs; grpcio-tools is not in this image,
so services are wired with grpc *generic handlers*: each method is an async
function taking/returning msgpack-serializable dicts, registered under the
same fully-qualified method names as rpc/proto/rpc.proto.  Messages stay
dicts (the proto file is the schema contract)."""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator, Callable, Dict, Optional

import grpc
import msgpack

logger = logging.getLogger(__name__)


def _ser(obj: Any) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def _deser(data: bytes) -> Any:
    return msgpack.unpackb(data, raw=False)


class RpcServer:
    """grpc.aio server hosting one or more msgpack services."""

    def __init__(self) -> None:
        self._services: Dict[str, Dict[str, Callable]] = {}
        self._streams: Dict[str, Dict[str, Callable]] = {}
        self.server: Optional[grpc.aio.Server] = None
        self.port: Optional[int] = None

    def add_service(self, service: str, methods: Dict[str, Callable],
                    stream_methods: Optional[Dict[str, Callable]] = None
                    ) -> None:
        """methods: name -> async fn(request_dict) -> response_dict;
        stream_methods: name -> async gen fn(request_dict) -> yields dicts."""
        self._services[service] = methods
        self._streams[service] = stream_methods or {}

    async def start(self, host: str = "0.0.0.0", port: int = 0) -> int:
        self.server = grpc.aio.server()

        outer = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                # method path: /package.Service/Method
                path = handler_call_details.method
                try:
                    _, svc, method = path.split("/")
                except ValueError:
                    return None
                svc = svc.rsplit(".", 1)[-1]
                methods = outer._services.get(svc, {})
                streams = outer._streams.get(svc, {})
                if method in methods:
                    fn = methods[method]

                    async def unary(request, context):
                        try:
                            return _ser(await fn(_deser(request)))
                        except Exception as e:  # surface as grpc error
                            logger.exception("rpc %s failed", path)
                            await context.abort(
                                grpc.StatusCode.INTERNAL, str(e))

                    return grpc.unary_unary_rpc_method_handler(
                        unary, request_deserializer=lambda b: b,
                        response_serializer=lambda b: b)
                if method in streams:
                    gen = streams[method]

                    async def streaming(request, context):
                        async for item in gen(_deser(request)):
                            yield _ser(item)

                    return grpc.unary_stream_rpc_method_handler(
                        streaming, request_deserializer=lambda b: b,
                        response_serializer=lambda b: b)
                return None

        self.server.add_generic_rpc_handlers((Handler(),))
        self.port = self.server.add_insecure_port(f"{host}:{port}")
        await self.server.start()
        return self.port

    async def stop(self, grace: float = 0.5) -> None:
        if self.server is not None:
            await self.server.stop(grace)


class RpcClient:
    """Client for one msgpack service on one endpoint."""

    def __init__(self, addr: str, service: str,
                 package: str = "arroyo_tpu.rpc"):
        self.addr = addr
        self.service = service
        self.package = package
        self.channel = grpc.aio.insecure_channel(addr)

    async def call(self, method: str, request: Optional[Dict] = None,
                   timeout: float = 10.0) -> Any:
        path = f"/{self.package}.{self.service}/{method}"
        fn = self.channel.unary_unary(
            path, request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        resp = await fn(_ser(request or {}), timeout=timeout)
        return _deser(resp)

    async def stream(self, method: str, request: Optional[Dict] = None
                     ) -> AsyncIterator[Any]:
        path = f"/{self.package}.{self.service}/{method}"
        fn = self.channel.unary_stream(
            path, request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        async for item in fn(_ser(request or {})):
            yield _deser(item)

    async def close(self) -> None:
        await self.channel.close()

    async def wait_ready(self, timeout: float = 10.0) -> None:
        await asyncio.wait_for(self.channel.channel_ready(), timeout)
