"""SQL lexer (Postgres-ish dialect, the subset Arroyo's sqlparser usage
covers — /root/reference/arroyo-sql/src/lib.rs:369-376)."""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Iterator, List, Optional

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "and", "or", "not", "in", "is", "null", "true", "false", "case",
    "when", "then", "else", "end", "cast", "interval", "join", "inner",
    "left", "right", "full", "outer", "cross", "on", "with", "create",
    "table", "insert", "into", "values", "distinct", "between", "like",
    "asc", "desc", "union", "all", "exists", "generated", "always",
    "explain",
    "virtual", "stored", "primary", "key", "if", "over", "partition",
}


@dataclass
class Token:
    kind: str  # 'kw' | 'ident' | 'number' | 'string' | 'op' | 'eof'
    value: str
    pos: int

    def __repr__(self):
        return f"{self.kind}:{self.value}"


TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|--[^\n]*\n?|/\*.*?\*/)
  | (?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op><>|!=|<=|>=|\|\||::|[-+*/%(),.<>=;\[\]])
    """,
    re.VERBOSE | re.DOTALL,
)


class SqlLexError(ValueError):
    pass


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    pos = 0
    n = len(sql)
    while pos < n:
        m = TOKEN_RE.match(sql, pos)
        if m is None:
            raise SqlLexError(f"unexpected character {sql[pos]!r} at {pos}: "
                              f"...{sql[max(0, pos - 20):pos + 10]}...")
        pos = m.end()
        if m.lastgroup == "ws":
            continue
        text = m.group()
        if m.lastgroup == "number":
            out.append(Token("number", text, m.start()))
        elif m.lastgroup == "string":
            out.append(Token("string", text[1:-1].replace("''", "'"), m.start()))
        elif m.lastgroup == "qident":
            out.append(Token("ident", text[1:-1].replace('""', '"'), m.start()))
        elif m.lastgroup == "ident":
            low = text.lower()
            if low in KEYWORDS:
                out.append(Token("kw", low, m.start()))
            else:
                out.append(Token("ident", text, m.start()))
        else:
            out.append(Token("op", text, m.start()))
    out.append(Token("eof", "", n))
    return out
