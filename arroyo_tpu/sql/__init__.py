"""SQL frontend: parse -> plan -> Program (arroyo-sql analog)."""

from .parser import parse_sql  # noqa: F401
from .planner import Planner, SqlPlanError, plan_sql  # noqa: F401
from .schema_provider import SchemaProvider  # noqa: F401
from .compiler import Schema, SqlCompileError  # noqa: F401
from .functions import (  # noqa: F401
    register_udaf,
    register_udf,
    unregister_udfs,
)
