"""Recursive-descent SQL parser for the dialect subset the reference engine
plans (arroyo-sql: Postgres dialect via sqlparser + the planner's supported
shapes — SELECT/CTE/JOIN/GROUP BY with hop/tumble/session, CREATE TABLE with
connector options and generated columns, INSERT INTO)."""

from __future__ import annotations

from typing import List, Optional, Tuple

from .ast_nodes import (
    Explain,
    Between,
    BinaryOp,
    Case,
    Cast,
    ColumnDef,
    ColumnRef,
    CreateTable,
    DerivedTable,
    Expr,
    FunctionCall,
    InList,
    InSubquery,
    Insert,
    IntervalLit,
    IsNull,
    Join,
    JoinKind,
    Literal,
    NamedTable,
    OrderItem,
    OverClause,
    Select,
    SelectItem,
    Star,
    TableRef,
    UnaryOp,
)
from .lexer import Token, tokenize

MICROS = {
    "microsecond": 1, "microseconds": 1,
    "millisecond": 1_000, "milliseconds": 1_000,
    "second": 1_000_000, "seconds": 1_000_000,
    "minute": 60_000_000, "minutes": 60_000_000,
    "hour": 3_600_000_000, "hours": 3_600_000_000,
    "day": 86_400_000_000, "days": 86_400_000_000,
}


def duration_text_micros(text: str) -> int:
    """'3 second' / '1 day 2 hours' / '30 seconds' -> micros.  The single
    shared duration parser: INTERVAL literals and the reference-style bare
    duration strings (session('30 seconds')) both route here."""
    parts = text.strip().split()
    if len(parts) < 2 or len(parts) % 2:
        raise SqlParseError(f"cannot parse duration {text!r}")
    micros = 0
    for i in range(0, len(parts), 2):
        unit = parts[i + 1].lower()
        if unit not in MICROS:
            raise SqlParseError(f"unknown interval unit {parts[i + 1]!r}")
        try:
            qty = float(parts[i])
        except ValueError:
            raise SqlParseError(f"cannot parse duration {text!r}")
        micros += int(qty * MICROS[unit])
    return micros


class SqlParseError(ValueError):
    pass


class Parser:
    def __init__(self, sql: str):
        self.tokens = tokenize(sql)
        self.i = 0

    # -- plumbing ----------------------------------------------------------

    def peek(self, offset: int = 0) -> Token:
        return self.tokens[min(self.i + offset, len(self.tokens) - 1)]

    def next(self) -> Token:
        t = self.tokens[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "kw" and t.value in kws

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.value in ops

    def eat_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def eat_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.eat_kw(kw):
            raise SqlParseError(f"expected {kw.upper()} at {self.peek()!r}")

    def expect_op(self, op: str) -> None:
        if not self.eat_op(op):
            raise SqlParseError(f"expected {op!r} at {self.peek()!r}")

    def expect_ident(self) -> str:
        t = self.peek()
        if t.kind == "ident":
            return self.next().value
        # many keywords are valid identifiers in practice (e.g. "window")
        if t.kind == "kw" and t.value not in ("select", "from", "where"):
            return self.next().value
        raise SqlParseError(f"expected identifier at {t!r}")

    # -- entry -------------------------------------------------------------

    def parse_statements(self) -> List:
        stmts = []
        while self.peek().kind != "eof":
            if self.eat_op(";"):
                continue
            stmts.append(self.parse_statement())
        return stmts

    def parse_statement(self):
        if self.at_kw("explain"):
            self.next()
            return Explain(self.parse_select())
        if self.at_kw("create"):
            return self.parse_create_table()
        if self.at_kw("insert"):
            return self.parse_insert()
        if self.at_kw("select", "with"):
            return self.parse_select()
        raise SqlParseError(f"unexpected token {self.peek()!r}")

    # -- CREATE TABLE ------------------------------------------------------

    def parse_create_table(self) -> CreateTable:
        self.expect_kw("create")
        self.expect_kw("table")
        self.eat_kw("if")  # IF NOT EXISTS
        self.eat_kw("not")
        self.eat_kw("exists")
        name = self.expect_ident()
        cols: List[ColumnDef] = []
        if self.eat_op("("):
            while not self.at_op(")"):
                cols.append(self.parse_column_def())
                if not self.eat_op(","):
                    break
            self.expect_op(")")
        options = {}
        if self.eat_kw("with"):
            self.expect_op("(")
            while not self.at_op(")"):
                key = self.expect_ident()
                self.expect_op("=")
                t = self.next()
                options[key.lower()] = t.value
                if not self.eat_op(","):
                    break
            self.expect_op(")")
        return CreateTable(name, cols, options)

    def parse_column_def(self) -> ColumnDef:
        name = self.expect_ident()
        type_ = self.parse_type_name()
        not_null = False
        generated = None
        while True:
            if self.eat_kw("not"):
                self.expect_kw("null")
                not_null = True
            elif self.eat_kw("generated"):
                self.expect_kw("always")
                if self.peek().kind == "ident" and self.peek().value.lower() == "as":
                    self.next()
                else:
                    self.expect_kw("as")
                self.expect_op("(")
                generated = self.parse_expr()
                self.expect_op(")")
                self.eat_kw("virtual", "stored")
            elif self.eat_kw("primary"):
                self.expect_kw("key")
            else:
                break
        return ColumnDef(name, type_, not_null, generated)

    def parse_type_name(self) -> str:
        t = self.next()
        name = t.value.lower()
        if name in ("double", "character"):  # DOUBLE PRECISION, CHARACTER VARYING
            nxt = self.peek()
            if nxt.kind == "ident" and nxt.value.lower() in ("precision", "varying"):
                self.next()
        if self.eat_op("("):
            while not self.eat_op(")"):
                self.next()
        return name

    # -- INSERT ------------------------------------------------------------

    def parse_insert(self) -> Insert:
        self.expect_kw("insert")
        self.expect_kw("into")
        name = self.expect_ident()
        if self.eat_op("("):  # column list ignored: projection must match
            while not self.eat_op(")"):
                self.next()
        return Insert(name, self.parse_select())

    # -- SELECT ------------------------------------------------------------

    def parse_select(self) -> Select:
        ctes: List[Tuple[str, Select]] = []
        if self.eat_kw("with"):
            while True:
                cname = self.expect_ident()
                self.expect_kw("as")
                self.expect_op("(")
                ctes.append((cname, self.parse_select()))
                self.expect_op(")")
                if not self.eat_op(","):
                    break
        sel = self.parse_select_body()
        sel.ctes = ctes + sel.ctes
        cur = sel
        while self.eat_kw("union"):
            if not self.eat_kw("all"):
                raise SqlParseError(
                    "UNION (distinct) over streams is unbounded-state; "
                    "use UNION ALL")
            cur.union_all = self.parse_select_body()
            cur = cur.union_all
        return sel

    def parse_select_body(self) -> Select:
        self.expect_kw("select")
        distinct = self.eat_kw("distinct")
        self.eat_kw("all")
        items = [self.parse_select_item()]
        while self.eat_op(","):
            items.append(self.parse_select_item())

        from_ = None
        if self.eat_kw("from"):
            from_ = self.parse_table_ref()
        where = self.parse_expr() if self.eat_kw("where") else None
        group_by: List[Expr] = []
        if self.eat_kw("group"):
            self.expect_kw("by")
            group_by.append(self.parse_expr())
            while self.eat_op(","):
                group_by.append(self.parse_expr())
        having = self.parse_expr() if self.eat_kw("having") else None
        order_by: List[OrderItem] = []
        if self.eat_kw("order"):
            self.expect_kw("by")
            while True:
                e = self.parse_expr()
                desc = False
                if self.eat_kw("desc"):
                    desc = True
                else:
                    self.eat_kw("asc")
                order_by.append(OrderItem(e, desc))
                if not self.eat_op(","):
                    break
        limit = None
        if self.eat_kw("limit"):
            t = self.next()
            limit = int(t.value)
        return Select(items, from_, where, group_by, having, order_by, limit,
                      distinct)

    def parse_select_item(self) -> SelectItem:
        if self.at_op("*"):
            self.next()
            return SelectItem(Star())
        # qualified star: ident.*
        if (self.peek().kind == "ident" and self.peek(1).kind == "op"
                and self.peek(1).value == "." and self.peek(2).value == "*"):
            q = self.next().value
            self.next()
            self.next()
            return SelectItem(Star(qualifier=q))
        expr = self.parse_expr()
        alias = None
        if self.eat_kw("as"):
            alias = self.expect_ident()
        elif self.peek().kind == "ident":
            alias = self.next().value
        return SelectItem(expr, alias)

    # -- FROM / JOIN -------------------------------------------------------

    def parse_table_ref(self) -> TableRef:
        left = self.parse_table_factor()
        while True:
            kind = None
            if self.eat_kw("join") or self.eat_kw("inner"):
                if self.peek(-1).value == "inner":
                    self.expect_kw("join")
                kind = JoinKind.INNER
            elif self.at_kw("left", "right", "full"):
                kw = self.next().value
                self.eat_kw("outer")
                self.expect_kw("join")
                kind = JoinKind[kw.upper()]
            elif self.eat_kw("cross"):
                self.expect_kw("join")
                right = self.parse_table_factor()
                left = Join(left, right, JoinKind.INNER, None)
                continue
            else:
                break
            right = self.parse_table_factor()
            on = None
            if self.eat_kw("on"):
                on = self.parse_expr()
            left = Join(left, right, kind, on)
        return left

    def parse_table_factor(self) -> TableRef:
        if self.eat_op("("):
            if self.at_kw("select", "with"):
                q = self.parse_select()
                self.expect_op(")")
                alias = self._maybe_alias()
                return DerivedTable(q, alias)
            inner = self.parse_table_ref()
            self.expect_op(")")
            return inner
        name = self.expect_ident()
        alias = self._maybe_alias()
        return NamedTable(name, alias)

    def _maybe_alias(self) -> Optional[str]:
        if self.eat_kw("as"):
            return self.expect_ident()
        if self.peek().kind == "ident":
            return self.next().value
        return None

    # -- expressions (precedence climbing) ---------------------------------

    def parse_expr(self) -> Expr:
        return self.parse_or()

    def parse_or(self) -> Expr:
        left = self.parse_and()
        while self.eat_kw("or"):
            left = BinaryOp("or", left, self.parse_and())
        return left

    def parse_and(self) -> Expr:
        left = self.parse_not()
        while self.eat_kw("and"):
            left = BinaryOp("and", left, self.parse_not())
        return left

    def parse_not(self) -> Expr:
        if self.eat_kw("not"):
            return UnaryOp("not", self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> Expr:
        left = self.parse_additive()
        while True:
            if self.at_op("=", "<>", "!=", "<", "<=", ">", ">="):
                op = self.next().value
                if op == "!=":
                    op = "<>"
                left = BinaryOp(op, left, self.parse_additive())
            elif self.at_kw("is"):
                self.next()
                negated = self.eat_kw("not")
                self.expect_kw("null")
                left = IsNull(left, negated)
            elif self.at_kw("in"):
                self.next()
                self.expect_op("(")
                if self.at_kw("select", "with"):
                    q = self.parse_select()
                    self.expect_op(")")
                    left = InSubquery(left, q)
                    continue
                items = [self.parse_expr()]
                while self.eat_op(","):
                    items.append(self.parse_expr())
                self.expect_op(")")
                left = InList(left, items)
            elif self.at_kw("between"):
                self.next()
                low = self.parse_additive()
                self.expect_kw("and")
                high = self.parse_additive()
                left = Between(left, low, high)
            elif self.at_kw("like"):
                self.next()
                left = BinaryOp("like", left, self.parse_additive())
            elif self.at_kw("not") and self.peek(1).value in ("in", "like", "between"):
                self.next()
                if self.eat_kw("in"):
                    self.expect_op("(")
                    if self.at_kw("select", "with"):
                        q = self.parse_select()
                        self.expect_op(")")
                        left = InSubquery(left, q, negated=True)
                        continue
                    items = [self.parse_expr()]
                    while self.eat_op(","):
                        items.append(self.parse_expr())
                    self.expect_op(")")
                    left = InList(left, items, negated=True)
                elif self.eat_kw("like"):
                    left = UnaryOp("not", BinaryOp("like", left, self.parse_additive()))
                else:
                    self.expect_kw("between")
                    low = self.parse_additive()
                    self.expect_kw("and")
                    high = self.parse_additive()
                    left = Between(left, low, high, negated=True)
            else:
                return left

    def parse_additive(self) -> Expr:
        left = self.parse_multiplicative()
        while self.at_op("+", "-", "||"):
            op = self.next().value
            left = BinaryOp(op, left, self.parse_multiplicative())
        return left

    def parse_multiplicative(self) -> Expr:
        left = self.parse_unary()
        while self.at_op("*", "/", "%"):
            op = self.next().value
            left = BinaryOp(op, left, self.parse_unary())
        return left

    def parse_unary(self) -> Expr:
        if self.eat_op("-"):
            return UnaryOp("-", self.parse_unary())
        if self.eat_op("+"):
            return self.parse_unary()
        return self.parse_postfix()

    def parse_postfix(self) -> Expr:
        e = self.parse_primary()
        while True:
            if self.at_op("."):
                # struct / qualifier access: a.b(.c)
                self.next()
                field = self.expect_ident()
                if isinstance(e, ColumnRef) and e.qualifier is None:
                    e = ColumnRef(field, qualifier=e.name)
                elif isinstance(e, ColumnRef):
                    # a.b.c: treat a.b as qualifier chain
                    e = ColumnRef(field, qualifier=f"{e.qualifier}.{e.name}")
                else:
                    raise SqlParseError("field access on non-column")
            elif self.at_op("::"):
                self.next()
                e = Cast(e, self.parse_type_name())
            else:
                return e

    def parse_primary(self) -> Expr:
        t = self.peek()
        if t.kind == "number":
            self.next()
            if any(c in t.value for c in ".eE"):
                return Literal(float(t.value), "float")
            return Literal(int(t.value), "int")
        if t.kind == "string":
            self.next()
            return Literal(t.value, "string")
        if self.eat_kw("null"):
            return Literal(None, "null")
        if self.eat_kw("true"):
            return Literal(True, "bool")
        if self.eat_kw("false"):
            return Literal(False, "bool")
        if self.eat_kw("interval"):
            return self.parse_interval()
        if self.eat_kw("case"):
            return self.parse_case()
        if self.eat_kw("cast"):
            self.expect_op("(")
            inner = self.parse_expr()
            self.expect_kw("as")
            typ = self.parse_type_name()
            self.expect_op(")")
            return Cast(inner, typ)
        if self.eat_op("("):
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if self.at_op("*"):
            self.next()
            return Star()
        if t.kind in ("ident", "kw"):
            name = self.expect_ident()
            if self.at_op("("):
                return self.parse_function(name)
            return ColumnRef(name)
        raise SqlParseError(f"unexpected token {t!r} in expression")

    def parse_interval(self) -> IntervalLit:
        t = self.next()
        if t.kind != "string":
            raise SqlParseError(f"expected interval string at {t!r}")
        text = t.value.strip()
        # forms: '2' SECOND | '3 second' | '1 day 2 hours'
        parts = text.split()
        if len(parts) == 1:
            unit_tok = self.peek()
            if unit_tok.kind not in ("ident", "kw"):
                raise SqlParseError("interval missing unit")
            unit = self.next().value.lower()
            return IntervalLit(duration_text_micros(f"{parts[0]} {unit}"))
        return IntervalLit(duration_text_micros(text))

    def parse_case(self) -> Case:
        operand = None
        if not self.at_kw("when"):
            operand = self.parse_expr()
        whens = []
        while self.eat_kw("when"):
            cond = self.parse_expr()
            self.expect_kw("then")
            val = self.parse_expr()
            whens.append((cond, val))
        else_ = None
        if self.eat_kw("else"):
            else_ = self.parse_expr()
        self.expect_kw("end")
        return Case(operand, whens, else_)

    def parse_function(self, name: str) -> FunctionCall:
        self.expect_op("(")
        if name.lower() == "extract" and self.peek().kind != "string":
            # standard SQL EXTRACT(field FROM expr): the field is a bare
            # keyword, normalized to the two-arg call form
            # extract('field', expr) the compiler already handles (a
            # leading string literal means the two-arg form — fall
            # through to generic arg parsing)
            field = self.expect_ident()
            self.expect_kw("from")
            operand = self.parse_expr()
            self.expect_op(")")
            return FunctionCall("extract",
                                [Literal(field.lower(), "string"),
                                 operand], False, None)
        distinct = self.eat_kw("distinct")
        args: List[Expr] = []
        if not self.at_op(")"):
            args.append(self.parse_expr())
            while self.eat_op(","):
                args.append(self.parse_expr())
        self.expect_op(")")
        over = None
        if self.eat_kw("over"):
            self.expect_op("(")
            partition: List[Expr] = []
            if self.eat_kw("partition"):
                self.expect_kw("by")
                partition.append(self.parse_expr())
                while self.eat_op(","):
                    partition.append(self.parse_expr())
            order: List[OrderItem] = []
            if self.eat_kw("order"):
                self.expect_kw("by")
                while True:
                    e = self.parse_expr()
                    desc = False
                    if self.eat_kw("desc"):
                        desc = True
                    else:
                        self.eat_kw("asc")
                    order.append(OrderItem(e, desc))
                    if not self.eat_op(","):
                        break
            self.expect_op(")")
            over = OverClause(partition, order)
        return FunctionCall(name.lower(), args, distinct, over)


def parse_sql(sql: str) -> List:
    return Parser(sql).parse_statements()
