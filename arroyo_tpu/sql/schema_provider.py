"""Table registry for SQL planning — the ``ArroyoSchemaProvider`` analog
(arroyo-sql/src/lib.rs:62-158): connector tables created via CREATE TABLE,
plus built-in virtual tables (nexmark, impulse)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .ast_nodes import ColumnDef, CreateTable, Expr
from .compiler import Schema, StructDef

TYPE_KIND = {
    "int": "i", "integer": "i", "bigint": "i", "smallint": "i",
    "tinyint": "i", "serial": "i",
    "float": "f", "double": "f", "real": "f", "decimal": "f", "numeric": "f",
    "bool": "b", "boolean": "b",
    "text": "s", "varchar": "s", "string": "s", "char": "s", "character": "s",
    "timestamp": "t", "datetime": "t", "timestamptz": "t", "date": "t",
}


@dataclass
class TableDef:
    name: str
    connector: str
    config: Dict[str, Any]
    schema: Schema
    is_source: bool = True
    is_sink: bool = False
    format: str = "json"
    event_time_field: Optional[str] = None
    watermark_field: Optional[str] = None
    generated: List[Tuple[str, str, Expr]] = field(default_factory=list)
    # (col name, type kind, expr)
    columns: List[ColumnDef] = field(default_factory=list)
    default_lateness_micros: int = 1_000_000
    is_updating: bool = False  # debezium formats produce updating streams


CONNECTOR_OPTION_KEYS = {
    # options consumed by the planner, not passed to the connector config
    "connector", "type", "format", "event_time_field", "watermark_field",
}


def nexmark_lateness_micros(rate: float) -> int:
    """Out-of-orderness bound of the nexmark generator: group size x
    inter-event delay (see nexmark.py's (event_number * 953) % 50 shuffle).
    Shared with bench.py's latency math — keep single-sourced."""
    return max(int(50 * 1_000_000.0 / max(rate, 1.0)), 1000)


def nexmark_table(config: Dict[str, Any]) -> TableDef:
    """Built-in nexmark virtual table: Event{person, auction, bid} structs
    flattened onto the generator's union columns."""
    schema = Schema(
        columns={
            "event_type": "i",
            "person_id": "i", "person_name": "s", "person_email": "s",
            "person_city": "s", "person_state": "s", "person_extra": "s",
            "auction_id": "i", "auction_seller": "i", "auction_category": "i",
            "auction_initial_bid": "i", "auction_reserve": "i",
            "auction_expires": "t", "auction_datetime": "t",
            "auction_item_name": "s", "auction_description": "s",
            "auction_extra": "s",
            "bid_auction": "i", "bid_bidder": "i", "bid_price": "i",
            "bid_datetime": "t", "bid_channel": "s", "bid_url": "s",
            "bid_extra": "s",
        },
        structs={
            "person": StructDef("person", {
                "id": "person_id", "name": "person_name",
                "email_address": "person_email", "city": "person_city",
                "state": "person_state", "datetime": "__timestamp",
                "extra": "person_extra",
            }, "event_type", 0),
            "auction": StructDef("auction", {
                "id": "auction_id", "seller": "auction_seller",
                "category": "auction_category",
                "initial_bid": "auction_initial_bid",
                "reserve": "auction_reserve", "expires": "auction_expires",
                "datetime": "auction_datetime",
                "item_name": "auction_item_name",
                "description": "auction_description",
                "extra": "auction_extra",
            }, "event_type", 1),
            "bid": StructDef("bid", {
                "auction": "bid_auction", "bidder": "bid_bidder",
                "price": "bid_price", "datetime": "bid_datetime",
                "channel": "bid_channel", "url": "bid_url",
                "extra": "bid_extra",
            }, "event_type", 2),
        },
        # the generator stamps each event's datetime field with the event
        # timestamp itself (nexmark.py: cols["bid_datetime"] =
        # where(is_bid, ts, 0), masked NULL when the struct is absent) —
        # declare the provenance so the optimizer can prove
        # window-range predicates on these columns pin rows to their own
        # event-time window (reference semantics: nexmark/mod.rs
        # datetime == wallclock event time)
        event_time_cols={"auction_datetime", "bid_datetime",
                         "__timestamp"},
    )
    rate = float(config.get("event_rate", 100_000.0))
    return TableDef("nexmark", "nexmark", config, schema,
                    default_lateness_micros=nexmark_lateness_micros(rate))


def impulse_table(config: Dict[str, Any]) -> TableDef:
    schema = Schema(columns={"counter": "i", "subtask_index": "i"})
    return TableDef("impulse", "impulse", config, schema,
                    default_lateness_micros=0)


class SchemaProvider:
    def __init__(self) -> None:
        self.tables: Dict[str, TableDef] = {}

    def register_udf(self, name: str, fn) -> None:
        """Register a scalar UDF ``fn(*cols: np.ndarray) -> np.ndarray``
        usable in any SQL expression (arroyo-sql/src/lib.rs:196-290
        analog; executed on the host expression path)."""
        from .functions import register_udf

        register_udf(name, fn)

    def register_udaf(self, name: str, fn) -> None:
        """Register a user aggregate ``fn(values: np.ndarray) -> scalar``,
        applied per group over non-null rows; windowed aggregations only
        (not mergeable — operators.rs:165-167 two-phase exclusion)."""
        from .functions import register_udaf

        register_udaf(name, fn)

    def get(self, name: str, default_config: Optional[Dict[str, Any]] = None
            ) -> TableDef:
        n = name.lower()
        if n in self.tables:
            return self.tables[n]
        if n == "nexmark":
            return nexmark_table(default_config or {})
        if n == "impulse":
            return impulse_table(default_config or {})
        raise KeyError(f"unknown table {name!r}; known: {sorted(self.tables)}"
                       " + built-ins [nexmark, impulse]")

    def add_memory_table(self, name: str, columns: Dict[str, str],
                         batches: List[Any],
                         lateness_micros: int = 0,
                         event_time_field: Optional[str] = None) -> TableDef:
        """Testing hook: register an in-memory table with explicit batches
        (plays the role of the reference's single_file test tables)."""
        td = TableDef(name.lower(), "memory", {"batches": batches},
                      Schema(columns=dict(columns)),
                      default_lateness_micros=lateness_micros,
                      event_time_field=event_time_field)
        self.tables[td.name] = td
        return td

    def add_create_table(self, ct: CreateTable) -> TableDef:
        opts = dict(ct.with_options)
        connector = opts.get("connector")
        if connector is None:
            raise ValueError(f"CREATE TABLE {ct.name} needs connector = '...'")
        typ = opts.get("type", "source")
        fmt = opts.get("format", "json")
        cfg = {k: v for k, v in opts.items() if k not in CONNECTOR_OPTION_KEYS}

        # built-in virtual tables keep their rich schema under a custom
        # name/config (CREATE TABLE my_nexmark WITH (connector='nexmark', ...))
        if connector in ("nexmark", "impulse") and not ct.columns:
            base = (nexmark_table(cfg) if connector == "nexmark"
                    else impulse_table(cfg))
            base.name = ct.name.lower()
            self.tables[base.name] = base
            return base

        schema = Schema()
        generated: List[Tuple[str, str, Expr]] = []
        for col in ct.columns:
            kind = TYPE_KIND.get(col.type, "n")
            schema.columns[col.name.lower()] = kind
            if col.generated_as is not None:
                generated.append((col.name.lower(), kind, col.generated_as))

        # the connector consumes the serde format too (it constructs the
        # Format); planner-only options stay stripped
        cfg["format"] = fmt
        if fmt == "avro" and "format_options" not in cfg and ct.columns:
            # DDL drives the serde: synthesize the Avro record schema from
            # the declared columns (nullable unions)
            avro_t = {"i": "long", "f": "double", "b": "boolean",
                      "s": "string", "t": "long"}
            cfg["format_options"] = {"schema": {
                "type": "record", "name": ct.name,
                "fields": [
                    {"name": c.name.lower(),
                     "type": ["null", avro_t.get(
                         TYPE_KIND.get(c.type, "s"), "string")]}
                    for c in ct.columns if c.generated_as is None],
            }}

        td = TableDef(
            ct.name.lower(), connector, cfg, schema,
            is_source=(typ == "source"), is_sink=(typ == "sink"),
            format=fmt,
            event_time_field=opts.get("event_time_field"),
            watermark_field=opts.get("watermark_field"),
            generated=generated,
            columns=ct.columns,
            is_updating=fmt.startswith("debezium"),
        )
        self.tables[td.name] = td
        return td
