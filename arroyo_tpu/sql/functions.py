"""SQL scalar function library — jnp implementations of the reference's
function set (arroyo-worker/src/operators/functions/*.rs: datetime, strings,
regexp, hash, json + math built-ins from the expression compiler).

Each function takes/returns `(value, mask)` pairs (mask None = all valid).
Numeric functions are jnp-traceable (run inside the jitted expression);
string/regex/json functions are host-side numpy-object ops and force the
expression onto the host path.
"""

from __future__ import annotations

import hashlib
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

MV = Tuple[Any, Optional[Any]]  # (value array, validity mask)

SECONDS = 1_000_000
DEVICE_FUNCTIONS: Dict[str, Callable] = {}
HOST_FUNCTIONS: Dict[str, Callable] = {}

# datetime precisions/fields that need calendar arithmetic (host path).
# 'week' is calendar too: Postgres truncates to the ISO Monday, not to
# 7-day buckets from the (Thursday) epoch.
CAL_TRUNC_PRECISIONS = {"week", "month", "quarter", "year", "decade",
                        "century"}
CAL_EXTRACT_FIELDS = {"year", "month", "day", "doy", "quarter", "week",
                      "isodow", "millennium", "century", "decade"}


def device_fn(name):
    def deco(f):
        DEVICE_FUNCTIONS[name] = f
        return f
    return deco


def host_fn(name):
    def deco(f):
        HOST_FUNCTIONS[name] = f
        return f
    return deco


# -- user-defined functions ---------------------------------------------------
#
# The analog of the reference's UDF registration into the planner
# (arroyo-sql/src/lib.rs:196-290) and worker-side execution
# (operators/mod.rs:347-494, wasmtime there — plain host Python here, the
# jit-or-callback policy SURVEY #20 prescribes).

SCALAR_UDFS: Dict[str, Callable] = {}
UDAFS: Dict[str, Callable] = {}


# names handled specially by the expression compiler / planner, never
# present in the function registries but still not shadowable
_RESERVED_FN_NAMES = {
    "count", "sum", "min", "max", "avg",  # built-in aggregates
    "hop", "tumble", "session",  # window assignment markers
    "date_trunc", "date_part", "extract",  # compiler special cases
}


def _check_udf_name(name: str) -> str:
    n = name.lower()
    if (n in DEVICE_FUNCTIONS or n in HOST_FUNCTIONS
            or n in _RESERVED_FN_NAMES or n in SCALAR_UDFS or n in UDAFS):
        raise ValueError(f"cannot shadow existing function {name!r}")
    return n


def register_udf(name: str, fn: Callable) -> None:
    """Register a scalar UDF: ``fn(*cols: np.ndarray) -> np.ndarray``,
    vectorized over the batch; runs on the host expression path."""
    SCALAR_UDFS[_check_udf_name(name)] = fn


def register_udaf(name: str, fn: Callable) -> None:
    """Register a user aggregate: ``fn(values: np.ndarray) -> scalar``,
    applied per group over the non-null input rows.  UDAFs are not
    mergeable and therefore plan onto buffered window operators only
    (the reference's two-phase rewrite likewise excludes UDAFs,
    operators.rs:165-167)."""
    UDAFS[_check_udf_name(name)] = fn


def unregister_udfs() -> None:
    """Testing hook: clear all user-registered functions."""
    SCALAR_UDFS.clear()
    UDAFS.clear()


def _all_valid_mask(masks):
    import jax.numpy as jnp

    ms = [m for m in masks if m is not None]
    if not ms:
        return None
    out = ms[0]
    for m in ms[1:]:
        out = out & m
    return out


# -- math (device) -----------------------------------------------------------

def _unary_math(fn):
    def impl(args: List[MV]) -> MV:
        (v, m), = args
        return fn(v), m
    return impl


def _register_math():
    import jax.numpy as jnp

    for name, fn in [
        ("abs", jnp.abs), ("ceil", jnp.ceil), ("floor", jnp.floor),
        ("round", jnp.round), ("sqrt", jnp.sqrt), ("exp", jnp.exp),
        ("ln", jnp.log), ("log10", jnp.log10), ("log2", jnp.log2),
        ("sin", jnp.sin), ("cos", jnp.cos), ("tan", jnp.tan),
        ("asin", jnp.arcsin), ("acos", jnp.arccos), ("atan", jnp.arctan),
        ("signum", jnp.sign), ("trunc", jnp.trunc),
    ]:
        DEVICE_FUNCTIONS[name] = _unary_math(fn)

    def power(args):
        (a, ma), (b, mb) = args
        return jnp.power(a, b), _all_valid_mask([ma, mb])

    DEVICE_FUNCTIONS["power"] = power
    DEVICE_FUNCTIONS["pow"] = power

    def nullif(args):
        (a, ma), (b, mb) = args
        eq = a == b
        if isinstance(eq, bool):  # scalar literals: ~True is -2, not False
            eq = np.bool_(eq)
        mask = ~eq if ma is None else (ma & ~eq)
        return a, mask

    DEVICE_FUNCTIONS["nullif"] = nullif

    def coalesce(args):
        from ..formats import nan_validity

        # NULL-ness must include the implicit encodings (NaN floats in
        # unmasked columns), not just explicit masks — else a NaN first
        # argument short-circuits and never falls through
        out_v, out_m = args[0]
        out_m = nan_validity(out_v, out_m)
        for v, m in args[1:]:
            if out_m is None:
                break
            m = nan_validity(v, m)
            # object (string) columns can't enter jnp.where — select on
            # host (nan_validity returns a mask for object arrays even
            # when every row is valid)
            obj = ((isinstance(out_v, np.ndarray) and out_v.dtype == object)
                   or (isinstance(v, np.ndarray) and v.dtype == object))
            out_v = (np.where(np.asarray(out_m), out_v, v) if obj
                     else jnp.where(out_m, out_v, v))
            # symmetric | broadcast: out_m may be scalar (literal first
            # arg) while m is row-shaped, or vice versa
            out_m = None if m is None else (out_m | m)
        return out_v, out_m

    DEVICE_FUNCTIONS["coalesce"] = coalesce


_register_math()


# -- datetime (device; timestamps are int64 micros) --------------------------

def _register_datetime():
    import jax.numpy as jnp

    TRUNC = {
        "second": SECONDS,
        "minute": 60 * SECONDS,
        "hour": 3600 * SECONDS,
        "day": 86400 * SECONDS,
        # no 'week' here: ISO weeks start Monday, the epoch was a Thursday
        # -> calendar (host) path
    }

    def date_trunc_factory(unit_micros):
        def impl(args):
            v, m = args[-1]
            return (v // unit_micros) * unit_micros, m
        return impl

    def date_trunc(args, precision: str):
        p = precision.lower()
        if p in TRUNC:
            v, m = args
            return (v // TRUNC[p]) * TRUNC[p], m
        raise ValueError(f"date_trunc precision {p} requires host path")

    DEVICE_FUNCTIONS["__date_trunc"] = date_trunc  # special-cased in compiler

    # calendar-aware precisions (month lengths vary): vectorized host
    # numpy datetime64 arithmetic; the compiler routes these precisions to
    # the host path (datetime.rs month/quarter/year parity)

    def date_trunc_host(args, precision: str):
        v, m = args
        dt = np.asarray(v, dtype=np.int64).astype("datetime64[us]")
        p = precision.lower()
        if p == "week":  # ISO week starts Monday; epoch day 0 was Thursday
            D = dt.astype("datetime64[D]")
            dow_mon0 = (D.astype(np.int64) + 3) % 7
            t = D - dow_mon0
        elif p == "month":
            t = dt.astype("datetime64[M]")
        elif p == "quarter":
            mo = dt.astype("datetime64[M]").astype(np.int64)
            t = ((mo // 3) * 3).astype("datetime64[M]")
        elif p == "year":
            t = dt.astype("datetime64[Y]")
        elif p == "decade":
            y = dt.astype("datetime64[Y]").astype(np.int64) + 1970
            t = ((y // 10) * 10 - 1970).astype("datetime64[Y]")
        elif p == "century":
            y = dt.astype("datetime64[Y]").astype(np.int64) + 1970
            t = (((y - 1) // 100) * 100 + 1 - 1970).astype("datetime64[Y]")
        else:
            raise ValueError(f"unsupported date_trunc precision {p}")
        return t.astype("datetime64[us]").astype(np.int64), m

    HOST_FUNCTIONS["__date_trunc_host"] = date_trunc_host

    def extract(args, field: str):
        v, m = args
        f = field.lower()
        if f == "second":
            return (v // SECONDS) % 60, m
        if f == "minute":
            return (v // (60 * SECONDS)) % 60, m
        if f == "hour":
            return (v // (3600 * SECONDS)) % 24, m
        if f in ("epoch",):
            return v // SECONDS, m
        if f in ("dow",):
            return ((v // (86400 * SECONDS)) + 4) % 7, m  # 1970-01-01 = Thursday
        raise ValueError(f"extract field {f} requires host path")

    DEVICE_FUNCTIONS["__extract"] = extract

    def extract_host(args, field: str):
        v, m = args
        dt = np.asarray(v, dtype=np.int64).astype("datetime64[us]")
        f = field.lower()
        Y = dt.astype("datetime64[Y]")
        year = Y.astype(np.int64) + 1970
        if f == "year":
            return year, m
        mo = dt.astype("datetime64[M]").astype(np.int64)
        month = mo % 12 + 1
        if f == "month":
            return month, m
        if f == "quarter":
            return (month - 1) // 3 + 1, m
        D = dt.astype("datetime64[D]")
        if f == "day":
            return ((D - dt.astype("datetime64[M]").astype("datetime64[D]"))
                    .astype(np.int64) + 1), m
        if f == "doy":
            return (D - Y.astype("datetime64[D]")).astype(np.int64) + 1, m
        if f == "isodow":  # Monday=1..Sunday=7
            return (D.astype(np.int64) + 3) % 7 + 1, m
        if f == "week":  # ISO 8601 week number
            import pandas as pd

            idx = pd.to_datetime(dt)
            return idx.isocalendar().week.to_numpy().astype(np.int64), m
        if f == "decade":
            return year // 10, m
        if f == "century":
            return (year - 1) // 100 + 1, m
        if f == "millennium":
            return (year - 1) // 1000 + 1, m
        raise ValueError(f"unsupported extract field {f}")

    HOST_FUNCTIONS["__extract_host"] = extract_host

    def from_unixtime(args):
        # nanoseconds -> micros timestamp (reference from_unixtime takes ns)
        (v, m), = args
        return v // 1000, m

    DEVICE_FUNCTIONS["from_unixtime"] = from_unixtime

    def to_timestamp(args):
        (v, m), = args
        return v.astype(jnp.int64), m

    DEVICE_FUNCTIONS["to_timestamp"] = to_timestamp

    def unix_timestamp(args):
        (v, m), = args
        return v // SECONDS, m

    DEVICE_FUNCTIONS["unix_timestamp"] = unix_timestamp


_register_datetime()


# -- strings (host) ----------------------------------------------------------

def _obj(v):
    return np.asarray(v, dtype=object)


def _row_get(v, i):
    """Row i of a column, or the value itself for scalar literals."""
    if isinstance(v, str) or np.ndim(v) == 0:
        return v.item() if isinstance(v, np.ndarray) else v
    return v[i]


def _n_rows(args) -> int:
    for a, _m in args:
        if not isinstance(a, str) and np.ndim(a) > 0:
            return len(a)
    return 1


def _row_is_valid(a, i) -> bool:
    """Row i of a (value, mask) pair is non-NULL: the value is not a host
    None AND its validity mask (device-side NULLs) allows it."""
    v, m = a
    if _row_get(v, i) is None:
        return False
    if m is None:
        return True
    mm = np.asarray(m)
    return bool(mm.reshape(-1)[i] if mm.ndim and mm.shape[0] > 1 else
                mm.reshape(-1)[0] if mm.ndim else mm)


@host_fn("upper")
def _upper(args):
    (v, m), = args
    return _obj([s.upper() if s is not None else None for s in v]), m


@host_fn("lower")
def _lower(args):
    (v, m), = args
    return _obj([s.lower() if s is not None else None for s in v]), m


@host_fn("length")
def _length(args):
    (v, m), = args
    return np.array([len(s) if s is not None else 0 for s in v],
                    dtype=np.int64), m


@host_fn("char_length")
def _char_length(args):
    return _length(args)


@host_fn("concat")
def _concat(args):
    n = _n_rows(args)
    out = ["".join(str(_row_get(a[0], i)) for a in args
                   if _row_get(a[0], i) is not None)
           for i in range(n)]
    return _obj(out), _all_valid_mask([m for _, m in args])


@host_fn("substr")
def _substr(args):
    v, m = args[0]
    start = np.asarray(args[1][0]).astype(int)
    if len(args) > 2:
        ln = np.asarray(args[2][0]).astype(int)
        out = [s[st - 1:st - 1 + l] if s is not None else None
               for s, st, l in zip(v, np.broadcast_to(start, (len(v),)),
                                   np.broadcast_to(ln, (len(v),)))]
    else:
        out = [s[st - 1:] if s is not None else None
               for s, st in zip(v, np.broadcast_to(start, (len(v),)))]
    return _obj(out), m


@host_fn("substring")
def _substring(args):
    return _substr(args)


@host_fn("trim")
def _trim(args):
    (v, m), = args
    return _obj([s.strip() if s is not None else None for s in v]), m


@host_fn("ltrim")
def _ltrim(args):
    (v, m), = args
    return _obj([s.lstrip() if s is not None else None for s in v]), m


@host_fn("rtrim")
def _rtrim(args):
    (v, m), = args
    return _obj([s.rstrip() if s is not None else None for s in v]), m


@host_fn("replace")
def _replace(args):
    v, m = args[0]
    old = args[1][0]
    new = args[2][0]
    out = [s.replace(o, nw) if s is not None else None
           for s, o, nw in zip(v, np.broadcast_to(old, (len(v),)),
                               np.broadcast_to(new, (len(v),)))]
    return _obj(out), m


@host_fn("split_part")
def _split_part(args):
    v, m = args[0]
    delim = args[1][0]
    idx = np.asarray(args[2][0]).astype(int)
    out = []
    for s, d, i in zip(v, np.broadcast_to(delim, (len(v),)),
                       np.broadcast_to(idx, (len(v),))):
        if s is None:
            out.append(None)
            continue
        parts = s.split(d)
        out.append(parts[i - 1] if 0 < i <= len(parts) else "")
    return _obj(out), m


@host_fn("starts_with")
def _starts_with(args):
    v, m = args[0]
    prefix = args[1][0]
    return np.array([bool(s and s.startswith(p)) for s, p in
                     zip(v, np.broadcast_to(prefix, (len(v),)))]), m


@host_fn("regexp_match")
def _regexp_match(args):
    v, m = args[0]
    pattern = str(np.asarray(args[1][0]).reshape(-1)[0])
    rx = re.compile(pattern)
    return np.array([bool(s is not None and rx.search(s)) for s in v]), m


@host_fn("regexp_replace")
def _regexp_replace(args):
    v, m = args[0]
    pattern = str(np.asarray(args[1][0]).reshape(-1)[0])
    repl = str(np.asarray(args[2][0]).reshape(-1)[0])
    rx = re.compile(pattern)
    return _obj([rx.sub(repl, s) if s is not None else None for s in v]), m


@host_fn("md5")
def _md5(args):
    (v, m), = args
    return _obj([hashlib.md5(str(s).encode()).hexdigest()
                 if s is not None else None for s in v]), m


@host_fn("sha256")
def _sha256(args):
    (v, m), = args
    return _obj([hashlib.sha256(str(s).encode()).hexdigest()
                 if s is not None else None for s in v]), m



def _json_path_query(args):
    """Evaluate a $.a.b path over a JSON string column, returning per row
    the list of ALL matches (array nodes fan out over their elements, as
    jsonpath does) or None on a parse error
    (/root/reference/arroyo-worker/src/operators/functions/json.rs)."""
    import json as _json

    v, m = args[0]
    path = str(np.asarray(args[1][0]).reshape(-1)[0])
    # split into segments, expanding indexers: a[0].b -> ['a', 0, 'b'],
    # a[*].b -> ['a', '*', 'b'] (jsonpath subset the reference's json.rs
    # relies on).  Only the leading '$.'/'$' root marker is stripped —
    # keys may legitimately contain '$' ($ref, $schema).
    if path.startswith("$."):
        path = path[2:]
    elif path.startswith("$"):
        path = path[1:]
    keys: list = []
    bad_path = False
    for part in path.split("."):
        if not part:
            continue
        base, _, rest = part.partition("[")
        if base:
            keys.append(base)
        while rest:
            idx, _, rest = rest.partition("]")
            if idx == "*":
                keys.append("*")
            elif re.fullmatch(r"-?\d+", idx):
                keys.append(int(idx))
            else:
                # unsupported bracket form ($['k'], slices, '--1', '+1',
                # '1_0'): no matches, never a crashed pipeline
                bad_path = True
            rest = rest.lstrip("[")
    if bad_path:
        return [[] for _ in v], m
    rows = []
    for s in v:
        try:
            nodes = [_json.loads(s)]
        except Exception:
            rows.append(None)
            continue
        for k in keys:
            nxt = []
            if isinstance(k, int):  # explicit array index (arrays only:
                for nd in nodes:     # [0] on a string is NOT char access)
                    if isinstance(nd, list):
                        try:
                            nxt.append(nd[k])
                        except IndexError:
                            pass
            elif k == "*":  # explicit wildcard over array elements
                for nd in nodes:
                    if isinstance(nd, list):
                        nxt.extend(nd)
            else:
                for nd in nodes:
                    items = nd if isinstance(nd, list) else [nd]
                    for item in items:
                        try:
                            nxt.append(item[k])
                        except Exception:
                            pass
            nodes = nxt
        rows.append(nodes)
    return rows, m


def _json_path_walk(args, convert):
    """First-match walk; per-row null when the path matches nothing.
    ``convert`` maps the matched object to the output value."""
    rows, m = _json_path_query(args)
    out = [convert(r[0]) if r else None for r in rows]
    mask = np.array([o is not None for o in out])
    return _obj(out), mask if m is None else (m & mask)


@host_fn("get_json_objects")
def _get_json_objects(args):
    """ALL path matches, each JSON-encoded, as a list per row
    (json.rs get_json_objects returns Vec<String>)."""
    import json as _json

    rows, m = _json_path_query(args)
    out = [[_json.dumps(o) for o in r] if r is not None else None
           for r in rows]
    mask = np.array([o is not None for o in out])
    return _obj(out), mask if m is None else (m & mask)


@host_fn("hash")
def _hash(args):
    from ..types import hash_any_column

    (v, m), = args
    return hash_any_column(np.asarray(v)).astype(np.int64), m


# -- string parity additions (strings.rs full inventory) ---------------------

def _map_str(v, f):
    return _obj([f(s) if s is not None else None for s in v])


def _and_input_nulls(v, m):
    """Validity mask with None input rows marked null, even when the
    incoming mask is absent (object string columns skip coercion)."""
    ok = np.array([s is not None for s in v])
    return ok if m is None else (m & ok)


@host_fn("ascii")
def _ascii(args):
    (v, m), = args
    return (np.array([ord(s[0]) if s else 0 for s in v], dtype=np.int64),
            _and_input_nulls(v, m))


@host_fn("chr")
def _chr(args):
    (v, m), = args
    out, ok = [], []
    for x in np.asarray(v).reshape(-1):
        # per-row null on invalid codepoints, never a batch abort
        if x is None or not (0 <= int(x) <= 0x10FFFF):
            out.append(None)
            ok.append(False)
        else:
            out.append(chr(int(x)))
            ok.append(True)
    okm = np.asarray(ok)
    return _obj(out), okm if m is None else (m & okm)


@host_fn("initcap")
def _initcap(args):
    import re as _re

    (v, m), = args

    def cap(s: str) -> str:
        # SQL initcap: words are alphanumeric runs (unlike str.title,
        # which also breaks on digits and apostrophes)
        return _re.sub(r"[A-Za-z0-9]+",
                       lambda mt: mt.group(0)[0].upper()
                       + mt.group(0)[1:].lower(), s)

    return _map_str(v, cap), m


@host_fn("left")
def _left(args):
    v, m = args[0]
    n = np.broadcast_to(np.asarray(args[1][0]).astype(int), (len(v),))
    return _obj([s[:k] if s is not None else None
                 for s, k in zip(v, n)]), m


@host_fn("right")
def _right(args):
    v, m = args[0]
    n = np.broadcast_to(np.asarray(args[1][0]).astype(int), (len(v),))

    def take(s, k):
        if k == 0:
            return ""  # Postgres: right(s, 0) = '' (s[-0:] would be s)
        if k > 0:
            return s[-k:] if k < len(s) else s
        return s[-k:]  # negative: all but the first |k| chars (Postgres)

    return _obj([take(s, k) if s is not None else None
                 for s, k in zip(v, n)]), m


@host_fn("lpad")
def _lpad(args):
    v, m = args[0]
    n = np.broadcast_to(np.asarray(args[1][0]).astype(int), (len(v),))
    fill = str(np.asarray(args[2][0]).reshape(-1)[0]) if len(args) > 2 \
        else " "
    out = []
    for s, k in zip(v, n):
        if s is None:
            out.append(None)
        elif k <= 0:
            out.append("")  # Postgres: non-positive length pads to empty
        elif len(s) >= k:
            out.append(s[:k])
        else:
            pad = (fill * k)[:k - len(s)]
            out.append(pad + s)
    return _obj(out), m


@host_fn("rpad")
def _rpad(args):
    v, m = args[0]
    n = np.broadcast_to(np.asarray(args[1][0]).astype(int), (len(v),))
    fill = str(np.asarray(args[2][0]).reshape(-1)[0]) if len(args) > 2 \
        else " "
    out = []
    for s, k in zip(v, n):
        if s is None:
            out.append(None)
        elif k <= 0:
            out.append("")  # Postgres: non-positive length pads to empty
        elif len(s) >= k:
            out.append(s[:k])
        else:
            pad = (fill * k)[:k - len(s)]
            out.append(s + pad)
    return _obj(out), m


@host_fn("octet_length")
def _octet_length(args):
    (v, m), = args
    return (np.array([len(str(s).encode()) if s is not None else 0
                      for s in v], dtype=np.int64),
            _and_input_nulls(v, m))


@host_fn("bit_length")
def _bit_length(args):
    (v, m), = args
    return (np.array([len(str(s).encode()) * 8 if s is not None else 0
                      for s in v], dtype=np.int64),
            _and_input_nulls(v, m))


@host_fn("strpos")
def _strpos(args):
    v, m = args[0]
    needle = str(np.asarray(args[1][0]).reshape(-1)[0])
    return (np.array([(s.find(needle) + 1) if s is not None else 0
                      for s in v], dtype=np.int64),
            _and_input_nulls(v, m))


@host_fn("translate")
def _translate(args):
    v, m = args[0]
    frm = str(np.asarray(args[1][0]).reshape(-1)[0])
    to = str(np.asarray(args[2][0]).reshape(-1)[0])
    table = {ord(f): (to[i] if i < len(to) else None)
             for i, f in enumerate(frm)}
    return _map_str(v, lambda s: s.translate(table)), m


def _sha_fn(algo):
    def fn(args):
        (v, m), = args
        return _obj([getattr(hashlib, algo)(str(s).encode()).hexdigest()
                     if s is not None else None for s in v]), m

    return fn


HOST_FUNCTIONS["sha224"] = _sha_fn("sha224")
HOST_FUNCTIONS["sha384"] = _sha_fn("sha384")
HOST_FUNCTIONS["sha512"] = _sha_fn("sha512")


@host_fn("extract_json_string")
def _extract_json_string(args):
    """First match, and only if it is a JSON string — non-string matches
    are NULL (json.rs extract_json_string matches Value::String only)."""
    return _json_path_walk(
        args, lambda o: o if isinstance(o, str) else None)


@host_fn("get_first_json_object")
def _get_first_json_object(args):
    import json as _json

    return _json_path_walk(
        args, lambda o: _json.dumps(o) if isinstance(o, (dict, list))
        else o)


# -- extended math (device) ---------------------------------------------------
# hyperbolics / roots / angle conversion / integer math, completing the
# reference's BuiltinScalarFunction math coverage (expressions.rs)

def _register_math_ext():
    import jax.numpy as jnp

    for name, fn in [
        ("sinh", jnp.sinh), ("cosh", jnp.cosh), ("tanh", jnp.tanh),
        ("asinh", jnp.arcsinh), ("acosh", jnp.arccosh),
        ("atanh", jnp.arctanh), ("cbrt", jnp.cbrt),
        ("degrees", jnp.degrees), ("radians", jnp.radians),
    ]:
        DEVICE_FUNCTIONS[name] = _unary_math(fn)

    DEVICE_FUNCTIONS["cot"] = _unary_math(lambda v: 1.0 / jnp.tan(v))

    def atan2(args):
        (y, my), (x, mx) = args
        return jnp.arctan2(y, x), _all_valid_mask([my, mx])

    DEVICE_FUNCTIONS["atan2"] = atan2

    def log(args):
        # Postgres: log(x) = log10; log(b, x) = log base b
        if len(args) == 1:
            (v, m), = args
            return jnp.log10(v), m
        (b, mb), (x, mx) = args
        return jnp.log(x) / jnp.log(b), _all_valid_mask([mb, mx])

    DEVICE_FUNCTIONS["log"] = log

    def pi(args):
        return jnp.pi, None

    DEVICE_FUNCTIONS["pi"] = pi

    def factorial(args):
        (v, m), = args
        # exact in int64 up to 20!; n > 20 overflows int64, so those rows
        # become NULL (the reference's DataFusion int64 factorial errors
        # on overflow — a masked-out row is our non-aborting analog)
        n = jnp.asarray(v, jnp.int64)
        ok = n <= 20
        nc = jnp.clip(n, 0, 20)
        i = jnp.arange(1, 21, dtype=jnp.int64)
        terms = jnp.where(i[None, :] <= nc[..., None], i[None, :],
                          jnp.int64(1))
        return jnp.prod(terms, axis=-1), (ok if m is None else m & ok)

    DEVICE_FUNCTIONS["factorial"] = factorial

    def gcd(args):
        from jax import lax

        (a, ma), (b, mb) = args
        x = jnp.abs(jnp.asarray(a, jnp.int64))
        y = jnp.abs(jnp.asarray(b, jnp.int64))
        x, y = jnp.broadcast_arrays(x, y)

        # exact Euclid: loop until every lane terminates (worst case ~90
        # iterations for int64 Fibonacci pairs — data-dependent, so a real
        # while_loop, not an unrolled approximation)
        def cond(s):
            return jnp.any(s[1] != 0)

        def body(s):
            sx, sy = s
            safe = jnp.where(sy == 0, 1, sy)
            return (jnp.where(sy != 0, sy, sx),
                    jnp.where(sy != 0, sx % safe, 0))

        x, _ = lax.while_loop(cond, body, (x, y))
        return x, _all_valid_mask([ma, mb])

    DEVICE_FUNCTIONS["gcd"] = gcd

    def lcm(args):
        (a, ma), (b, mb) = args
        g, m = gcd(args)
        x = jnp.abs(jnp.asarray(a, jnp.int64))
        y = jnp.abs(jnp.asarray(b, jnp.int64))
        v = jnp.where(g != 0, x // jnp.where(g == 0, 1, g) * y, 0)
        return v, m

    DEVICE_FUNCTIONS["lcm"] = lcm


_register_math_ext()


# -- extended strings / binary (host) ----------------------------------------


@host_fn("repeat")
def _repeat(args):
    (v, m), (n, mn) = args
    rows = _n_rows(args)
    out = []
    for i in range(rows):
        s, k = _row_get(v, i), _row_get(n, i)
        out.append(s * max(int(k), 0) if s is not None else None)
    return _obj(out), _all_valid_mask([m, mn])


@host_fn("reverse")
def _reverse(args):
    (v, m), = args
    rows = _n_rows(args)
    return _obj([(_row_get(v, i) or "")[::-1] if _row_get(v, i) is not None
                 else None for i in range(rows)]), m


@host_fn("btrim")
def _btrim(args):
    v, m = args[0]
    chars = None
    if len(args) > 1:
        cv = args[1][0]
        chars = cv if isinstance(cv, str) else str(np.asarray(cv).reshape(-1)[0])
    if isinstance(v, str) or np.ndim(v) == 0:
        sv = _row_get(v, 0)
        return np.asarray(sv.strip(chars) if sv is not None else None,
                          dtype=object), m
    return _obj([s.strip(chars) if s is not None else None for s in v]), m


@host_fn("to_hex")
def _to_hex(args):
    (v, m), = args

    def hx(x):
        # negatives render as 64-bit two's complement ('ffffffffffffffff'
        # for -1), matching Postgres/DataFusion — not '-<hex>'
        return format(int(x) & 0xFFFFFFFFFFFFFFFF, "x")

    vals = np.asarray(v)
    if vals.ndim == 0:  # scalar literal: 0-d result broadcasts downstream
        return np.asarray(hx(vals), dtype=object), m
    return _obj([hx(x) for x in vals.tolist()]), m


@host_fn("encode")
def _encode(args):
    import base64

    (v, m), (f, mf) = args
    fmt = f if isinstance(f, str) else str(np.asarray(f).reshape(-1)[0])
    fmt = fmt.lower()

    def enc(s):
        if s is None:
            return None
        raw = s.encode() if isinstance(s, str) else bytes(s)
        if fmt == "hex":
            return raw.hex()
        if fmt == "base64":
            return base64.b64encode(raw).decode()
        raise ValueError(f"encode: unknown format {fmt!r}")

    return _obj([enc(_row_get(v, i)) for i in range(_n_rows(args[:1]))]), \
        _all_valid_mask([m, mf])


@host_fn("decode")
def _decode(args):
    import base64

    (v, m), (f, mf) = args
    fmt = f if isinstance(f, str) else str(np.asarray(f).reshape(-1)[0])
    fmt = fmt.lower()

    def dec(s):
        if s is None:
            return None
        if fmt == "hex":
            raw = bytes.fromhex(s)
        elif fmt == "base64":
            raw = base64.b64decode(s)
        else:
            raise ValueError(f"decode: unknown format {fmt!r}")
        # valid UTF-8 round-trips as str; anything else stays raw bytes
        # rather than being mangled through replacement characters
        try:
            return raw.decode("utf-8")
        except UnicodeDecodeError:
            return raw

    return _obj([dec(_row_get(v, i)) for i in range(_n_rows(args[:1]))]), \
        _all_valid_mask([m, mf])


@host_fn("concat_ws")
def _concat_ws(args):
    (sep_v, sep_m) = args[0]
    rest = args[1:]
    n = _n_rows(args)
    out = []
    valid = np.ones(n, dtype=bool)
    for i in range(n):
        # the separator is evaluated per row (it may be a column), and a
        # NULL separator yields a NULL result (Postgres/DataFusion) —
        # NULL value args, by contrast, are merely skipped
        sep = _row_get(sep_v, i)
        # broadcastable length-1 masks (scalar-literal separator) index
        # row 0 for every row, same as _row_is_valid
        sm = (None if sep_m is None else np.asarray(sep_m).reshape(-1))
        if sep is None or (sm is not None
                           and not bool(sm[i if sm.shape[0] > 1 else 0])):
            out.append(None)
            valid[i] = False
            continue
        out.append(str(sep).join(str(_row_get(a[0], i)) for a in rest
                                 if _row_is_valid(a, i)))
    return _obj(out), (None if valid.all() else valid)


def _uuid(args, env):
    import uuid as _u

    n = len(env["__timestamp"])
    return _obj([str(_u.uuid4()) for _ in range(n)]), None


_uuid.needs_env = True
HOST_FUNCTIONS["uuid"] = _uuid


def _random(args, env):
    n = len(env["__timestamp"])
    return np.random.random(n), None


_random.needs_env = True
HOST_FUNCTIONS["random"] = _random


@host_fn("digest")
def _digest(args):
    (v, m), (a, ma) = args
    algo = a if isinstance(a, str) else str(np.asarray(a).reshape(-1)[0])
    algo = algo.lower().replace("-", "")

    def d(s):
        if s is None:
            return None
        h = hashlib.new(algo)
        h.update(s.encode() if isinstance(s, str) else bytes(s))
        return h.hexdigest()

    return _obj([d(_row_get(v, i)) for i in range(_n_rows(args[:1]))]), \
        _all_valid_mask([m, ma])


# -- extended datetime (host wallclock + device conversions) ------------------


def _now(args, env):
    import time as _t

    return np.int64(int(_t.time() * 1e6)), None


_now.needs_env = True
HOST_FUNCTIONS["now"] = _now
HOST_FUNCTIONS["current_timestamp"] = _now


def _current_date(args, env):
    import time as _t

    micros = int(_t.time() * 1e6)
    return np.int64(micros - micros % (86_400 * SECONDS)), None


_current_date.needs_env = True
HOST_FUNCTIONS["current_date"] = _current_date


def _current_time(args, env):
    import time as _t

    micros = int(_t.time() * 1e6)
    return np.int64(micros % (86_400 * SECONDS)), None


_current_time.needs_env = True
HOST_FUNCTIONS["current_time"] = _current_time


def _register_datetime_ext():
    import jax.numpy as jnp

    def to_ts_seconds(args):
        (v, m), = args
        return jnp.asarray(v, jnp.int64) * SECONDS, m

    def to_ts_millis(args):
        (v, m), = args
        return jnp.asarray(v, jnp.int64) * 1000, m

    def to_ts_micros(args):
        (v, m), = args
        return jnp.asarray(v, jnp.int64), m

    DEVICE_FUNCTIONS["to_timestamp_seconds"] = to_ts_seconds
    DEVICE_FUNCTIONS["to_timestamp_millis"] = to_ts_millis
    DEVICE_FUNCTIONS["to_timestamp_micros"] = to_ts_micros

    def date_bin(args):
        # date_bin(stride, ts, origin): floor ts into stride-sized bins
        # anchored at origin (DataFusion semantics)
        (stride, ms), (ts, mt) = args[0], args[1]
        origin = args[2][0] if len(args) > 2 else 0
        t = jnp.asarray(ts, jnp.int64)
        s = jnp.asarray(stride, jnp.int64)
        o = jnp.asarray(origin, jnp.int64)
        return o + ((t - o) // s) * s, _all_valid_mask([ms, mt])

    DEVICE_FUNCTIONS["date_bin"] = date_bin


_register_datetime_ext()


# -- arrays (host; object columns of python lists) ---------------------------
# the reference exposes DataFusion's array family (expressions.rs
# ArrayAppend/Concat/..); arrays travel as object columns of lists here


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple, np.ndarray)) else [x]


@host_fn("make_array")
def _make_array(args):
    n = len(args[0][0]) if args and hasattr(args[0][0], "__len__") \
        and not isinstance(args[0][0], str) else 1
    out = []
    for i in range(n):
        out.append([a[0][i] if hasattr(a[0], "__len__")
                    and not isinstance(a[0], str) else a[0] for a in args])
    return _obj(out), _all_valid_mask([m for _, m in args])


@host_fn("array_append")
def _array_append(args):
    (v, m), (x, mx) = args
    xs = x if hasattr(x, "__len__") and not isinstance(x, str) \
        else [x] * len(v)
    return _obj([(_as_list(a) + [b]) if a is not None else None
                 for a, b in zip(v, xs)]), _all_valid_mask([m, mx])


@host_fn("array_prepend")
def _array_prepend(args):
    (x, mx), (v, m) = args
    xs = x if hasattr(x, "__len__") and not isinstance(x, str) \
        else [x] * len(v)
    return _obj([([b] + _as_list(a)) if a is not None else None
                 for a, b in zip(v, xs)]), _all_valid_mask([m, mx])


@host_fn("array_concat")
def _array_concat(args):
    n = len(args[0][0])
    out = []
    for i in range(n):
        row = []
        for a, _m in args:
            if a[i] is not None:
                row.extend(_as_list(a[i]))
        out.append(row)
    return _obj(out), _all_valid_mask([m for _, m in args])


@host_fn("array_contains")
def _array_contains(args):
    (v, m), (x, mx) = args
    xs = x if hasattr(x, "__len__") and not isinstance(x, str) \
        else [x] * len(v)
    return np.array([b in _as_list(a) if a is not None else False
                     for a, b in zip(v, xs)]), _all_valid_mask([m, mx])


@host_fn("array_length")
def _array_length(args):
    v, m = args[0]
    return np.array([len(_as_list(a)) if a is not None else 0
                     for a in v], dtype=np.int64), m


HOST_FUNCTIONS["cardinality"] = HOST_FUNCTIONS["array_length"]


@host_fn("array_position")
def _array_position(args):
    (v, m), (x, mx) = args
    xs = x if hasattr(x, "__len__") and not isinstance(x, str) \
        else [x] * len(v)

    def pos(a, b):
        if a is None:
            return 0
        lst = _as_list(a)
        return lst.index(b) + 1 if b in lst else 0  # 1-based; 0 = absent

    out = np.array([pos(a, b) for a, b in zip(v, xs)], dtype=np.int64)
    return out, _all_valid_mask([m, mx])


@host_fn("array_positions")
def _array_positions(args):
    (v, m), (x, mx) = args
    xs = x if hasattr(x, "__len__") and not isinstance(x, str) \
        else [x] * len(v)
    return _obj([[i + 1 for i, el in enumerate(_as_list(a)) if el == b]
                 if a is not None else None
                 for a, b in zip(v, xs)]), _all_valid_mask([m, mx])


@host_fn("array_remove")
def _array_remove(args):
    (v, m), (x, mx) = args
    xs = x if hasattr(x, "__len__") and not isinstance(x, str) \
        else [x] * len(v)
    return _obj([[el for el in _as_list(a) if el != b]
                 if a is not None else None
                 for a, b in zip(v, xs)]), _all_valid_mask([m, mx])


@host_fn("array_replace")
def _array_replace(args):
    (v, m), (x, mx), (y, my) = args
    n = len(v)
    xs = x if hasattr(x, "__len__") and not isinstance(x, str) else [x] * n
    ys = y if hasattr(y, "__len__") and not isinstance(y, str) else [y] * n
    return _obj([[c if el == b else el for el in _as_list(a)]
                 if a is not None else None
                 for a, b, c in zip(v, xs, ys)]), \
        _all_valid_mask([m, mx, my])


@host_fn("array_to_string")
def _array_to_string(args):
    (v, m), (s, ms) = args
    sep = s if isinstance(s, str) else str(np.asarray(s).reshape(-1)[0])
    return _obj([sep.join(str(el) for el in _as_list(a))
                 if a is not None else None
                 for a in v]), _all_valid_mask([m, ms])


@host_fn("trim_array")
def _trim_array(args):
    (v, m), (n, mn) = args
    nn = np.broadcast_to(np.asarray(n).astype(int), (len(v),))
    return _obj([_as_list(a)[:max(len(_as_list(a)) - int(k), 0)]
                 if a is not None else None
                 for a, k in zip(v, nn)]), _all_valid_mask([m, mn])


@host_fn("array_ndims")
def _array_ndims(args):
    v, m = args[0]

    def nd(a):
        d = 0
        while isinstance(a, (list, tuple)) and a:
            d += 1
            a = a[0]
        return d if d else (1 if isinstance(a, (list, tuple)) else 0)

    return np.array([nd(a) if a is not None else 0 for a in v],
                    dtype=np.int64), m


@host_fn("array_dims")
def _array_dims(args):
    v, m = args[0]

    def dims(a):
        out = []
        while isinstance(a, (list, tuple)):
            out.append(len(a))
            a = a[0] if a else None
        return out

    return _obj([dims(a) if a is not None else None for a in v]), m
