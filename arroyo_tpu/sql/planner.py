"""SQL planner: AST -> logical dataflow Program.

The analog of the reference's ``SqlPipelineBuilder`` + ``PlanGraph``
(arroyo-sql/src/pipeline.rs:384-441, plan_graph.rs:36-94) with its optimizer
decisions folded in: mergeable windowed aggregates plan straight onto the
two-phase binned aggregator (the reference's two-phase rewrite,
optimizations.rs:241-291), session windows and DISTINCT aggregates fall back
to the buffered window operator, aggregate-without-window becomes the
updating NonWindowAggregator, and joins become windowed hash joins (window
equality present) or TTL'd updating joins."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..graph.logical import (
    AggKind,
    AggSpec,
    ColumnExpr,
    ExprReturnType,
    InstantWindow,
    JoinType,
    LogicalOperator,
    OpKind,
    Program,
    SessionWindow,
    SlidingWindow,
    SlidingAggregatingTopNSpec,
    Stream,
    TopNSpec,
    TumblingWindow,
)
from .ast_nodes import (
    BinaryOp,
    InSubquery,
    Case,
    Cast,
    ColumnRef,
    CreateTable,
    DerivedTable,
    Explain,
    Expr,
    FunctionCall,
    Insert,
    IntervalLit,
    IsNull,
    Join,
    JoinKind,
    Literal,
    NamedTable,
    map_children,
    Select,
    SelectItem,
    Star,
    TableRef,
    UnaryOp,
)
from .compiler import Compiled, Schema, SqlCompileError, StructDef, compile_scalar
from .parser import parse_sql
from .schema_provider import SchemaProvider, TableDef

AGG_NAMES = {"count", "sum", "min", "max", "avg"}


def _is_agg_name(name: str) -> bool:
    from .functions import UDAFS

    return name in AGG_NAMES or name in UDAFS
DEFAULT_JOIN_TTL = 3_600_000_000  # 1h, micros
DEFAULT_UPDATING_TTL = 86_400_000_000  # 1d (reference updating default)


class SqlPlanError(ValueError):
    pass


class _TeeSet:
    """``add``-only set fanning out to several sides' used-column sets
    (join output schemas: a column may belong to either source)."""

    def __init__(self, sinks):
        self.sinks = sinks

    def add(self, item):
        for s in self.sinks:
            s.add(item)


def _conjuncts(e: Expr) -> List[Expr]:
    """Flatten a predicate's top-level AND chain."""
    if isinstance(e, BinaryOp) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _conjoin(parts: List[Expr]) -> Optional[Expr]:
    out = None
    for c in parts:
        out = c if out is None else BinaryOp("and", out, c)
    return out


def _expr_name(e: Expr, i: int) -> str:
    if isinstance(e, ColumnRef):
        return e.name.lower()
    if isinstance(e, FunctionCall):
        return f"{e.name}_{i}"
    if isinstance(e, Cast):
        return _expr_name(e.operand, i)
    return f"expr_{i}"


def _window_from_call(fc: FunctionCall):
    def micros(arg):
        if isinstance(arg, IntervalLit):
            return arg.micros
        if isinstance(arg, Literal) and arg.type == "string":
            # the reference accepts bare duration strings in window
            # functions: session('30 seconds')
            from .parser import SqlParseError, duration_text_micros

            try:
                return duration_text_micros(arg.value)
            except SqlParseError as e:
                raise SqlPlanError(str(e))
        raise SqlPlanError(f"{fc.name}() arguments must be INTERVALs")

    if fc.name == "tumble":
        return TumblingWindow(micros(fc.args[0]))
    if fc.name == "hop":
        if len(fc.args) != 2:
            raise SqlPlanError("hop(slide, width) takes two intervals")
        return SlidingWindow(width_micros=micros(fc.args[1]),
                             slide_micros=micros(fc.args[0]))
    if fc.name == "session":
        return SessionWindow(micros(fc.args[0]))
    return None


class AggCollector:
    """Find aggregate calls in an expression tree and replace them with
    placeholder column refs ``__agg{i}``."""

    def __init__(self) -> None:
        self.aggs: List[FunctionCall] = []

    def rewrite(self, e: Expr) -> Expr:
        if isinstance(e, FunctionCall):
            if e.over is not None:
                # the ROW_NUMBER TopN shape is rewritten before planning;
                # any OVER clause reaching here would be silently treated
                # as a plain aggregate — reject instead
                raise SqlPlanError(
                    f"window function {e.name}() OVER (...) is only "
                    "supported as ROW_NUMBER() OVER (PARTITION BY window "
                    "ORDER BY col DESC) with an outer rank filter")
            if _is_agg_name(e.name):
                for j, existing in enumerate(self.aggs):
                    if repr(existing) == repr(e):
                        return ColumnRef(f"__agg{j}")
                self.aggs.append(e)
                return ColumnRef(f"__agg{len(self.aggs) - 1}")
        return map_children(e, self.rewrite)


def _has_aggregates(sel: Select) -> bool:
    c = AggCollector()
    for item in sel.items:
        if not isinstance(item.expr, Star):
            c.rewrite(item.expr)
    if sel.having is not None:
        c.rewrite(sel.having)
    return bool(c.aggs) or bool(sel.group_by)


def _apply_validity(v, m):
    """Materialize a SQL validity mask into the projected column: None for
    object/string/host-bool rows, NaN for numerics (the engine's null
    convention; nullable int results are promoted to f64, exact to 2^53;
    traced-bool results become f64 0.0/1.0/NaN — the only null-capable
    dtype available inside jit)."""
    if isinstance(v, (str, bytes)) or (
            isinstance(v, np.ndarray) and v.dtype.kind in "USO"):
        mm = np.asarray(m, dtype=bool)
        if mm.ndim == 0 and np.ndim(v) == 0:
            return (v.item() if isinstance(v, np.ndarray) else v) \
                if bool(mm) else None
        n = mm.shape[0] if mm.ndim else np.shape(v)[0]
        out = np.empty(n, dtype=object)
        out[:] = np.broadcast_to(np.asarray(v, dtype=object), (n,))
        out[~np.broadcast_to(mm, (n,))] = None
        return out
    if isinstance(v, np.ndarray) and v.dtype == np.bool_ \
            and not hasattr(m, "aval"):
        out = v.astype(object)
        out[~np.broadcast_to(np.asarray(m, dtype=bool), v.shape)] = None
        return out
    import jax.numpy as jnp

    arr = jnp.asarray(v)
    if not jnp.issubdtype(arr.dtype, jnp.floating):
        arr = arr.astype(jnp.float64)
    return jnp.where(jnp.asarray(m), arr, jnp.nan)


# per-process nonce space for null join keys: a random 30-bit salt in
# the high bits (distinct processes land in distinct 2^33-row regions)
# plus a monotone row counter
_jk_nonce_next = [(__import__("secrets").randbits(30) << 33) | (1 << 62)]


def _null_key_nonce_fn(base_fn: Callable, jk_cols: List[str]) -> Callable:
    """Wrap a join-key map so null-keyed rows get a UNIQUE nonce (valid
    rows get 0): SQL NULL keys must never equal anything, including each
    other.  Uniqueness spans batches (a per-process counter in the high
    bits) and processes (a random salt); restored buffers keep their old
    nonces, which a fresh salt cannot collide with in practice — the
    same 64-bit-hash-uniqueness assumption the join itself rests on."""

    def fn(cols: Dict[str, Any]) -> Dict[str, Any]:
        out = base_fn(cols)
        from ..formats import nan_validity

        n = len(np.asarray(cols["__timestamp"]))
        nullmask = np.zeros(n, dtype=bool)
        for c in jk_cols:
            v = np.asarray(out[c])
            out[c] = v  # keep the host copy: downstream must not convert again
            # route through THE null definition (formats.nan_validity) so
            # the nonce cannot drift from IS NULL semantics (e.g. object
            # cells holding np.float32 NaN)
            ok = nan_validity(v, None)
            if ok is not None:
                nullmask |= ~np.asarray(ok)
        nonce = np.zeros(n, dtype=np.int64)
        if nullmask.any():
            idx = nullmask.nonzero()[0]
            base = _jk_nonce_next[0]
            _jk_nonce_next[0] = base + len(idx)
            nonce[idx] = base + np.arange(len(idx), dtype=np.int64)
        out["__jknonce"] = nonce
        return out

    return fn


def _zero_nonce_fn(base_fn: Callable) -> Callable:
    """Join-key map variant for keys that can never be NULL (all-window
    joins): a constant-zero nonce, jit-traceable, so the projection
    stays on the padded/jitted map path."""

    def fn(cols: Dict[str, Any]) -> Dict[str, Any]:
        out = base_fn(cols)
        out["__jknonce"] = np.zeros(len(cols["__timestamp"]),
                                    dtype=np.int64)
        return out

    return fn


def _wrap_record(compiled: List[Tuple[str, Compiled]], passthrough: List[str]
                 ) -> Callable:
    """Build a cols->cols projection fn from compiled items."""

    def fn(cols: Dict[str, Any]) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name, c in compiled:
            v, m = c.fn(cols)
            if m is not None:
                v = _apply_validity(v, m)
            if np.ndim(v) == 0:
                # scalar result (python scalar OR 0-d array): broadcast.
                # jnp handles traced values (this fn can run inside jit);
                # np.full would choke on tracers
                n = len(cols["__timestamp"])
                if v is None:  # scalar NULL (e.g. nullif of equal literals)
                    v = np.full(n, None, dtype=object)
                elif isinstance(v, (np.ndarray, np.generic, int, float, bool,
                                    str)):
                    v = np.full(n, v)
                else:
                    import jax.numpy as jnp

                    v = jnp.broadcast_to(v, (n,))
            out[name] = v
        for name in passthrough:
            if name in cols:
                out[name] = cols[name]
        # NOTE: __timestamp is deliberately NOT passed through here — the
        # engine preserves batch.timestamp (int64 micros) host-side when the
        # projection doesn't set it, keeping epoch timestamps out of jit
        # (where x64-disabled JAX would truncate them to int32)
        return out

    # compile-time column footprint -> executor skips untouched columns
    used = set(passthrough) | {"__timestamp"}
    for _name, c in compiled:
        if c.used_cols is None:
            used = None
            break
        used |= c.used_cols
    if used is not None:
        fn.used_cols = frozenset(used)
    return fn


def _wrap_predicate(compiled: Compiled) -> Callable:
    def fn(cols: Dict[str, Any]) -> Any:
        v, m = compiled.fn(cols)
        import jax.numpy as jnp

        v = jnp.asarray(v).astype(bool) if not isinstance(v, np.ndarray) \
            else v.astype(bool)
        if m is not None:
            v = v & m
        return v

    if compiled.used_cols is not None:
        fn.used_cols = frozenset(compiled.used_cols | {"__timestamp"})
    return fn


@dataclass
class Planned:
    stream: Stream
    schema: Schema
    # set when this plan ends in [binned window aggregate -> projection]:
    # the aggregate's node id and the SELECT-name -> internal agg output
    # mapping, so a following ORDER BY/LIMIT can fuse into the aggregate
    agg_node: Optional[str] = None
    agg_map: Optional[Dict[str, str]] = None
    # the stream carries __op retraction rows (updating aggregates, outer
    # joins): downstream projections must pass the column through
    updating: bool = False
    # set when this plan is `SELECT max/min(x), window FROM <windowed
    # aggregate> GROUP BY window` (q5's MaxBids shape): the inner
    # aggregate's node id, the internal agg output x maps to, max|min,
    # the visible output column, and the inner window's width — the
    # join planner fuses a self-join against this into WindowArgmax
    max_of: Optional[Dict[str, Any]] = None
    # set when this plan ends in an INNER equi-join: the already-keyed
    # side streams, their visible specs, and per-key-slot sets of
    # joined-schema column names carrying the key's value — a following
    # cascaded join on the same key extends into ONE multi-way join
    # operator instead of nesting (no pairwise intermediates)
    multi_join: Optional[Dict[str, Any]] = None


class Planner:
    def __init__(self, provider: Optional[SchemaProvider] = None):
        self.provider = provider or SchemaProvider()
        self._sql_counter = 0

    # -- top level ---------------------------------------------------------

    def plan(self, sql: str, query_parallelism: int = 1) -> Program:
        """parse_and_get_program analog (arroyo-sql/src/lib.rs:350-362)."""
        stmts = parse_sql(sql)
        program: Optional[Program] = None
        inserts: List[Insert] = []
        selects: List[Select] = []
        explains: List[Explain] = []
        for s in stmts:
            if isinstance(s, CreateTable):
                self.provider.add_create_table(s)
            elif isinstance(s, Insert):
                inserts.append(s)
            elif isinstance(s, Select):
                selects.append(s)
            elif isinstance(s, Explain):
                explains.append(s)

        self.parallelism = query_parallelism
        self._pushdowns: List[Tuple[Dict[str, Any], set]] = []
        if explains:
            if inserts or selects or len(explains) > 1:
                raise SqlPlanError(
                    "EXPLAIN must be the only executable statement in a "
                    "script (CREATE TABLEs are fine)")
            return self._plan_explain(explains[0])
        prog = Program()
        if inserts:
            for ins in inserts:
                self._plan_insert(ins, prog)
        elif selects:
            # bare SELECT: attach the preview sink (the reference auto-adds a
            # GrpcSink streaming results to the console, lib.rs:386-418)
            planned = self.plan_select(selects[-1], prog, {})
            planned.stream.sink("memory", {"name": "results"})
        else:
            raise SqlPlanError("no executable statement (SELECT/INSERT) found")
        # projection pushdown: now that every expression has compiled, hand
        # each source the union of physical columns the query touches
        for op_cfg, used in self._pushdowns:
            if used:
                op_cfg["projection"] = sorted(used)
        # drop subplans the optimizer bypassed (argmax fusion's pruned
        # max side), then merge textually duplicated subplans (q5's
        # double hop aggregate, q8's double source scan)
        prog.prune_dead()
        prog.eliminate_common_subplans()
        self._push_argmax_local(prog)
        # factor-window sharing (graph/factor_windows.py): correlated
        # window aggregates left distinct by CSE (same input/keys,
        # DIFFERENT widths/slides) rewrite onto one shared pane ring.
        # Runs after argmax_local so emission-coupled aggregates are
        # visible (and excluded); ARROYO_FACTOR_WINDOWS=0 is a no-op.
        from ..graph.factor_windows import apply_factor_windows

        apply_factor_windows(prog)
        return prog

    @staticmethod
    def _push_argmax_local(prog: Program) -> None:
        """Let the window aggregate's EMISSION pre-filter to local
        per-pane argmax candidates when a WindowArgmax stage is its only
        consumer: every global argmax row is also a local argmax row
        (value <= local max <= global max with equality required), so
        the filter is a sound superset and the argmax stage settles the
        global answer.  On a tunneled TPU this collapses the dominant
        pane readback from every (key, pane) cell to ~ties-per-pane.

        Applies only when (a) the chain from aggregate to argmax is
        single-consumer row-preserving projections/key_bys — a second
        consumer or a filter would see pruned rows — and (b) the tracked
        value is a bare COUNT(*): null-skipping aggregates hold device
        identities for all-null panes, which a device-side max would
        wrongly rank."""
        for nid in list(prog.graph.nodes):
            node = prog.node(nid)
            if node.operator.kind != OpKind.WINDOW_ARGMAX:
                continue
            spec = node.operator.spec
            if not spec.agg_out:
                continue
            preds = list(prog.graph.predecessors(nid))
            ok = len(preds) == 1
            cur = preds[0] if ok else None
            while ok and prog.node(cur).operator.kind in (
                    OpKind.EXPRESSION, OpKind.KEY_BY, OpKind.UDF):
                op = prog.node(cur).operator
                # row preservation must be proven, not assumed: host
                # FILTERS also compile as RECORD-typed UDF nodes, so the
                # only expression nodes accepted are the planner's own
                # post-aggregate projections (pure column maps by
                # construction) — anything else bails
                if (op.kind != OpKind.KEY_BY
                        and not op.name.startswith("agg_project_")):
                    ok = False
                    break
                if (op.expr is not None
                        and op.expr.return_type != ExprReturnType.RECORD):
                    ok = False
                    break
                if prog.graph.out_degree(cur) != 1:
                    ok = False
                    break
                preds = list(prog.graph.predecessors(cur))
                if len(preds) != 1:
                    ok = False
                    break
                cur = preds[0]
            if not ok or cur is None:
                continue
            agg = prog.node(cur)
            if agg.operator.kind not in (
                    OpKind.SLIDING_WINDOW_AGGREGATOR,
                    OpKind.TUMBLING_WINDOW_AGGREGATOR):
                continue
            if prog.graph.out_degree(cur) != 1:
                continue
            aspec = agg.operator.spec
            target = next((a for a in aspec.aggs
                           if a.output == spec.agg_out), None)
            if (target is None or target.kind != AggKind.COUNT
                    or target.column is not None):
                continue
            aspec.argmax_local = (spec.agg_out, spec.minmax)

    def _plan_insert(self, ins: Insert, prog: Program) -> None:
        sink_table = self.provider.get(ins.table)
        if not sink_table.is_sink and sink_table.connector in ("kafka",):
            pass
        planned = self.plan_select(ins.query, prog, {})
        # positional projection onto the sink's declared columns
        declared = [c.name.lower() for c in sink_table.columns]
        have = [c for c in planned.schema.columns if not c.startswith("__")]
        if declared and len(declared) == len(have) and declared != have:
            mapping = list(zip(declared, have))

            def rename(cols, _mapping=mapping):
                out = {new: cols[old] for new, old in _mapping}
                out["__timestamp"] = cols["__timestamp"]
                return out

            planned = Planned(
                planned.stream.udf(rename, name=f"to_{ins.table}"),
                planned.schema)
        # single_file appends to ONE local path: parallel subtasks would
        # open/truncate the same file over each other — pin to one
        # subtask (across rescales too)
        par = 1 if sink_table.connector == "single_file" else None
        planned.stream.sink(sink_table.connector, sink_table.config,
                            parallelism=par, max_parallelism=par,
                            name=f"{ins.table}_sink")

    # -- FROM --------------------------------------------------------------

    def plan_select(self, sel: Select, prog: Program,
                    ctes: Dict[str, Planned]) -> Planned:
        scope = dict(ctes)
        for name, cte_sel in sel.ctes:
            scope[name.lower()] = self.plan_select(cte_sel, prog, scope)

        if sel.from_ is None:
            raise SqlPlanError("SELECT without FROM is not a stream")
        # canonical ROW_NUMBER TopN: FROM (SELECT ..., ROW_NUMBER() OVER
        # (PARTITION BY window ORDER BY x DESC) rn FROM ...) WHERE rn <= k
        rewritten = self._rewrite_rownumber_topn(sel, prog, scope)
        if rewritten is not None:
            upstream, remaining_where = rewritten
        else:
            upstream = self._plan_table_ref(sel.from_, prog, scope,
                                            where=sel.where)
            remaining_where = sel.where

        # WHERE: IN (SELECT ...) conjuncts become semi-joins, the rest a
        # filter
        if remaining_where is not None:
            upstream, remaining_where = self._apply_in_subqueries(
                upstream, remaining_where, prog, scope)
        if remaining_where is not None:
            upstream = self._filter(upstream, remaining_where, "where")

        # top-level ROW_NUMBER() OVER (...) with no outer filter shape:
        # rank-only per-window TopN (no pruning), the rank materialized
        # as a column and the select item rewritten to read it
        rn_top = [(i, it) for i, it in enumerate(sel.items)
                  if isinstance(it.expr, FunctionCall)
                  and it.expr.name == "row_number"
                  and it.expr.over is not None]
        if rn_top and rewritten is None:
            from dataclasses import replace as _replace

            if len(rn_top) > 1:
                raise SqlPlanError(
                    "only one ROW_NUMBER() per query is supported")
            # only aggregate-free selects qualify: with aggregates the
            # rank would bind to the pre-aggregation stream (the sort
            # column does not exist there) — fall through so the agg
            # collector reports the unsupported OVER shape instead
            rn_idxs = {i for i, _ in rn_top}
            sel_no_rn = _replace(sel, items=[
                it for i, it in enumerate(sel.items) if i not in rn_idxs])
            if _has_aggregates(sel_no_rn):
                rn_top = []
        if rn_top and rewritten is None:
            from dataclasses import replace as _replace

            idx, it = rn_top[0]
            alias = (it.alias or "row_number").lower()
            over = it.expr.over
            if not over.order_by or len(over.order_by) != 1 \
                    or not isinstance(over.order_by[0].expr, ColumnRef):
                raise SqlPlanError(
                    "ROW_NUMBER() OVER requires ORDER BY a single column")
            if not over.order_by[0].desc:
                raise SqlPlanError(
                    "streaming TopN requires ORDER BY ... DESC")
            part_cols = self._rownumber_partition(over, upstream.schema)
            shim = Select(items=[], order_by=[over.order_by[0]], limit=None)
            upstream = self._plan_top_n(shim, upstream, tuple(part_cols),
                                        rank_column=alias)
            new_items = list(sel.items)
            new_items[idx] = SelectItem(ColumnRef(alias),
                                        it.alias or "row_number")
            sel = _replace(sel, items=new_items)

        if _has_aggregates(sel):
            planned = self._plan_aggregate(sel, upstream)
        else:
            planned = self._plan_projection(sel, upstream)

        if sel.having is not None and not _has_aggregates(sel):
            planned = self._filter(planned, sel.having, "having")

        if sel.union_all is not None and (sel.order_by
                                          or sel.limit is not None):
            # a leading ORDER BY/LIMIT would be planned as a branch-local
            # TopN before the union — ambiguous; standard SQL requires
            # parens here
            raise SqlPlanError(
                "ORDER BY/LIMIT on a UNION ALL branch must be wrapped "
                "in a subquery (SELECT * FROM (...) LIMIT ...)")
        if sel.order_by and sel.limit is not None:
            planned = self._plan_top_n(sel, planned)

        if sel.union_all is not None:
            if sel.union_all.order_by or sel.union_all.limit is not None:
                # trailing ORDER BY/LIMIT would bind to the last branch
                # only — reject rather than silently cap one branch
                raise SqlPlanError(
                    "ORDER BY/LIMIT after UNION ALL must be applied via an "
                    "outer SELECT (e.g. SELECT * FROM (... UNION ALL ...) "
                    "ORDER BY ... LIMIT ...)")
            # branches see the same scope (incl. this select's CTEs)
            other = self.plan_select(sel.union_all, prog, scope)
            ours = {(c, k) for c, k in planned.schema.columns.items()
                    if not c.startswith("__")}
            theirs = {(c, k) for c, k in other.schema.columns.items()
                      if not c.startswith("__")}
            if ours != theirs:
                raise SqlPlanError(
                    f"UNION ALL branches must produce the same columns and "
                    f"types ({sorted(ours)} vs {sorted(theirs)})")
            if planned.updating != other.updating:
                # mixing __op retraction rows with append-only rows would
                # leave downstream batches with inconsistent columns
                raise SqlPlanError(
                    "UNION ALL branches must both be updating or both "
                    "append-only")
            merged = planned.stream.union(
                other.stream, name=f"union_{self._next_id()}")
            mschema = planned.schema.clone()
            # provenance holds for the union only where EVERY branch
            # proves it (a lone non-event-time branch would let the raw
            # argmax fusion mis-window that branch's rows)
            mschema.event_time_cols &= other.schema.event_time_cols
            planned = Planned(merged, mschema,
                              updating=planned.updating or other.updating)
        return planned

    def _plan_explain(self, ex: Explain) -> Program:
        """EXPLAIN <select>: plan the inner query, then return a program
        that EMITS the planned DAG as rows (operator_id, operator,
        parallelism, inputs) — database-style, runs through any runner/
        console.  The reference bails on EXPLAIN (pipeline.rs:432)."""
        from ..types import Batch

        inner = Program()
        planned = self.plan_select(ex.query, inner, {})
        # the SAME terminal a bare SELECT gets (preview sink) + the same
        # post-planning pushdown injection, so EXPLAIN shows the plan
        # that would actually run
        planned.stream.sink("memory", {"name": "results"})
        for op_cfg, used in self._pushdowns:
            if used:
                op_cfg["projection"] = sorted(used)
        self._pushdowns = []
        rows = []
        for node_id in inner.topo_order():
            node = inner.node(node_id)
            preds = [inner.node(p).operator_id
                     for p in inner.graph.predecessors(node_id)]
            rows.append({
                "operator_id": node.operator_id,
                "operator": node.operator.kind.value,
                "name": node.operator.name,
                "parallelism": node.parallelism,
                "inputs": ", ".join(preds),
            })
        cols = {k: np.array([r[k] for r in rows], dtype=object)
                for k in ("operator_id", "operator", "name", "inputs")}
        cols["parallelism"] = np.array(
            [r["parallelism"] for r in rows], dtype=np.int64)
        batch = Batch(np.zeros(len(rows), dtype=np.int64), cols)
        prog = Program()
        (Stream.source("memory", {"batches": [batch]}, program=prog,
                       name="explain")
         .sink("memory", {"name": "results"}))
        return prog

    def _plan_table_ref(self, tr: TableRef, prog: Program,
                        scope: Dict[str, Planned],
                        where: Optional[Expr] = None) -> Planned:
        if isinstance(tr, NamedTable):
            key = tr.name.lower()
            if key in scope:
                base = scope[key]
                schema = base.schema.clone()
                if tr.alias:
                    schema.aliases.add(tr.alias)
                schema.aliases.add(tr.name)
                return Planned(base.stream, schema, updating=base.updating)
            td = self.provider.get(tr.name)
            planned = self._plan_source(td, prog)
            schema = planned.schema.clone()
            if tr.alias:
                schema.aliases.add(tr.alias)
            schema.aliases.add(tr.name)
            return Planned(planned.stream, schema)
        if isinstance(tr, DerivedTable):
            planned = self.plan_select(tr.query, prog, scope)
            schema = planned.schema.clone()
            if tr.alias:
                schema.aliases.add(tr.alias)
            # aggregate provenance survives the alias wrap: the join
            # planner's argmax fusion reads it off the subquery sides
            return Planned(planned.stream, schema,
                           agg_node=planned.agg_node,
                           agg_map=planned.agg_map,
                           updating=planned.updating,
                           max_of=planned.max_of)
        if isinstance(tr, Join):
            return self._plan_join(tr, prog, scope, where=where)
        raise SqlPlanError(f"unsupported FROM clause {tr!r}")

    # connectors whose sources honor a 'projection' config hint (the
    # DataFusion projection-pushdown analog): the planner records every
    # physical column the query resolves against the source schema and
    # hands the final set to the connector, which skips generating or
    # decoding untouched columns
    PROJECTION_PUSHDOWN = {"nexmark"}

    def _plan_source(self, td: TableDef, prog: Program) -> Planned:
        stream = Stream.source(td.connector, td.config, program=prog,
                               parallelism=self.parallelism,
                               name=f"{td.name}_source")
        schema = td.schema.clone()
        if td.connector in self.PROJECTION_PUSHDOWN:
            used: set = set()
            if td.event_time_field:
                used.add(td.event_time_field.lower())
            if td.watermark_field:
                used.add(td.watermark_field.lower())
            schema.source_used = used
            op_cfg = prog.node(stream.tail).operator.spec.config
            self._pushdowns.append((op_cfg, used))

        # generated (virtual) columns (tables.rs virtual fields)
        if td.generated:
            compiled = []
            for name, kind, expr in td.generated:
                compiled.append((name, compile_scalar(expr, schema)))
            passthrough = [c for c in schema.columns
                           if c not in {n for n, _, _ in td.generated}]
            fn = _wrap_record(compiled, passthrough)
            # timestamp-typed generated columns stay host-side (int64 micros)
            host = (any(c.needs_host for _, c in compiled)
                    or any(kind == "t" for _, kind, _ in td.generated))
            stream = (stream.udf(fn, name=f"{td.name}_virtual") if host
                      else stream.map(fn, name=f"{td.name}_virtual"))

        # event-time column (host path: timestamps are int64 micros)
        if td.event_time_field:
            et = td.event_time_field.lower()

            def set_ts(cols, _et=et):
                out = dict(cols)
                out["__timestamp"] = np.asarray(cols[_et], dtype=np.int64)
                return out

            # structural token: two scans of the same table plan this
            # udf twice with distinct closures — the token keeps
            # subplan_equal/CSE comparing them by meaning, not identity
            stream = stream.udf(set_ts, name=f"{td.name}_event_time",
                                sql=f"set_ts:{td.name}:{et}")
            # after set_ts the column IS the stream timestamp
            schema.event_time_cols.add(et)

        # watermark generator
        if td.watermark_field:
            wf = td.watermark_field.lower()
            stream = stream.watermark(
                expression=lambda cols, _wf=wf: {"__timestamp": cols[_wf]},
                name=f"{td.name}_watermark")
        else:
            stream = stream.watermark(
                max_lateness_micros=td.default_lateness_micros,
                name=f"{td.name}_watermark")
        return Planned(stream, schema)

    # -- filters / projections --------------------------------------------

    def _filter(self, planned: Planned, pred: Expr, name: str) -> Planned:
        # `WHERE s IS NOT NULL` conjuncts guarantee struct presence on
        # surviving rows: downstream field loads can skip the presence
        # mask (and the NULL materialization it would force) entirely
        guaranteed = set()
        for c in _conjuncts(pred):
            if isinstance(c, IsNull) and c.negated \
                    and isinstance(c.operand, ColumnRef):
                try:
                    kind, target = planned.schema.resolve(
                        c.operand, record=False)
                except SqlCompileError:
                    continue
                if kind == "struct":
                    guaranteed.add(target.name.lower())
        compiled = compile_scalar(pred, planned.schema)
        fn = _wrap_predicate(compiled)
        # STRUCTURAL token (same canonicalization as aggin): textually
        # repeated WHERE clauses (every multi-query script over one
        # source repeats its null-guard) now CSE-merge even when the
        # chains diverge below — which is what lets the factor-window
        # pass see correlated aggregates hanging off ONE shared filter
        pred_tok = f"{name}:" + self._canon_token(pred, planned.schema)
        expr = ColumnExpr(f"{name}_{self._next_id()}", fn,
                          ExprReturnType.PREDICATE, sql=pred_tok)
        if compiled.needs_host:
            stream = planned.stream._chain(LogicalOperator(
                OpKind.UDF, expr.name,
                expr=ColumnExpr(expr.name, self._host_filter(fn),
                                ExprReturnType.RECORD, sql=pred_tok)))
        else:
            stream = planned.stream._chain(LogicalOperator(
                OpKind.EXPRESSION, expr.name, expr=expr))
        schema = planned.schema
        if guaranteed:
            schema = schema.clone()
            schema.presence_guaranteed |= guaranteed
        return Planned(stream, schema, updating=planned.updating)

    @staticmethod
    def _host_filter(pred_fn):
        def fn(cols):
            mask = np.asarray(pred_fn(cols)).astype(bool)
            if mask.ndim == 0:
                # constant predicate (e.g. a now()-only comparison):
                # indexing columns with a scalar bool would dimension-
                # lift every column to (1, n) and crash downstream
                # (mirrored in ops/expr.eval_predicate for the jitted
                # path; see the note there on why the sites are split)
                mask = np.full(len(cols["__timestamp"]), bool(mask))
            return {k: np.asarray(v)[mask] for k, v in cols.items()}

        return fn

    def _next_id(self) -> int:
        self._sql_counter += 1
        return self._sql_counter

    def _expand_items(self, sel: Select, schema: Schema
                      ) -> List[Tuple[str, Expr]]:
        """Resolve * and name every projection item."""
        out: List[Tuple[str, Expr]] = []
        for i, item in enumerate(sel.items):
            if isinstance(item.expr, Star):
                q = item.expr.qualifier
                if q and (q in schema.structs or q.lower() in schema.structs):
                    sd = schema.structs.get(q) or schema.structs[q.lower()]
                    for fname, phys in sd.fields.items():
                        out.append((fname, ColumnRef(fname, sd.name)))
                else:
                    for col in schema.columns:
                        if not col.startswith("__"):
                            out.append((col, ColumnRef(col)))
                    if schema.window:
                        pass
                continue
            name = item.alias.lower() if item.alias else _expr_name(item.expr, i)
            out.append((name, item.expr))
        return out

    def _plan_projection(self, sel: Select, planned: Planned) -> Planned:
        schema = planned.schema
        items = self._expand_items(sel, schema)

        compiled: List[Tuple[str, Compiled]] = []
        new_schema = Schema(aliases=set(), window=False,
                            window_names=set())
        passthrough: List[str] = []
        needs_host = False
        identity = True
        for name, expr in items:
            if isinstance(expr, ColumnRef):
                try:
                    kind, target = schema.resolve(expr)
                except SqlCompileError:
                    kind, target = "col", None
                if kind == "struct":
                    sd: StructDef = target
                    new_schema.structs[name] = StructDef(
                        name, dict(sd.fields), sd.presence_col,
                        sd.presence_val)
                    passthrough.extend(sd.fields.values())
                    if sd.presence_col:
                        passthrough.append(sd.presence_col)
                    for f, phys in sd.fields.items():
                        if phys in schema.columns:
                            new_schema.columns[phys] = schema.columns[phys]
                    continue
                if kind == "window":
                    new_schema.window = True
                    new_schema.window_names.add(name)
                    passthrough.extend(["window_start", "window_end"])
                    new_schema.columns["window_start"] = "t"
                    new_schema.columns["window_end"] = "t"
                    continue
            c = compile_scalar(expr, schema)
            needs_host = needs_host or c.needs_host
            compiled.append((name, c))
            new_schema.columns[name] = self._infer_kind(expr, schema)
            try:
                is_identity = (isinstance(expr, ColumnRef) and schema.resolve(
                    expr, record=False) == ("col", name))
            except SqlCompileError:  # niladic keyword refs (current_date)
                is_identity = False
            if not is_identity:
                identity = False
            # event-time provenance survives pass-through column refs
            # (incl. struct-field loads, whose non-null values are the
            # raw physical column): a plain ColumnRef copies values, so
            # non-NULL output == __timestamp still holds
            if isinstance(expr, ColumnRef):
                try:
                    tag, phys = schema.resolve(expr, record=False)
                except SqlCompileError:
                    tag, phys = None, None
                if tag == "col" and phys in schema.event_time_cols:
                    new_schema.event_time_cols.add(name)

        # SELECT * over a windowed input expands window_start/window_end as
        # plain columns — keep the schema's windowness so downstream
        # ROW_NUMBER()/TopN still sees `window`
        if schema.window and "window_start" in new_schema.columns \
                and "window_end" in new_schema.columns \
                and not new_schema.window:
            new_schema.window = True
            new_schema.window_names |= schema.window_names | {"window"}

        if identity and not compiled and passthrough:
            # pure struct/window passthrough — no map needed
            return Planned(planned.stream, new_schema,
                           updating=planned.updating)

        if planned.updating:
            from ..types import UPDATE_OP_COLUMN

            passthrough.append(UPDATE_OP_COLUMN)
        fn = _wrap_record(compiled, passthrough)
        name = f"project_{self._next_id()}"
        # attach the compile-time column kinds so plan-level analyses
        # (shardcheck's sticky string-column checks) see through the
        # projection instead of going opaque at the first map
        kinds = dict(new_schema.columns)
        stream = (planned.stream.udf(fn, name=name, output_schema=kinds)
                  if needs_host
                  else planned.stream.map(fn, name=name,
                                          output_schema=kinds))
        return Planned(stream, new_schema, updating=planned.updating)

    def _infer_kind(self, e: Expr, schema: Schema) -> str:
        if isinstance(e, ColumnRef):
            try:
                kind, target = schema.resolve(e)
                if kind == "col":
                    return schema.columns.get(target, "n")
            except SqlCompileError:
                return "n"
        if isinstance(e, Cast):
            from .schema_provider import TYPE_KIND

            return TYPE_KIND.get(e.target_type, "n")
        if isinstance(e, Literal):
            return {"int": "i", "float": "f", "string": "s",
                    "bool": "b"}.get(e.type, "n")
        if isinstance(e, FunctionCall) and e.name in (
                "upper", "lower", "concat", "substr", "substring", "trim",
                "replace", "split_part", "regexp_replace", "md5", "sha256"):
            return "s"
        return "n"

    # -- aggregates --------------------------------------------------------

    def _plan_aggregate(self, sel: Select, planned: Planned) -> Planned:
        if planned.updating:
            # aggregates here don't retract consumed DELETE rows, so the
            # result would silently double-count — reject at plan time
            # (the reference converts via Debezium/updating operators)
            raise SqlPlanError(
                "aggregating over an updating stream (outer join or "
                "non-windowed aggregate) is not supported; aggregate "
                "before the join or use an inner join")
        schema = planned.schema
        items = self._expand_items(sel, schema)

        # resolve GROUP BY: ordinals, window functions, aliases
        window = None
        grouped_by_window = False  # GROUP BY the window col of a windowed input
        group_exprs: List[Tuple[str, Expr]] = []
        for ge in sel.group_by:
            e = ge
            if isinstance(e, Literal) and e.type == "int":
                name, e = items[e.value - 1]
            elif isinstance(e, ColumnRef) and e.qualifier is None:
                matched = [n for n, ie in items
                           if n == e.name.lower()]
                if matched:
                    e = dict(items)[matched[0]]
                name = _expr_name(ge, 0)
            else:
                name = _expr_name(ge, len(group_exprs))
            if isinstance(e, FunctionCall):
                w = _window_from_call(e)
                if w is not None:
                    if window is not None and w != window:
                        raise SqlPlanError("multiple windows in GROUP BY")
                    window = w
                    continue
            if isinstance(e, ColumnRef):
                try:
                    if schema.resolve(e, record=False)[0] == "window":
                        # re-aggregation keyed by the upstream window (q5's
                        # MaxBids: GROUP BY window): key on window_end and
                        # carry window_start through as a dependent key
                        grouped_by_window = True
                        group_exprs.append(("window_end",
                                            ColumnRef("window_end")))
                        group_exprs.append(("window_start",
                                            ColumnRef("window_start")))
                        continue
                except SqlCompileError:
                    pass
            group_exprs.append((name, e))

        # map group expressions to their materialized key columns so that
        # post-aggregation references (e.g. `auction.id` appearing in SELECT)
        # resolve to the key column instead of the pre-agg schema
        group_repr = {repr(e): name for name, e in group_exprs}

        def sub_group(e: Expr) -> Expr:
            if repr(e) in group_repr:
                return ColumnRef(group_repr[repr(e)])
            if isinstance(e, FunctionCall) and _is_agg_name(e.name):
                return e  # aggregate args are not group refs
            return map_children(e, sub_group)

        # collect aggregates from items (+ having), rewrite exprs
        collector = AggCollector()
        post_items: List[Tuple[str, Expr]] = []
        window_item_names: List[str] = []
        for name, expr in items:
            expr = sub_group(expr)
            if isinstance(expr, FunctionCall) and _window_from_call(expr):
                window_item_names.append(name)
                continue
            if isinstance(expr, ColumnRef):
                try:
                    if schema.resolve(expr, record=False)[0] == "window":
                        window_item_names.append(name)
                        continue
                except SqlCompileError:
                    pass
            post_items.append((name, collector.rewrite(expr)))
        having_rewritten = (collector.rewrite(sub_group(sel.having))
                            if sel.having is not None else None)

        # materialize group keys + agg inputs (pre-projection)
        pre_compiled: List[Tuple[str, Compiled]] = []
        key_cols: List[str] = []
        key_kinds: Dict[str, str] = {}
        for name, e in group_exprs:
            col = name
            pre_compiled.append((col, compile_scalar(e, schema)))
            key_cols.append(col)
            key_kinds[col] = self._infer_kind(e, schema)

        from .functions import UDAFS

        aggs: List[AggSpec] = []
        post_fixups: Dict[str, Tuple[str, str]] = {}  # out -> (sum_col, cnt_col)
        int_outputs: List[str] = []
        str_outputs: List[str] = []
        str_inputs: List[str] = []  # __ain* cols carrying object rows
        udaf_subs: Dict[str, Expr] = {}  # __agg ref -> partial-combine AST
        needs_generic = isinstance(window, SessionWindow)
        for j, fc in enumerate(collector.aggs):
            out = f"__agg{j}"
            arg = fc.args[0] if fc.args else None
            if fc.name in UDAFS:
                if window is None:
                    raise SqlPlanError(
                        f"UDAF {fc.name}() requires a window: user "
                        "aggregates are not mergeable, so they cannot run "
                        "as updating (non-windowed) aggregates")
                if fc.distinct:
                    raise SqlPlanError(
                        f"DISTINCT is not supported with UDAF {fc.name}()")
                if len(fc.args) != 1:
                    raise SqlPlanError(
                        f"UDAF {fc.name}() takes exactly one column "
                        f"argument, got {len(fc.args)}")
                sub = self._compile_udaf_partials(fc, arg, j, out, window,
                                                  schema, pre_compiled,
                                                  aggs)
                if sub is not None:
                    # decomposable numeric UDAF on a binned window:
                    # hidden mergeable partial aggregates + an arithmetic
                    # combine in the post-projection — the buffered
                    # generic path (and its per-segment host loop) never
                    # materializes
                    udaf_subs[out] = sub
                    continue
                needs_generic = True  # buffered path only (not mergeable)
                col = f"__ain{j}"
                pre_compiled.append((col, compile_scalar(arg, schema)))
                aggs.append(AggSpec(AggKind.UDAF, col, out,
                                    fn=UDAFS[fc.name]))
                if self._infer_kind(arg, schema) == "s":
                    # a string-fed UDAF ships the object column to the
                    # buffered window; declare it so shardcheck's
                    # sticky-route model (and the session-host-aggregate
                    # finding) sees the host pin instead of a false "f"
                    str_inputs.append(col)
                continue
            if fc.distinct:
                needs_generic = True
                col = f"__ain{j}"
                pre_compiled.append((col, compile_scalar(arg, schema)))
                aggs.append(AggSpec(AggKind.COUNT_DISTINCT, col, out))
                int_outputs.append(out)
                continue
            if fc.name == "count":
                if arg is None or isinstance(arg, Star):
                    aggs.append(AggSpec(AggKind.COUNT, None, out))
                    int_outputs.append(out)
                else:
                    c = compile_scalar(arg, schema)
                    col = f"__ain{j}"
                    pre_compiled.append((col, self._mask_indicator(c)))
                    aggs.append(AggSpec(AggKind.SUM, col, out))
                    int_outputs.append(out)
                continue
            c = compile_scalar(arg, schema)
            col = f"__ain{j}"
            kind = AggKind[fc.name.upper()]
            if self._infer_kind(arg, schema) == "s":
                # string aggregates: MIN/MAX are well-defined
                # (lexicographic, like the reference's DataFusion) but
                # not bin-mergeable as f64 — route to the buffered path,
                # where segment_aggregate host-reduces object columns.
                # SUM/AVG over strings are type errors at plan time.
                if kind not in (AggKind.MIN, AggKind.MAX):
                    raise SqlPlanError(
                        f"{fc.name}() is not defined for string "
                        "arguments")
                needs_generic = True
                pre_compiled.append((col, c))
                aggs.append(AggSpec(kind, col, out))
                str_outputs.append(out)
                continue
            fill = {"sum": 0.0, "avg": 0.0, "min": float("inf"),
                    "max": float("-inf")}[fc.name]
            pre_compiled.append((col, self._mask_fill(c, fill)))
            aggs.append(AggSpec(kind, col, out))

        if udaf_subs:
            # rewrite references to compiled-away UDAF outputs into their
            # partial-combine expressions (post-projection AND HAVING see
            # the mid-schema, where only the partial columns exist)
            def sub_udaf(e: Expr) -> Expr:
                if (isinstance(e, ColumnRef) and e.qualifier is None
                        and e.name in udaf_subs):
                    return udaf_subs[e.name]
                return map_children(e, sub_udaf)

            post_items = [(name, sub_udaf(e)) for name, e in post_items]
            if having_rewritten is not None:
                having_rewritten = sub_udaf(having_rewritten)

        pre_fn = _wrap_record(pre_compiled, [])
        pre_host = any(c.needs_host for _, c in pre_compiled)
        pname = f"agg_input_{self._next_id()}"
        # STRUCTURAL hash token (AST reprs after resolving column refs to
        # PHYSICAL columns, so table aliases like q5's B1/B2 don't break
        # equality): textually duplicated subqueries (q5's
        # AuctionBids/CountBids pattern) get equal tokens, which is what
        # lets the common-subplan pass merge the whole duplicated
        # aggregate chain into one operator
        pre_tok = ("aggin:"
                   + repr([(n, self._canon_token(e, schema))
                           for n, e in group_exprs])
                   + "|" + repr([self._canon_token(fc, schema)
                                 for fc in collector.aggs]))
        # column kinds of the materialized agg input: group keys keep
        # their inferred kinds, __ain* inputs are numeric except the
        # string-aggregate path — shardcheck's sticky-route checks read
        # this to prove whether the keyed shuffle edge can ride the mesh
        pre_kinds = dict(key_kinds)
        for col, _c in pre_compiled:
            pre_kinds.setdefault(
                col, "s" if col in str_inputs
                or any(a.column == col and a.output in str_outputs
                       for a in aggs) else "f")
        stream = (planned.stream.udf(pre_fn, name=pname, sql=pre_tok,
                                     output_schema=pre_kinds)
                  if pre_host
                  else planned.stream.map(pre_fn, name=pname, sql=pre_tok,
                                          output_schema=pre_kinds))

        # key + window operator
        if key_cols:
            stream = stream.key_by(*key_cols)
        else:
            stream = stream.global_key()

        if window is None:
            # GROUP BY the window of a windowed input (q5's MaxBids) is a
            # bounded per-window re-aggregation: refinements consolidate
            # in state and each window emits its FINAL row exactly once,
            # when the watermark passes window_end (flush_key) — upstream
            # panes always precede the watermark that releases them, so
            # the output is genuinely append-only even when one window's
            # rows arrive in several batches from parallel subtasks.
            stream = stream.non_window_aggregate(
                DEFAULT_UPDATING_TTL, aggs,
                flush_key="window_end" if grouped_by_window else None)
            post_updating = not grouped_by_window
        else:
            post_updating = False
            if needs_generic:
                stream = stream.window(window, aggs)
            elif isinstance(window, TumblingWindow):
                stream = stream.tumbling_aggregate(window.width_micros, aggs)
            elif isinstance(window, SlidingWindow):
                stream = stream.sliding_aggregate(window.width_micros,
                                                  window.slide_micros, aggs)
            else:
                stream = stream.window(window, aggs)

        # post-projection schema: keys + window + agg outputs
        mid_schema = Schema(window=(window is not None or grouped_by_window))
        for col in key_cols:
            mid_schema.columns[col] = key_kinds.get(col, "n")
        for j, a in enumerate(aggs):
            mid_schema.columns[a.output] = (
                "i" if a.output in int_outputs
                else "s" if a.output in str_outputs else "f")
        windowed_out = window is not None or grouped_by_window
        if windowed_out:
            mid_schema.columns["window_start"] = "t"
            mid_schema.columns["window_end"] = "t"
            mid_schema.window_names = set(window_item_names) | {"window"}

        post_compiled: List[Tuple[str, Compiled]] = []
        out_schema = Schema(window=windowed_out,
                            window_names=set(window_item_names) | (
                                {"window"} if windowed_out else set()))
        passthrough: List[str] = []
        if windowed_out:
            passthrough.extend(["window_start", "window_end"])
            out_schema.columns["window_start"] = "t"
            out_schema.columns["window_end"] = "t"
        for name, e in post_items:
            c = compile_scalar(e, mid_schema)
            cast_int = (isinstance(e, ColumnRef) and e.qualifier is None
                        and e.name in int_outputs)
            if cast_int:
                c = self._cast_int(c)
            post_compiled.append((name, c))
            out_schema.columns[name] = self._infer_kind(e, mid_schema) \
                if not cast_int else "i"
        if post_updating:
            from ..types import UPDATE_OP_COLUMN

            passthrough.append(UPDATE_OP_COLUMN)

        agg_tail = stream.tail
        agg_kind = stream.program.node(agg_tail).operator.kind
        agg_outputs = {a.output for a in aggs}

        if having_rewritten is not None:
            # HAVING filters BEFORE the post-projection, where aggregate
            # (__agg) columns still exist physically — so aggregates need
            # not be selected, and aggregates nested in selected
            # expressions work.  References to SELECT output aliases
            # substitute to their defining expressions (which are written
            # in mid-schema terms; a single pass suffices)
            name_to_expr = {name.lower(): e for name, e in post_items}

            def sub_alias(e: Expr) -> Expr:
                # standard SQL resolution: a real mid-schema column
                # (group key) of the same name wins over a SELECT alias
                if isinstance(e, ColumnRef) and e.qualifier is None \
                        and e.name.lower() in name_to_expr \
                        and e.name.lower() not in mid_schema.columns:
                    return name_to_expr[e.name.lower()]
                return map_children(e, sub_alias)

            stream = self._filter(
                Planned(stream, mid_schema, updating=post_updating),
                sub_alias(having_rewritten), "having").stream

        post_fn = _wrap_record(post_compiled, passthrough)
        post_host = any(c.needs_host for _, c in post_compiled)
        pname2 = f"agg_project_{self._next_id()}"
        post_kinds = dict(out_schema.columns)
        stream = (stream.udf(post_fn, name=pname2,
                             output_schema=post_kinds) if post_host
                  else stream.map(post_fn, name=pname2,
                                  output_schema=post_kinds))
        # TopN fusion rewrites the AGGREGATE node itself; with a HAVING
        # filter between the aggregate and the TopN, fusing would prune
        # groups BEFORE the filter — so HAVING disables the fusion
        fusable = (agg_kind in (OpKind.SLIDING_WINDOW_AGGREGATOR,
                                OpKind.TUMBLING_WINDOW_AGGREGATOR)
                   and having_rewritten is None)
        # q5 MaxBids shape: a single MAX/MIN over one output of a binned
        # window aggregate, re-grouped by that window — record enough
        # provenance for the join planner's argmax fusion
        max_of = None
        if (window is None and grouped_by_window
                # grouped by the window ONLY (its end/start key columns):
                # extra keys (GROUP BY window, k) make this a per-key
                # max, which the global per-window argmax rewrite would
                # silently change
                and all(c in ("window_end", "window_start")
                        for c in key_cols)
                and having_rewritten is None and len(aggs) == 1
                and aggs[0].kind in (AggKind.MAX, AggKind.MIN)
                and planned.agg_node is not None
                and planned.agg_map):
            fc = collector.aggs[0] if collector.aggs else None
            arg = (fc.args[0] if fc is not None and fc.args else None)
            out_name = next((name for name, e in post_items
                             if isinstance(e, ColumnRef)
                             and e.qualifier is None
                             and e.name == aggs[0].output), None)
            inner_out = None
            if isinstance(arg, ColumnRef):
                try:
                    tag, phys = planned.schema.resolve(arg, record=False)
                except SqlCompileError:
                    tag, phys = None, None
                if tag == "col":
                    inner_out = planned.agg_map.get(phys)
            if inner_out is not None and out_name is not None:
                width = getattr(
                    stream.program.node(planned.agg_node).operator.spec,
                    "width_micros", 0)
                max_of = {"raw": False,
                          "inner_agg_node": planned.agg_node,
                          "inner_out": inner_out,
                          "kind": ("max" if aggs[0].kind == AggKind.MAX
                                   else "min"),
                          "out_col": out_name,
                          "width_micros": int(width)}
        # q7 MaxPrice shape: a single numeric MAX/MIN of one input column
        # over a TUMBLING window of the RAW stream, grouped by the window
        # only (global per-window extremum) — the join planner's
        # raw-stream argmax fusion needs the input subplan, the input
        # column, and the window width.  Tumbling only: a sliding
        # window would put each row in width/slide windows, which the
        # one-window-per-row rewrite cannot represent.
        if (max_of is None and isinstance(window, TumblingWindow)
                and not key_cols and not grouped_by_window
                and having_rewritten is None and len(aggs) == 1
                and aggs[0].kind in (AggKind.MAX, AggKind.MIN)
                and not str_outputs):
            fc = collector.aggs[0] if collector.aggs else None
            arg = (fc.args[0] if fc is not None and fc.args else None)
            out_name = next((name for name, e in post_items
                             if isinstance(e, ColumnRef)
                             and e.qualifier is None
                             and e.name == aggs[0].output), None)
            input_col = None
            if isinstance(arg, ColumnRef):
                try:
                    tag, phys = schema.resolve(arg, record=False)
                except SqlCompileError:
                    tag, phys = None, None
                if tag == "col":
                    input_col = phys
            if input_col is not None and out_name is not None:
                max_of = {"raw": True,
                          "input_node": planned.stream.tail,
                          "input_col": input_col,
                          "kind": ("max" if aggs[0].kind == AggKind.MAX
                                   else "min"),
                          "out_col": out_name,
                          "width_micros": int(window.width_micros)}
        return Planned(
            stream, out_schema,
            agg_node=agg_tail if fusable else None,
            agg_map={name: e.name for name, e in post_items
                     if isinstance(e, ColumnRef) and e.qualifier is None
                     and e.name in agg_outputs} if fusable else None,
            updating=post_updating,
            max_of=max_of)

    @staticmethod
    def _canon_token(e: Expr, schema) -> str:
        """Structural token for an expression with column refs resolved to
        PHYSICAL columns (record=False probe: no projection side effects).
        Equal tokens <=> same computation over the same input schema, so
        duplicated subqueries differing only in table aliases compare
        equal for common-subplan elimination.  Unresolvable refs keep
        their qualifier — a collision-averse fallback (a missed merge is
        only a missed optimization; a wrong merge would be a bug)."""
        def walk(x: Expr) -> Expr:
            if isinstance(x, ColumnRef):
                try:
                    tag, phys = schema.resolve(x, record=False)
                except Exception:
                    return ColumnRef(x.name.lower(), x.qualifier
                                     and x.qualifier.lower())
                if tag == "col":
                    return ColumnRef(phys)
                if tag == "window":
                    return ColumnRef("__window__")
                return ColumnRef(x.name.lower(), x.qualifier
                                 and x.qualifier.lower())
            return map_children(x, walk)

        return repr(walk(e))

    def _compile_udaf_partials(self, fc: FunctionCall, arg: Expr, j: int,
                               out: str, window, schema: Schema,
                               pre_compiled: List[Tuple[str, Compiled]],
                               aggs: List[AggSpec]) -> Optional[Expr]:
        """UDAF -> bin-agg channels at PLAN time: when the registered fn
        probes as a member of the mergeable-partial algebra
        (ops/udaf.py), emit hidden SUM/MIN/MAX partial aggregates over
        (masked) input columns and return the arithmetic combine AST
        that replaces the UDAF's output reference — so the query plans
        onto the binned tumbling/sliding aggregator (KeyedBinState /
        mesh channels) instead of the buffered generic window.  Returns
        None to keep the buffered UDAF path (session windows buffer
        rows anyway, and their segment reduce compiles the same plan at
        fire time; non-decomposable fns stay host).

        All-null windows: the N/N guard (NaN when the non-null count is
        zero, 1 otherwise) reproduces the host loop's NaN for every
        combine that is not already self-guarding through a division by
        N.  ``ARROYO_UDAF_COMPILE=off`` disables the rewrite."""
        import os

        from ..ops.udaf import udaf_plan

        if os.environ.get("ARROYO_UDAF_COMPILE", "on").lower() in (
                "off", "0", "false", "no"):
            return None
        if not isinstance(window, (TumblingWindow, SlidingWindow)):
            return None
        from .functions import UDAFS

        plan = udaf_plan(UDAFS[fc.name])
        if plan is None:
            return None
        c = compile_scalar(arg, schema)
        refs: Dict[str, ColumnRef] = {}

        def channel(ch: str) -> ColumnRef:
            if ch in refs:
                return refs[ch]
            col = f"__ain{j}_{ch}"
            pout = f"{out}_{ch}"
            if ch == "nnz":
                pre_compiled.append((col, self._mask_indicator(c)))
                aggs.append(AggSpec(AggKind.SUM, col, pout))
            elif ch == "sum":
                pre_compiled.append((col, self._mask_fill(c, 0.0)))
                aggs.append(AggSpec(AggKind.SUM, col, pout))
            elif ch == "sumsq":
                sq = compile_scalar(BinaryOp("*", arg, arg), schema)
                pre_compiled.append((col, self._mask_fill(sq, 0.0)))
                aggs.append(AggSpec(AggKind.SUM, col, pout))
            elif ch == "min":
                pre_compiled.append((col, self._mask_fill(c, float("inf"))))
                aggs.append(AggSpec(AggKind.MIN, col, pout))
            else:  # max
                pre_compiled.append((col,
                                     self._mask_fill(c, float("-inf"))))
                aggs.append(AggSpec(AggKind.MAX, col, pout))
            refs[ch] = ColumnRef(pout)
            return refs[ch]

        N = channel("nnz")
        guard = BinaryOp("/", N, N)  # NaN when nnz == 0, else 1

        def centered(denom: Expr) -> Expr:
            # single-pass variance: (Σx² - (Σx)²/n) / denom, cancellation
            # residue clipped via abs (it only appears when var ≈ 0)
            s, sq = channel("sum"), channel("sumsq")
            num = BinaryOp("-", sq, BinaryOp("/", BinaryOp("*", s, s), N))
            return FunctionCall("abs", [BinaryOp("/", num, denom)])

        name = plan.name
        if name == "count":
            return BinaryOp("*", N, guard)
        if name == "sum":
            return BinaryOp("*", channel("sum"), guard)
        if name == "mean":
            return BinaryOp("/", channel("sum"), N)
        if name == "min":
            return BinaryOp("*", channel("min"), guard)
        if name == "max":
            return BinaryOp("*", channel("max"), guard)
        if name == "ptp":
            return BinaryOp("*", BinaryOp("-", channel("max"),
                                          channel("min")), guard)
        if name == "var_pop":
            return centered(N)
        if name == "var_samp":
            return centered(BinaryOp("-", N, Literal(1, "int")))
        if name == "std_pop":
            return FunctionCall("sqrt", [centered(N)])
        if name == "std_samp":
            return FunctionCall("sqrt",
                                [centered(BinaryOp("-", N,
                                                   Literal(1, "int")))])
        return None

    @staticmethod
    def _mask_indicator(c: Compiled) -> Compiled:
        def fn(env):
            import jax.numpy as jnp

            from .compiler import nan_validity

            v, m = c.fn(env)
            valid = nan_validity(v, m)  # NaN / None rows are SQL NULLs
            if valid is None:
                base = jnp.ones(np.shape(v), dtype=jnp.float32) \
                    if hasattr(v, "shape") else 1.0
                return base, None
            return jnp.asarray(valid).astype(jnp.float32), None

        return Compiled(fn, c.needs_host, c.sql, c.used_cols)

    @staticmethod
    def _mask_fill(c: Compiled, fill: float) -> Compiled:
        def fn(env):
            import jax.numpy as jnp

            v, m = c.fn(env)
            if m is None:
                return v, None
            if isinstance(v, np.ndarray) and v.dtype == object:
                return np.where(np.asarray(m), v, fill), None
            return jnp.where(m, v, fill), None

        return Compiled(fn, c.needs_host, c.sql, c.used_cols)

    @staticmethod
    def _normalize_key(c: Compiled) -> Compiled:
        def fn(env):
            import jax.numpy as jnp

            v, m = c.fn(env)
            arr = np.asarray(v) if isinstance(v, np.ndarray) else v
            if isinstance(arr, np.ndarray) and arr.dtype == object:
                return v, m
            return jnp.asarray(v).astype(jnp.float32), m

        return Compiled(fn, c.needs_host, c.sql, c.used_cols)

    @staticmethod
    def _cast_int(c: Compiled) -> Compiled:
        def fn(env):
            import jax.numpy as jnp

            v, m = c.fn(env)
            return jnp.asarray(v).astype(jnp.int64), m

        return Compiled(fn, c.needs_host, c.sql, c.used_cols)

    # -- TopN --------------------------------------------------------------

    def _apply_in_subqueries(self, planned: Planned, where: Expr,
                             prog: Program, scope: Dict[str, Planned]):
        """``x IN (SELECT c FROM ...)`` conjuncts -> streaming semi-joins
        (left rows emit exactly once on a TTL'd right-key match); returns
        (planned, remaining predicate or None)."""
        subs = []
        rest = []
        for c in _conjuncts(where):
            (subs if isinstance(c, InSubquery) else rest).append(c)
        if not subs:
            return planned, where

        if planned.updating:
            # the semi-join key projection strips __op, so retraction rows
            # from an updating left input would pass as data — reject
            # (an updating RIGHT subquery is fine: key existence is
            # monotone under create/update rows)
            raise SqlPlanError(
                "IN (SELECT ...) over an updating stream (outer join or "
                "non-windowed aggregate) is not supported")
        for e in subs:
            if e.negated:
                raise SqlPlanError(
                    "NOT IN (SELECT ...) is not supported in streaming SQL")
            sub = self.plan_select(e.query, prog, scope)
            sub_cols = [c for c in sub.schema.columns
                        if not c.startswith("__")
                        and c not in ("window_start", "window_end")]
            if len(sub_cols) != 1:
                raise SqlPlanError(
                    "IN (SELECT ...) subquery must produce exactly one "
                    f"column, got {sub_cols}")
            lkey = self._normalize_key(
                compile_scalar(e.operand, planned.schema))
            rkey = self._normalize_key(
                compile_scalar(ColumnRef(sub_cols[0]), sub.schema))
            lcols = [c for c in planned.schema.columns
                     if not c.startswith("__")]
            # NULL semantics match the join path: `NULL IN (...)` is
            # never TRUE, so null keys on either side get unique nonces
            # and can never pair
            lstream = planned.stream.udf(
                _null_key_nonce_fn(_wrap_record([("__sk", lkey)], lcols),
                                   ["__sk"]),
                name=f"semi_lkey_{self._next_id()}").key_by("__sk",
                                                            "__jknonce")
            rstream = sub.stream.udf(
                _null_key_nonce_fn(_wrap_record([("__sk", rkey)], []),
                                   ["__sk"]),
                name=f"semi_rkey_{self._next_id()}").key_by("__sk",
                                                            "__jknonce")
            out = lstream.join_with_expiration(
                rstream, DEFAULT_JOIN_TTL, DEFAULT_JOIN_TTL, JoinType.SEMI,
                name=f"semi_join_{self._next_id()}")
            out = out.map(_wrap_record([], lcols),
                          name=f"semi_drop_{self._next_id()}")
            planned = Planned(out, planned.schema)

        return planned, _conjoin(rest)

    def _rewrite_rownumber_topn(self, sel: Select, prog: Program,
                                scope: Dict[str, Planned]):
        """ROW_NUMBER() OVER (PARTITION BY window ORDER BY x DESC) with an
        outer rank filter -> per-window TopN (the reference's window-TopN
        rewrite recognizes exactly this shape, optimizations.rs:293-501).
        Returns (planned-after-topn, remaining where) or None."""
        from dataclasses import replace as _replace

        if not isinstance(sel.from_, DerivedTable):
            return None
        inner = sel.from_.query
        rn_items = [(i, it) for i, it in enumerate(inner.items)
                    if isinstance(it.expr, FunctionCall)
                    and it.expr.name == "row_number"
                    and it.expr.over is not None]
        if not rn_items:
            return None
        if len(rn_items) > 1:
            raise SqlPlanError("only one ROW_NUMBER() per query is supported")
        idx, rn_item = rn_items[0]
        rn_alias = (rn_item.alias or "row_number").lower()
        over = rn_item.expr.over

        # outer WHERE: find `rn <= k` / `rn < k` / `rn = k` among
        # top-level conjuncts.  No bound found -> rank-only mode: keep
        # every row per window partition and materialize the rank column
        # (bounded by window contents, so still streaming-safe)
        limit = None
        remaining = []
        for c in (_conjuncts(sel.where) if sel.where is not None else []):
            if (limit is None and isinstance(c, BinaryOp)
                    and c.op in ("<=", "<", "=")
                    and isinstance(c.left, ColumnRef)
                    and c.left.name.lower() == rn_alias
                    and isinstance(c.right, Literal)
                    and c.right.type == "int"):
                limit = (c.right.value - 1 if c.op == "<"
                         else c.right.value)
                if c.op == "=" and c.right.value > 1:
                    # prune to the top k, then filter the exact rank on
                    # the materialized rank column
                    remaining.append(c)
            else:
                remaining.append(c)
        if not over.order_by or len(over.order_by) != 1 \
                or not isinstance(over.order_by[0].expr, ColumnRef):
            raise SqlPlanError(
                "ROW_NUMBER() OVER requires ORDER BY a single column")
        if not over.order_by[0].desc:
            raise SqlPlanError("streaming TopN requires ORDER BY ... DESC")

        # removing the rn item shifts later items down: remap GROUP BY
        # ordinals (1-based) pointing past it, reject ones pointing AT it
        def remap_ordinal(e: Expr) -> Expr:
            if isinstance(e, Literal) and e.type == "int":
                o = e.value - 1
                if o == idx:
                    raise SqlPlanError(
                        "GROUP BY ordinal may not reference ROW_NUMBER()")
                if o > idx:
                    return Literal(e.value - 1, "int")
            return e

        inner2 = _replace(
            inner,
            items=[it for i, it in enumerate(inner.items) if i != idx],
            group_by=[remap_ordinal(g) for g in inner.group_by])
        planned = self.plan_select(inner2, prog, scope)
        if sel.from_.alias:
            schema = planned.schema.clone()
            schema.aliases.add(sel.from_.alias)
            planned = Planned(planned.stream, schema,
                              planned.agg_node, planned.agg_map)

        part_cols = self._rownumber_partition(over, planned.schema)

        shim = Select(items=[], order_by=[over.order_by[0]], limit=limit)
        planned = self._plan_top_n(shim, planned, tuple(part_cols),
                                   rank_column=rn_alias)
        return planned, _conjoin(remaining)

    def _rownumber_partition(self, over, schema: Schema) -> List[str]:
        """PARTITION BY must include the window; extra simple columns
        ride as TopN partition columns."""
        part_cols: List[str] = []
        saw_window = False
        for pe in over.partition_by:
            if self._is_window_ref(pe, schema):
                saw_window = True
            elif isinstance(pe, ColumnRef):
                part_cols.append(pe.name.lower())
            else:
                raise SqlPlanError(
                    "ROW_NUMBER() PARTITION BY supports the window and "
                    "simple columns")
        if not saw_window:
            raise SqlPlanError(
                "ROW_NUMBER() in streaming SQL must PARTITION BY the "
                "window (unbounded ranking is not supported)")
        return part_cols

    def _plan_top_n(self, sel: Select, planned: Planned,
                    partition_cols: Tuple[str, ...] = (),
                    rank_column: Optional[str] = None) -> Planned:
        """ORDER BY ... LIMIT n over a windowed stream -> per-window TopN
        (the reference's window-TopN rewrite, optimizations.rs:293-501).

        When the input is directly a binned window aggregate, the TopN
        fuses INTO the aggregate (SlidingAggregatingTopN,
        sliding_top_n_aggregating_window.rs): each pane emission keeps
        only the top rows instead of materializing every (key, pane)
        aggregate downstream.  A parallel aggregate keeps a parallelism-1
        global TopN stage after the fused local one (two-phase TopN).
        """
        if planned.updating:
            # the TopN buffer would rank __op DELETE retraction rows as
            # ordinary data rows — reject rather than mis-rank
            raise SqlPlanError(
                "ORDER BY ... LIMIT over an updating stream (non-windowed "
                "aggregate or outer join) is not supported; window the "
                "aggregate first")
        if not planned.schema.window:
            raise SqlPlanError(
                "ORDER BY/LIMIT requires a windowed input in streaming SQL")
        if len(sel.order_by) > 1:
            raise SqlPlanError(
                "streaming TopN supports a single ORDER BY column")
        item = sel.order_by[0]
        if not isinstance(item.expr, ColumnRef):
            raise SqlPlanError("ORDER BY expression must be a column")
        col = item.expr.name.lower()
        if not item.desc:
            raise SqlPlanError("streaming TopN requires ORDER BY ... DESC")

        stream = planned.stream
        node = None
        sort_col = None
        tail_node = stream.program.node(stream.tail)
        tail_spec = tail_node.operator.spec
        if (tail_node.operator.kind in (OpKind.SLIDING_WINDOW_AGGREGATOR,
                                        OpKind.TUMBLING_WINDOW_AGGREGATOR)
                and col in {a.output for a in tail_spec.aggs}):
            node, sort_col = tail_node, col  # direct Stream-API shape
        elif (planned.agg_node is not None
              and planned.agg_map is not None and col in planned.agg_map):
            # SQL shape: [bin agg -> projection]; fuse through the
            # projection using the internal agg output name
            node = stream.program.node(planned.agg_node)
            sort_col = planned.agg_map[col]
        if node is not None and sel.limit is not None:
            # rank-only mode (limit None) cannot prune locally — the
            # fusion only applies when a bound exists
            spec = node.operator.spec
            slide = getattr(spec, "slide_micros", spec.width_micros)
            node.operator.kind = OpKind.SLIDING_AGGREGATING_TOP_N
            node.operator.spec = SlidingAggregatingTopNSpec(
                width_micros=spec.width_micros, slide_micros=slide,
                aggs=spec.aggs, partition_cols=partition_cols,
                sort_column=sort_col,
                max_elements=sel.limit, projection=spec.projection)
            # local (per key range) top-N pruning done; the global merge
            # stage below is always kept — the aggregate's parallelism can
            # change after planning (rescale), so correctness must not
            # depend on it being 1 at plan time

        # global per-window-instance TopN: a single merging subtask
        # (pinned across rescales) partitioned by window_end inside TopN;
        # materializes the ROW_NUMBER() column when the query reads it
        stream = stream._chain(LogicalOperator(
            OpKind.TUMBLING_TOP_N, f"topn_{self._next_id()}",
            spec=TopNSpec(width_micros=1, max_elements=sel.limit,
                          sort_column=col, partition_cols=partition_cols,
                          rank_column=rank_column)),
            parallelism=1)
        stream.program.node(stream.tail).max_parallelism = 1
        schema = planned.schema
        if rank_column is not None:
            schema = schema.clone()
            schema.columns[rank_column] = "i"
        return Planned(stream, schema)

    # -- joins -------------------------------------------------------------

    def _plan_join(self, j: Join, prog: Program,
                   scope: Dict[str, Planned],
                   where: Optional[Expr] = None) -> Planned:
        left = self._plan_table_ref(j.left, prog, scope, where=where)
        right = self._plan_table_ref(j.right, prog, scope)

        if j.on is None:
            raise SqlPlanError("JOIN requires an ON clause")
        pairs = self._split_on(j.on, left.schema, right.schema)

        window_join = False
        lkeys: List[Expr] = []
        rkeys: List[Expr] = []
        for le, re_ in pairs:
            lw = self._is_window_ref(le, left.schema)
            rw = self._is_window_ref(re_, right.schema)
            if lw and rw:
                window_join = True
                lkeys.append(ColumnRef("window_end"))
                rkeys.append(ColumnRef("window_end"))
            else:
                lkeys.append(le)
                rkeys.append(re_)

        kind = JoinType[j.kind.name]
        if left.updating or right.updating:
            # the join buffers treat every row as data — a __op DELETE
            # retraction from an updating input would be joined as if it
            # were a live row, silently double-counting; reject at plan
            # time (semi-joins via IN (...) are fine: group existence is
            # monotone under create/update rows)
            raise SqlPlanError(
                "joining an updating stream (non-windowed aggregate or "
                "outer join) is not supported; window the aggregate "
                "or restructure the query")
        lcols = [c for c in left.schema.columns if not c.startswith("__")]
        rcols = [c for c in right.schema.columns if not c.startswith("__")]
        out = None
        if window_join and kind == JoinType.INNER:
            out = self._try_argmax_fusion(left, right, pairs, rcols)
        if out is None and not window_join and kind == JoinType.INNER:
            out = self._try_raw_argmax_fusion(left, right, pairs, rcols,
                                              where)
        mw_sides: Optional[Dict[str, Any]] = None  # cascade metadata
        if out is None and kind == JoinType.INNER:
            mw = self._try_multiway_extend(left, right, pairs, rcols,
                                           window_join)
            if mw is not None:
                out, mw_sides = mw
        if out is None:
            # numeric join keys normalize to float32 so that e.g. an
            # int64 COUNT equi-joins against a float aggregate (both
            # sides hash identically)
            lpre = [(f"__jk{i}",
                     self._normalize_key(compile_scalar(e, left.schema)))
                    for i, e in enumerate(lkeys)]
            rpre = [(f"__jk{i}",
                     self._normalize_key(compile_scalar(e, right.schema)))
                    for i, e in enumerate(rkeys)]
            # SQL NULL join keys never match — not even each other.  The
            # key maps append a nonce column that is 0 for valid rows and
            # UNIQUE per null-keyed row, so null rows hash uniquely:
            # they pair with nothing, yet still flow through the buffers
            # and emit null-padded on outer kinds — one mechanism for
            # every join type.  (The nullable-key maps run as host UDFs:
            # the nonce counter is Python state a jit trace could not
            # carry.  All-window joins can't have NULL keys, so they stay
            # on the jitted map path with a constant-zero nonce.)
            jks = [f"__jk{i}" for i in range(len(lkeys))]
            all_window = all(
                self._is_window_ref(le, left.schema)
                and self._is_window_ref(re_, right.schema)
                for le, re_ in pairs)
            if all_window:
                lstream = left.stream.map(
                    _zero_nonce_fn(_wrap_record(lpre, lcols)),
                    name=f"join_lkey_{self._next_id()}")
                rstream = right.stream.map(
                    _zero_nonce_fn(_wrap_record(rpre, rcols)),
                    name=f"join_rkey_{self._next_id()}")
            else:
                lstream = left.stream.udf(
                    _null_key_nonce_fn(_wrap_record(lpre, lcols), jks),
                    name=f"join_lkey_{self._next_id()}")
                rstream = right.stream.udf(
                    _null_key_nonce_fn(_wrap_record(rpre, rcols), jks),
                    name=f"join_rkey_{self._next_id()}")
            jcols = jks + ["__jknonce"]
            lstream = lstream.key_by(*jcols)
            rstream = rstream.key_by(*jcols)

            # visible side schemas (name, kind) so outer joins can
            # null-pad a side that has produced no rows yet
            lspec = tuple((c, left.schema.columns[c]) for c in lcols)
            rspec = tuple((c, right.schema.columns[c]) for c in rcols)
            if window_join:
                out = lstream.window_join(
                    rstream, InstantWindow(), kind, lspec, rspec,
                    name=f"window_join_{self._next_id()}")
            else:
                out = lstream.join_with_expiration(
                    rstream, DEFAULT_JOIN_TTL, DEFAULT_JOIN_TTL, kind,
                    lspec, rspec, name=f"join_{self._next_id()}")
            if kind == JoinType.INNER and self._multiway_enabled():
                mw_sides = {"sides": [(lstream, lspec), (rstream, rspec)]}

        schema = Schema(aliases=left.schema.aliases | right.schema.aliases)
        for c in lcols:
            schema.columns[c] = left.schema.columns[c]
        rename: Dict[str, str] = {}
        for c in rcols:
            name = c if c not in schema.columns else f"r_{c}"
            schema.columns[name] = right.schema.columns[c]
            rename[c] = name
        # qualified refs bind to their own side even when a collision
        # renamed the right column (r.id -> r_id).  Child bindings are
        # inherited FIRST (remapped through this join's renames) so that
        # in nested joins an inner alias keeps pointing at its own
        # column; the blanket per-alias mapping below only fills gaps.
        for key, phys in left.schema.qualified.items():
            schema.qualified[key] = phys  # left names survive unchanged
        for key, phys in right.schema.qualified.items():
            schema.qualified[key] = rename.get(phys, phys)
        for a in left.schema.aliases:
            for c in lcols:
                schema.qualified.setdefault((a.lower(), c.lower()), c)
        for a in right.schema.aliases:
            for c in rcols:
                schema.qualified.setdefault((a.lower(), c.lower()),
                                            rename[c])
        schema.structs = {**right.schema.structs, **left.schema.structs}
        # pushdown: columns resolved against the JOINED schema may come
        # from either side's source — record into both sides' used sets
        # (over-inclusive on the side that doesn't own the column, which a
        # connector treats as harmless)
        tees = [s.source_used for s in (left.schema, right.schema)
                if s.source_used is not None]
        if tees:
            schema.source_used = _TeeSet(tees)
        if left.schema.window and right.schema.window:
            schema.window = True
            schema.window_names = (left.schema.window_names
                                   | right.schema.window_names | {"window"})
        # TTL'd outer joins emit __op retraction rows (windowed outer joins
        # are append-only: each window fires once, so no retractions)
        outer = kind in (JoinType.LEFT, JoinType.RIGHT, JoinType.FULL)
        planned = Planned(out, schema, updating=(outer and not window_join))
        if mw_sides is not None:
            # record cascade metadata: per key slot, the joined-schema
            # column names whose value equals that key (either side's
            # source column when it is a plain reference) — a later
            # `... JOIN C ON <one of these> = C.x` extends in place
            base = mw_sides.get("base_equiv")
            equiv: List[Any] = ([set(s) if s != "__window__" else s
                                 for s in base] if base is not None
                                else [set() for _ in pairs])
            slot_of = mw_sides.get("slot_of") or {
                j: j for j in range(len(pairs))}
            for j, (le, re_) in enumerate(pairs):
                i = slot_of[j]
                if (self._is_window_ref(le, left.schema)
                        and self._is_window_ref(re_, right.schema)):
                    equiv[i] = "__window__"
                    continue
                if equiv[i] == "__window__":
                    continue
                if isinstance(le, ColumnRef):
                    try:
                        tag, phys = left.schema.resolve(le, record=False)
                        if tag == "col":
                            equiv[i].add(phys)
                    except SqlCompileError:
                        pass
                if isinstance(re_, ColumnRef):
                    try:
                        tag, phys = right.schema.resolve(re_, record=False)
                        if tag == "col":
                            equiv[i].add(rename.get(phys, phys))
                    except SqlCompileError:
                        pass
            planned.multi_join = {
                "sides": mw_sides["sides"],
                "window": window_join,
                "equiv": equiv,
                "n_keys": len(equiv),
            }
        return planned

    @staticmethod
    def _multiway_enabled() -> bool:
        import os

        return os.environ.get("ARROYO_MULTIWAY", "1") not in (
            "0", "off", "false")

    def _try_multiway_extend(self, left: Planned, right: Planned,
                             pairs: List[Tuple[Expr, Expr]],
                             rcols: List[str], window_join: bool):
        """Rewrite ``(A JOIN B ON k) JOIN C ON k`` — a cascade of INNER
        equi-joins sharing one key — into ONE multi-way join operator
        that probes every side per fire ("Streaming SQL Multi-Way Join
        Method for Long State Streams", PAPERS.md).  The nested plan
        materializes |A⋈B| intermediate rows, re-keys and re-buffers
        them, and probes C against that; the N-ary operator expands the
        per-key cross product across all sides directly, so the pairwise
        intermediate never exists.

        Extends only a directly nested join whose Planned carries
        ``multi_join`` metadata, when every ON pair's left expr is a
        plain reference to a recorded key-equivalent column (same key,
        same windowing).  Every bail returns None — a missed
        optimization, never a wrong plan."""
        if not self._multiway_enabled():
            return None
        mj = left.multi_join
        if mj is None or mj["window"] != window_join or right.updating:
            return None
        if len(pairs) != mj["n_keys"] or len(mj["sides"]) >= 8:
            return None
        equiv = mj["equiv"]
        slot_of: Dict[int, int] = {}
        used: set = set()
        rexpr_by_slot: Dict[int, Expr] = {}
        for j, (le, re_) in enumerate(pairs):
            win = (self._is_window_ref(le, left.schema)
                   and self._is_window_ref(re_, right.schema))
            target = None
            if win:
                for i, eq in enumerate(equiv):
                    if eq == "__window__" and i not in used:
                        target = i
                        break
            elif isinstance(le, ColumnRef):
                try:
                    tag, phys = left.schema.resolve(le, record=False)
                except SqlCompileError:
                    return None
                if tag != "col":
                    return None
                for i, eq in enumerate(equiv):
                    if eq != "__window__" and phys in eq \
                            and i not in used:
                        target = i
                        break
            if target is None:
                return None
            used.add(target)
            slot_of[j] = target
            rexpr_by_slot[target] = (ColumnRef("window_end") if win
                                     else re_)
        if len(used) != len(equiv):
            return None
        # the new side gets its own key map (slot order) + keying, same
        # as the pairwise path would have built
        n_keys = len(equiv)
        try:
            rpre = [(f"__jk{i}", self._normalize_key(
                compile_scalar(rexpr_by_slot[i], right.schema)))
                for i in range(n_keys)]
        except SqlCompileError:
            return None
        jks = [f"__jk{i}" for i in range(n_keys)]
        if all(eq == "__window__" for eq in equiv):
            rstream = right.stream.map(
                _zero_nonce_fn(_wrap_record(rpre, rcols)),
                name=f"join_rkey_{self._next_id()}")
        else:
            rstream = right.stream.udf(
                _null_key_nonce_fn(_wrap_record(rpre, rcols), jks),
                name=f"join_rkey_{self._next_id()}")
        rstream = rstream.key_by(*(jks + ["__jknonce"]))
        rspec = tuple((c, right.schema.columns[c]) for c in rcols)
        sides = list(mj["sides"]) + [(rstream, rspec)]
        streams = [s for s, _spec in sides]
        specs = tuple(spec for _s, spec in sides)
        out = streams[0].multi_way_join(
            streams[1:],
            typ=InstantWindow() if window_join else None,
            ttl_micros=DEFAULT_JOIN_TTL, side_cols=specs,
            name=f"multi_join_{self._next_id()}")
        return out, {"sides": sides, "slot_of": slot_of,
                     "base_equiv": equiv}

    def _try_argmax_fusion(self, left: Planned, right: Planned,
                           pairs: List[Tuple[Expr, Expr]],
                           rcols: List[str]):
        """Rewrite ``A JOIN (SELECT max(x), window FROM A GROUP BY
        window) ON A.x = mx AND A.window = window`` into a single
        per-window argmax filter over A (nexmark q5's hot-items shape).

        The self-join materializes every (key, window) aggregate row,
        re-aggregates the max, and hash-joins the two — all to keep the
        rows achieving the max.  The fused plan keys A's output by
        window and filters in one buffered pass; at upstream
        parallelism > 1 this stage is still globally correct because
        all rows of one window shuffle to one subtask.  DataFusion-based
        planners (the reference) run the full self-join.

        Returns the fused output Stream, or None when the shape doesn't
        provably match (every bail is a missed optimization, never a
        wrong plan)."""
        import os

        if os.environ.get("ARROYO_ARGMAX", "1") in ("0", "off", "false"):
            return None
        mo = right.max_of
        if (mo is None or mo.get("raw") or left.agg_node is None
                or not left.agg_map or len(pairs) != 2):
            return None
        val_pairs = [(le, re_) for le, re_ in pairs
                     if not (self._is_window_ref(le, left.schema)
                             and self._is_window_ref(re_, right.schema))]
        if len(val_pairs) != 1:
            return None
        le, re_ = val_pairs[0]
        if not (isinstance(le, ColumnRef) and isinstance(re_, ColumnRef)):
            return None
        try:
            lt, lcol = left.schema.resolve(le, record=False)
            rt, rcol = right.schema.resolve(re_, record=False)
        except SqlCompileError:
            return None
        if lt != "col" or rt != "col":
            return None
        # the joined value must be exactly the aggregate output the max
        # side maximizes, over a provably identical aggregate subplan
        if (left.agg_map.get(lcol) != mo["inner_out"]
                or rcol != mo["out_col"]):
            return None
        prog = left.stream.program
        if not prog.subplan_equal(left.agg_node, mo["inner_agg_node"]):
            return None
        # every pruned-side column must be synthesizable from a left row
        # (out names mirror the join's collision renames, so downstream
        # column resolution is identical either way)
        synth = []
        for c in rcols:
            out_name = c if c not in left.schema.columns else f"r_{c}"
            if c == mo["out_col"]:
                synth.append((out_name, lcol))
            elif (c in ("window_start", "window_end")
                  and c in left.schema.columns):
                synth.append((out_name, c))
            else:
                return None
        return (left.stream.key_by("window_end")
                .window_argmax(lcol, mo["kind"], tuple(synth),
                               mo["width_micros"] or 1,
                               name=f"window_argmax_{self._next_id()}",
                               agg_out=mo["inner_out"]))

    _FLIP = {">=": "<=", "<=": ">=", ">": "<", "<": ">"}

    def _try_raw_argmax_fusion(self, left: Planned, right: Planned,
                               pairs: List[Tuple[Expr, Expr]],
                               rcols: List[str],
                               where: Optional[Expr]):
        """Rewrite ``A JOIN (SELECT max(x), TUMBLE(w) AS window FROM A
        GROUP BY 2) M ON A.x = M.mx WHERE A.et >= M.window_start AND
        A.et < M.window_end`` into a per-window argmax over the RAW
        stream A (nexmark q7's highest-bid shape).

        Soundness chain: (1) the max side aggregates the provably same
        subplan A over tumbling windows of A's __timestamp; (2) ``et``
        carries event-time provenance (Schema.event_time_cols: non-NULL
        values equal __timestamp), so both WHERE conjuncts being true
        pins the joined M row's window to the A row's OWN window
        ([start, end) membership — a non-strict upper bound would admit
        the boundary of the previous window and must bail); (3) the
        WHERE stays in the plan as a post-filter over the fused output,
        which re-drops NULL-``et`` rows exactly as the join would have.
        The fused plan emits each window's max-achieving rows (ties
        included) with the pruned side's columns synthesized, replacing
        a TTL'd stream-stream join whose state held every raw row.
        DataFusion-based planners (the reference) run the full join
        (optimizations.rs has no analogous rewrite).

        Every bail returns None — a missed optimization, never a wrong
        plan."""
        import os

        if os.environ.get("ARROYO_ARGMAX", "1") in ("0", "off", "false"):
            return None
        mo = right.max_of
        if mo is None or not mo.get("raw") or where is None:
            return None
        if len(pairs) != 1 or left.updating:
            return None
        le, re_ = pairs[0]
        if not (isinstance(le, ColumnRef) and isinstance(re_, ColumnRef)):
            return None
        try:
            lt, lcol = left.schema.resolve(le, record=False)
            rt, rcol = right.schema.resolve(re_, record=False)
        except SqlCompileError:
            return None
        if lt != "col" or rt != "col":
            return None
        # the joined value must be the raw column the max side maximizes,
        # over a provably identical input subplan (CTE references share
        # nodes, so the common case short-circuits on identity)
        if rcol != mo["out_col"] or lcol != mo["input_col"]:
            return None
        prog = left.stream.program
        if not prog.subplan_equal(left.stream.tail, mo["input_node"]):
            return None
        # the rewrite introduces canonical window columns on A's stream
        if ("window_start" in left.schema.columns
                or "window_end" in left.schema.columns
                or left.schema.window):
            return None
        # string extrema would need object-dtype handling in the
        # running-extremum pre-filter — not worth the path
        if left.schema.columns.get(lcol) == "s":
            return None
        width = int(mo["width_micros"])
        if width <= 0:
            return None
        # WHERE must contain both window-membership bounds
        lower_ok = upper_ok = False
        for c in _conjuncts(where):
            if not isinstance(c, BinaryOp) \
                    or c.op not in (">=", ">", "<", "<="):
                continue
            for a, b, op in ((c.left, c.right, c.op),
                             (c.right, c.left, self._FLIP[c.op])):
                et = self._event_time_side(a, left, right)
                bound = self._window_bound_side(b, left, right)
                if et is None or bound is None:
                    continue
                if bound == "window_start" and op in (">=", ">"):
                    lower_ok = True
                elif bound == "window_end" and op == "<":
                    upper_ok = True
        if not (lower_ok and upper_ok):
            return None
        # every pruned-side column must be synthesizable from a fused row
        synth = []
        for c in rcols:
            out_name = c if c not in left.schema.columns else f"r_{c}"
            if c == mo["out_col"]:
                synth.append((out_name, lcol))
            elif c in ("window_start", "window_end"):
                # produced under these exact names by _win_assign below;
                # out_name == c always (the collision case bailed above)
                pass
            else:
                return None

        def _win_assign(cols, _w=width):
            ts = np.asarray(cols["__timestamp"], dtype=np.int64)
            we = (ts // _w + 1) * _w
            out = dict(cols)
            out["window_start"] = we - _w
            out["window_end"] = we
            # aggregate-row timestamp convention (operator _emit): the
            # argmax stage buffers by ts == end - 1 and its timers fire
            # when the watermark passes the window end
            out["__timestamp"] = we - 1
            return out

        stream = left.stream.udf(_win_assign,
                                 name=f"win_assign_{self._next_id()}")
        return (stream.key_by("window_end")
                .window_argmax(lcol, mo["kind"], tuple(synth), width,
                               name=f"window_argmax_{self._next_id()}",
                               raw=True,
                               late_ttl_micros=DEFAULT_JOIN_TTL))

    def _event_time_side(self, e: Expr, left: Planned,
                         right: Planned) -> Optional[str]:
        """Resolve ``e`` as a LEFT column with event-time provenance, or
        None.  A ref that also resolves on the right is ambiguous — the
        joined schema might bind it elsewhere — and bails."""
        if not isinstance(e, ColumnRef):
            return None
        try:
            tag, phys = left.schema.resolve(e, record=False)
        except SqlCompileError:
            return None
        if tag != "col" or phys not in left.schema.event_time_cols:
            return None
        try:
            right.schema.resolve(e, record=False)
            return None
        except SqlCompileError:
            return phys

    def _window_bound_side(self, e: Expr, left: Planned,
                           right: Planned) -> Optional[str]:
        """Resolve ``e`` as the right (max) side's window_start or
        window_end, or None; ambiguous refs bail as above."""
        if not isinstance(e, ColumnRef) or not right.schema.window:
            return None
        try:
            tag, phys = right.schema.resolve(e, record=False)
        except SqlCompileError:
            return None
        if tag != "col" or phys not in ("window_start", "window_end"):
            return None
        try:
            left.schema.resolve(e, record=False)
            return None
        except SqlCompileError:
            return phys

    def _split_on(self, on: Expr, ls: Schema, rs: Schema
                  ) -> List[Tuple[Expr, Expr]]:
        conjuncts: List[Expr] = []

        def flatten(e: Expr):
            if isinstance(e, BinaryOp) and e.op == "and":
                flatten(e.left)
                flatten(e.right)
            else:
                conjuncts.append(e)

        flatten(on)
        pairs: List[Tuple[Expr, Expr]] = []
        for c in conjuncts:
            if not (isinstance(c, BinaryOp) and c.op == "="):
                raise SqlPlanError(f"JOIN ON supports equality only, got {c!r}")
            a, b = c.left, c.right
            if self._belongs(a, ls) and self._belongs(b, rs):
                pairs.append((a, b))
            elif self._belongs(b, ls) and self._belongs(a, rs):
                pairs.append((b, a))
            else:
                raise SqlPlanError(
                    f"cannot attribute join condition {c!r} to sides")
        return pairs

    def _belongs(self, e: Expr, schema: Schema) -> bool:
        try:
            compile_scalar(e, schema)
            return True
        except SqlCompileError:
            if self._is_window_ref(e, schema):
                return True
            return False

    @staticmethod
    def _is_window_ref(e: Expr, schema: Schema) -> bool:
        if isinstance(e, ColumnRef):
            try:
                return schema.resolve(e, record=False)[0] == "window"
            except SqlCompileError:
                return False
        return False


def plan_sql(sql: str, provider: Optional[SchemaProvider] = None,
             parallelism: int = 1) -> Program:
    return Planner(provider).plan(sql, parallelism)
