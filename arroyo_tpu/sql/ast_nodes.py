"""SQL AST — the analog of the expression/statement trees the reference gets
from sqlparser + DataFusion (arroyo-sql/src/expressions.rs operator taxonomy)."""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any, List, Optional, Tuple


# -- expressions -------------------------------------------------------------


@dataclass
class Expr:
    pass


@dataclass
class Literal(Expr):
    value: Any  # int | float | str | bool | None
    type: str = ""  # 'int'|'float'|'string'|'bool'|'null'


@dataclass
class IntervalLit(Expr):
    micros: int


@dataclass
class ColumnRef(Expr):
    name: str
    qualifier: Optional[str] = None  # table alias or struct column

    @property
    def display(self) -> str:
        return f"{self.qualifier}.{self.name}" if self.qualifier else self.name


@dataclass
class Star(Expr):
    qualifier: Optional[str] = None


@dataclass
class BinaryOp(Expr):
    op: str  # + - * / % = <> < <= > >= and or || like
    left: Expr
    right: Expr


@dataclass
class UnaryOp(Expr):
    op: str  # - not
    operand: Expr


@dataclass
class IsNull(Expr):
    operand: Expr
    negated: bool = False


@dataclass
class InList(Expr):
    operand: Expr
    items: List[Expr]
    negated: bool = False


@dataclass
class InSubquery(Expr):
    """``x IN (SELECT c FROM ...)`` — planned as a streaming semi-join."""

    operand: Expr
    query: "Select"
    negated: bool = False


@dataclass
class Between(Expr):
    operand: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass
class Case(Expr):
    operand: Optional[Expr]
    whens: List[Tuple[Expr, Expr]]
    else_: Optional[Expr]


@dataclass
class Cast(Expr):
    operand: Expr
    target_type: str  # normalized lowercase type name


@dataclass
class OverClause:
    """OVER (PARTITION BY ... ORDER BY ...) for SQL window functions
    (ROW_NUMBER — the streaming planner rewrites it into TopN)."""

    partition_by: List[Expr]
    order_by: List["OrderItem"]


@dataclass
class FunctionCall(Expr):
    name: str  # lowercase
    args: List[Expr]
    distinct: bool = False
    over: Optional[OverClause] = None

    @property
    def is_window_fn(self) -> bool:
        return self.name in ("hop", "tumble", "session")


AGG_FUNCTIONS = {"count", "sum", "min", "max", "avg"}


# -- statements --------------------------------------------------------------


def map_children(e: "Expr", fn) -> "Expr":
    """Rebuild ``e`` with ``fn`` applied to each direct child expression —
    THE single structural traversal every expression rewriter must use,
    so node-type coverage is a one-place fix (three hand-rolled switch
    ladders had already drifted on Case/InList/Between)."""
    if isinstance(e, BinaryOp):
        return BinaryOp(e.op, fn(e.left), fn(e.right))
    if isinstance(e, UnaryOp):
        return UnaryOp(e.op, fn(e.operand))
    if isinstance(e, IsNull):
        return IsNull(fn(e.operand), e.negated)
    if isinstance(e, InList):
        return InList(fn(e.operand), [fn(x) for x in e.items], e.negated)
    if isinstance(e, Between):
        return Between(fn(e.operand), fn(e.low), fn(e.high), e.negated)
    if isinstance(e, Case):
        return Case(fn(e.operand) if e.operand is not None else None,
                    [(fn(c), fn(v)) for c, v in e.whens],
                    fn(e.else_) if e.else_ is not None else None)
    if isinstance(e, Cast):
        return Cast(fn(e.operand), e.target_type)
    if isinstance(e, InSubquery):
        # the subquery plans separately; only the operand is a child expr
        return InSubquery(fn(e.operand), e.query, e.negated)
    if isinstance(e, FunctionCall):
        return FunctionCall(e.name, [fn(a) for a in e.args], e.distinct,
                            e.over)
    return e


@dataclass
class SelectItem:
    expr: Expr
    alias: Optional[str] = None


class JoinKind(Enum):
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    FULL = "full"


@dataclass
class TableRef:
    pass


@dataclass
class NamedTable(TableRef):
    name: str
    alias: Optional[str] = None


@dataclass
class DerivedTable(TableRef):
    query: "Select"
    alias: Optional[str] = None


@dataclass
class Join(TableRef):
    left: TableRef
    right: TableRef
    kind: JoinKind
    on: Optional[Expr]


@dataclass
class OrderItem:
    expr: Expr
    desc: bool = False


@dataclass
class Select:
    items: List[SelectItem]
    from_: Optional[TableRef] = None
    where: Optional[Expr] = None
    group_by: List[Expr] = field(default_factory=list)
    having: Optional[Expr] = None
    order_by: List[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    distinct: bool = False
    ctes: List[Tuple[str, "Select"]] = field(default_factory=list)
    # UNION ALL chain (the reference bails on unions, pipeline.rs:393 —
    # supporting them is deliberate over-parity)
    union_all: Optional["Select"] = None


@dataclass
class Explain:
    """EXPLAIN <select> — emits the planned operator DAG as rows (the
    reference bails on EXPLAIN, pipeline.rs:432)."""

    query: "Select"


@dataclass
class ColumnDef:
    name: str
    type: str
    not_null: bool = False
    generated_as: Optional[Expr] = None


@dataclass
class CreateTable:
    name: str
    columns: List[ColumnDef]
    with_options: dict = field(default_factory=dict)


@dataclass
class Insert:
    table: str
    query: Select


Statement = Any  # CreateTable | Insert | Select
