"""SQL expression -> columnar closure compiler.

The analog of the reference's expression compiler (arroyo-sql/src/
expressions.rs + code_gen.rs, 4.3k LoC of Rust-source emission): instead of
emitting Rust strings for rustc, each AST node compiles to a Python closure
over the column environment that jax.jit traces into one fused XLA program.

Values flow as ``(array, mask)`` pairs — mask is the SQL validity (None =
all valid), which keeps three-valued logic cheap: masks are just bool arrays
AND-ed along the way.  Struct columns (nexmark's person/bid/auction) resolve
to flattened physical columns plus a presence mask from the schema.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from .ast_nodes import (
    Between,
    BinaryOp,
    Case,
    Cast,
    ColumnRef,
    Expr,
    FunctionCall,
    InList,
    InSubquery,
    IntervalLit,
    IsNull,
    Literal,
    Star,
    UnaryOp,
)
from .functions import DEVICE_FUNCTIONS, HOST_FUNCTIONS

MV = Tuple[Any, Optional[Any]]


class SqlCompileError(ValueError):
    pass


@dataclass
class StructDef:
    """A struct-typed column flattened into physical columns, with a presence
    test (nexmark Event{person,bid,auction}: presence = event_type == k)."""

    name: str
    fields: Dict[str, str]  # field name -> physical column
    presence_col: Optional[str] = None
    presence_val: Optional[int] = None

    def presence_mask(self, env):
        if self.presence_col is None:
            return None
        return np.asarray(env[self.presence_col]) == self.presence_val \
            if isinstance(env.get(self.presence_col), np.ndarray) \
            else env[self.presence_col] == self.presence_val


@dataclass
class Schema:
    """Logical schema of one dataflow edge for SQL resolution."""

    columns: Dict[str, str] = field(default_factory=dict)  # name -> kind i/f/s/b/t
    structs: Dict[str, StructDef] = field(default_factory=dict)
    aliases: Set[str] = field(default_factory=set)
    window: bool = False  # window_start/window_end present
    window_names: Set[str] = field(default_factory=set)  # aliases of the window
    event_time_col: str = "__timestamp"
    # projection pushdown: source schemas carry a SHARED mutable set that
    # resolve() records physical-column accesses into (clones alias it, so
    # every reference to the table accumulates here); the planner hands the
    # final set to the source connector so it can skip generating/decoding
    # untouched columns — the DataFusion-planner pushdown analog
    source_used: Optional[Set[str]] = None
    # qualified-name overrides from joins: (alias_lower, col_lower) ->
    # physical column, so `r.id` resolves to the collision-renamed `r_id`
    # instead of falling back to the left side's `id`
    qualified: Dict[Tuple[str, str], str] = field(default_factory=dict)
    # structs whose presence a preceding `WHERE s IS NOT NULL` filter
    # guarantees: field loads skip the presence mask (and projections skip
    # NULL materialization — the hot-path case for nexmark struct fields)
    presence_guaranteed: Set[str] = field(default_factory=set)
    # event-time provenance: physical columns whose every NON-NULL value
    # provably equals the stream's __timestamp (declared by the source —
    # event_time_field, or connector-known fields like nexmark's
    # bid.datetime — and propagated through pass-through projections and
    # filters; joins and aggregates drop it, since their output rows get
    # fresh timestamps).  The optimizer's raw-stream argmax fusion uses
    # this to prove a post-join window-range WHERE pins each row to its
    # own event-time window (planner._try_raw_argmax_fusion).
    event_time_cols: Set[str] = field(default_factory=set)

    def clone(self) -> "Schema":
        return Schema(dict(self.columns), dict(self.structs),
                      set(self.aliases), self.window, set(self.window_names),
                      self.event_time_col, self.source_used,
                      dict(self.qualified), set(self.presence_guaranteed),
                      set(self.event_time_cols))

    def is_string(self, col: str) -> bool:
        return self.columns.get(col) == "s"

    def _use(self, col: str, record: bool = True) -> Tuple[str, str]:
        if record and self.source_used is not None:
            self.source_used.add(col)
        return ("col", col)

    def _use_struct(self, sd: "StructDef", presence_only: bool = False,
                    record: bool = True) -> Tuple[str, "StructDef"]:
        if record and self.source_used is not None:
            # a bare struct reference (SELECT bid, struct passthrough)
            # keeps the WHOLE struct live: presence column and every field
            # column (the projection operator passes fields through,
            # planner._plan_projection).  ``presence_only`` is for
            # `struct IS [NOT] NULL`, which reads just the presence column.
            if sd.presence_col is not None:
                self.source_used.add(sd.presence_col)
            if not presence_only:
                for phys in sd.fields.values():
                    self.source_used.add(phys)
        return ("struct", sd)

    def resolve(self, ref: ColumnRef, presence_only: bool = False,
                record: bool = True) -> Tuple[str, Any]:
        """Resolve to ('col', phys) | ('struct', StructDef) | ('window', part).

        ``record=False`` makes this a pure PROBE (planner shape checks)
        that must not mark columns as used for projection pushdown."""
        q, n = ref.qualifier, ref.name
        nl = n.lower()
        if q is None:
            if nl in self.window_names or (nl == "window" and self.window):
                return ("window", None)
            if n in self.columns:
                return self._use(n, record)
            if nl in self.columns:
                return self._use(nl, record)
            if n in self.structs:
                return self._use_struct(self.structs[n], presence_only,
                                        record)
            if nl in self.structs:
                return self._use_struct(self.structs[nl], presence_only,
                                        record)
            # case-insensitive fallback
            for c in self.columns:
                if c.lower() == nl:
                    return self._use(c, record)
            raise SqlCompileError(f"unknown column {ref.display!r} "
                                  f"(have {sorted(self.columns)[:20]})")
        ql = q.lower()
        if ql in self.structs or q in self.structs:
            sd = self.structs.get(q) or self.structs[ql]
            if nl in sd.fields:
                return self._use(sd.fields[nl], record)
            raise SqlCompileError(f"struct {q} has no field {n}")
        if ql in self.window_names:
            if nl in ("start", "end"):
                return self._use(f"window_{nl}", record)
            raise SqlCompileError(f"window has no field {n}")
        if (ql, nl) in self.qualified:
            return self._use(self.qualified[(ql, nl)], record)
        if ql in {a.lower() for a in self.aliases}:
            return self.resolve(ColumnRef(n), presence_only, record)
        # qualifier might be a struct accessed through an alias chain a.b.c
        if "." in ql:
            parts = ql.split(".")
            if parts[-1] in self.structs:
                return self.resolve(ColumnRef(n, parts[-1]),
                                    presence_only, record)
            if parts[0] in {a.lower() for a in self.aliases}:
                return self.resolve(ColumnRef(n, ".".join(parts[1:])),
                                    presence_only, record)
        raise SqlCompileError(f"cannot resolve qualifier {q!r} for column {n!r}")


@dataclass
class Compiled:
    fn: Callable[[Dict[str, Any]], MV]
    needs_host: bool = False
    sql: str = ""
    # physical columns the expression reads (from the compile-time AST):
    # lets the executor skip coercing/padding untouched columns
    used_cols: Optional[frozenset] = None


def _jnp():
    import jax.numpy as jnp

    return jnp


from ..formats import nan_validity  # noqa: F401  (re-export: SQL layers
# import the shared null-modality definition from here)


def _mask_and(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


LIKE_CACHE: Dict[str, Any] = {}


def _coerce_object_col(v: np.ndarray):
    from ..formats import coerce_object_col

    return coerce_object_col(v)


def _like_to_regex(pattern: str):
    if pattern not in LIKE_CACHE:
        rx = "^" + re.escape(pattern).replace("%", ".*").replace("_", ".") + "$"
        LIKE_CACHE[pattern] = re.compile(rx)
    return LIKE_CACHE[pattern]


class ExprCompiler:
    def __init__(self, schema: Schema):
        self.schema = schema
        self.needs_host = False
        self.used_cols: set = set()

    # -- main dispatch ----------------------------------------------------

    def compile(self, e: Expr) -> Callable[[Dict[str, Any]], MV]:
        jnp = _jnp()
        if isinstance(e, Literal):
            if e.value is None:
                return lambda env: (np.int64(0), np.bool_(False))
            v = e.value
            return lambda env: (v, None)
        if isinstance(e, IntervalLit):
            us = e.micros
            return lambda env: (us, None)
        if isinstance(e, ColumnRef):
            # niladic SQL keywords (no parens in the grammar) arrive as
            # bare column refs: CURRENT_DATE / CURRENT_TIME / CURRENT_TIMESTAMP
            if (e.qualifier is None
                    and e.name.lower() in ("current_date", "current_time",
                                           "current_timestamp")
                    and e.name.lower() not in self.schema.columns):
                return self._compile_function(FunctionCall(e.name.lower(), []))
            kind, target = self.schema.resolve(e)
            if kind == "col":
                self.used_cols.add(target)
                if self.schema.is_string(target):
                    self.needs_host = True
                # temporal columns are int64 epoch micros: jit (x64 off)
                # would truncate them to int32, so they force the host path
                if (self.schema.columns.get(target) == "t"
                        or target == "__timestamp"):
                    self.needs_host = True
                # struct-field presence mask applies when the physical column
                # came from a struct
                sd = self._struct_of_field(target)
                pcpv = ((sd.presence_col, sd.presence_val)
                        if sd is not None and sd.presence_col is not None
                        and sd.name.lower() not in
                        self.schema.presence_guaranteed
                        else None)
                if pcpv is not None:
                    self.used_cols.add(pcpv[0])
                is_str = self.schema.is_string(target)

                def load(env, _t=target, _p=pcpv, _s=is_str):
                    v = env[_t]
                    # in jit envs, object columns were pre-coerced by
                    # CompiledExpr with their validity under __mask_<col>;
                    # on host paths the raw object array is coerced here
                    m = env.get("__mask_" + _t)
                    if (not _s and isinstance(v, np.ndarray)
                            and v.dtype == object):
                        v, m2 = _coerce_object_col(v)
                        m = m2 if m is None else (
                            m if m2 is None else (m & m2))
                    elif (_s and isinstance(v, np.ndarray)
                            and v.dtype == object):
                        # string NULLs (None cells) must carry validity:
                        # without a mask, None == None compared TRUE and
                        # `WHERE s = s` kept NULL rows (SQL: NULL = NULL
                        # is NULL, never true).  All-valid columns skip
                        # the mask so plain projections stay zero-copy.
                        nn = np.asarray(nan_validity(v, None))
                        if not nn.all():
                            m = nn if m is None else (m & nn)
                    if _p is not None:
                        pm = env[_p[0]] == _p[1]
                        m = pm if m is None else (m & pm)
                    return v, m

                return load
            if kind == "struct":
                sd = target
                if sd.presence_col is None:
                    raise SqlCompileError(
                        f"struct {sd.name} has no presence column; "
                        "use its fields")
                pc, pv = sd.presence_col, sd.presence_val
                self.used_cols.add(pc)
                # a struct used as a value: expose its presence (IS NULL etc.)
                return lambda env: (env[pc] == pv, None)
            raise SqlCompileError(
                "window column can only be projected as `window` or compared "
                "for equality in a join")
        if isinstance(e, BinaryOp):
            return self._compile_binary(e)
        if isinstance(e, UnaryOp):
            inner = self.compile(e.operand)
            if e.op == "-":
                return lambda env: ((lambda v, m: (-v, m))(*inner(env)))
            if e.op == "not":
                def notf(env):
                    v, m = inner(env)
                    return ~v if hasattr(v, "__invert__") else (not v), m
                return notf
            raise SqlCompileError(f"unary {e.op}")
        if isinstance(e, IsNull):
            inner_e = e.operand
            # `struct IS NOT NULL` -> presence mask directly (and only the
            # presence column counts as used for pushdown)
            if isinstance(inner_e, ColumnRef):
                kind, target = self.schema.resolve(inner_e,
                                                   presence_only=True)
                if kind == "struct":
                    pc, pv = target.presence_col, target.presence_val
                    self.used_cols.add(pc)
                    if e.negated:
                        return lambda env: (env[pc] == pv, None)
                    return lambda env: (env[pc] != pv, None)
            inner = self.compile(inner_e)

            def isnull(env):
                v, m = inner(env)
                valid = nan_validity(v, m)
                if valid is None:
                    is_valid = jnp.ones(np.shape(v) or (1,), dtype=bool) \
                        if hasattr(v, "shape") else True
                    res = is_valid if e.negated else ~is_valid \
                        if hasattr(is_valid, "__invert__") else not is_valid
                    return res, None
                return (valid if e.negated else ~valid), None
            return isnull
        if isinstance(e, InList):
            inner = self.compile(e.operand)
            items = [self.compile(x) for x in e.items]

            def inlist(env):
                v, m = inner(env)
                acc = None
                for it in items:
                    iv, im = it(env)
                    eq = v == iv
                    acc = eq if acc is None else (acc | eq)
                    m = _mask_and(m, im)
                if e.negated:
                    acc = ~acc
                return acc, m
            return inlist
        if isinstance(e, Between):
            inner = self.compile(e.operand)
            lo = self.compile(e.low)
            hi = self.compile(e.high)

            def between(env):
                v, m = inner(env)
                lv, lm = lo(env)
                hv, hm = hi(env)
                res = (v >= lv) & (v <= hv)
                if e.negated:
                    res = ~res
                return res, _mask_and(m, _mask_and(lm, hm))
            return between
        if isinstance(e, Case):
            return self._compile_case(e)
        if isinstance(e, Cast):
            return self._compile_cast(e)
        if isinstance(e, FunctionCall):
            return self._compile_function(e)
        if isinstance(e, Star):
            raise SqlCompileError("* is only valid as a projection item")
        raise SqlCompileError(f"unsupported expression {e!r}")

    def _struct_of_field(self, phys_col: str) -> Optional[StructDef]:
        for sd in self.schema.structs.values():
            if phys_col in sd.fields.values():
                return sd
        return None

    # -- pieces ------------------------------------------------------------

    def _compile_binary(self, e: BinaryOp):
        jnp = _jnp()
        left = self.compile(e.left)
        right = self.compile(e.right)
        op = e.op

        if op == "like":
            self.needs_host = True

            def like(env):
                v, m = left(env)
                pv, pm = right(env)
                pattern = pv if isinstance(pv, str) else str(np.asarray(pv).reshape(-1)[0])
                rx = _like_to_regex(pattern)
                res = np.array([bool(s is not None and rx.match(s)) for s in v])
                return res, _mask_and(m, pm)
            return like

        if op in ("and", "or"):
            def boolop(env):
                lv, lm = left(env)
                rv, rm = right(env)
                if lm is not None:
                    lv = lv & lm
                if rm is not None:
                    rv = rv & rm
                return (lv & rv) if op == "and" else (lv | rv), None
            return boolop

        import operator as pyop

        ops = {"+": pyop.add, "-": pyop.sub, "*": pyop.mul,
               "=": pyop.eq, "<>": pyop.ne, "<": pyop.lt,
               "<=": pyop.le, ">": pyop.gt, ">=": pyop.ge}

        def _is_int(v):
            if isinstance(v, (bool, np.bool_)):
                return False
            if isinstance(v, (int, np.integer)):
                return True
            if hasattr(v, "dtype"):
                return np.issubdtype(np.asarray(v).dtype, np.integer) \
                    if isinstance(v, np.ndarray) \
                    else jnp.issubdtype(v.dtype, jnp.integer)
            return False

        def _trunc_divmod(lv, rv):
            """(quotient, remainder, zero_mask) with SQL TRUNCATION
            semantics (-7/2 = -3, -7%2 = -1 — python floor-divides) and
            a divisor==0 mask for NULL results.  Pure arithmetic only,
            so numpy inputs stay on host and tracers stay traced."""
            zero = rv == 0
            if isinstance(zero, bool):  # python scalar divisor
                zero = np.bool_(zero)
            sr = rv + zero  # divisor 0 -> 1 (never used: row masked NULL)
            q0 = lv // sr
            rem = lv - q0 * sr
            q = q0 + ((rem != 0) & ((lv < 0) ^ (sr < 0)))
            return q, lv - q * sr, zero

        if op == "||":
            self.needs_host = True

            def concat(env):
                lv, lm = left(env)
                rv, rm = right(env)
                n = len(lv) if hasattr(lv, "__len__") else len(rv)
                lvb = np.broadcast_to(np.asarray(lv, dtype=object), (n,))
                rvb = np.broadcast_to(np.asarray(rv, dtype=object), (n,))
                return (np.asarray([str(a) + str(b) for a, b in zip(lvb, rvb)],
                                   dtype=object), _mask_and(lm, rm))
            return concat

        if op == "/":
            def div(env):
                lv, lm = left(env)
                rv, rm = right(env)
                m = _mask_and(lm, rm)
                # SQL integer division stays integral, TRUNCATES toward
                # zero, and yields NULL on a zero divisor (the previous
                # jnp.maximum(rv, 1) guard silently clamped EVERY
                # divisor below 1 — 10/0 returned 10 and 10/-2 returned
                # 10)
                if _is_int(lv) and _is_int(rv):
                    q, _, zero = _trunc_divmod(lv, rv)
                    return q, _mask_and(m, ~zero)
                return lv / rv, m
            return div

        if op == "%":
            def mod(env):
                lv, lm = left(env)
                rv, rm = right(env)
                m = _mask_and(lm, rm)
                if _is_int(lv) and _is_int(rv):
                    # SQL % carries the DIVIDEND's sign (-7 % 2 = -1;
                    # python floors to 1) and is NULL on a zero divisor
                    _, rem, zero = _trunc_divmod(lv, rv)
                    return rem, _mask_and(m, ~zero)
                # float %: IEEE fmod matches SQL (np.mod floors);
                # fmod(x, 0) is NaN, i.e. SQL NULL, natively

                def is_jax(v):
                    return (hasattr(v, "dtype")
                            and not isinstance(v, (np.ndarray, np.generic)))

                f = jnp.fmod if (is_jax(lv) or is_jax(rv)) else np.fmod
                return f(lv, rv), m
            return mod

        fn = ops[op]

        def binop(env):
            lv, lm = left(env)
            rv, rm = right(env)
            return fn(lv, rv), _mask_and(lm, rm)
        return binop

    def _compile_case(self, e: Case):
        jnp = _jnp()
        operand = self.compile(e.operand) if e.operand is not None else None
        whens = [(self.compile(c), self.compile(v)) for c, v in e.whens]
        else_ = self.compile(e.else_) if e.else_ is not None else None

        def case(env):
            ov = operand(env) if operand else None
            # start from ELSE (or null)
            if else_ is not None:
                out_v, out_m = else_(env)
            else:
                out_v, out_m = np.int64(0), np.bool_(False)
            decided = None
            for cond_c, val_c in whens:
                cv, cm = cond_c(env)
                if ov is not None:
                    cv = (ov[0] == cv)
                    cm = _mask_and(ov[1], cm)
                if cm is not None:
                    cv = cv & cm
                take = cv if decided is None else (cv & ~decided)
                vv, vm = val_c(env)
                out_v = jnp.where(take, vv, out_v)
                if vm is None and out_m is None:
                    pass
                else:
                    vm_full = vm if vm is not None else True
                    om_full = out_m if out_m is not None else True
                    out_m = jnp.where(take, vm_full, om_full)
                decided = cv if decided is None else (decided | cv)
            return out_v, out_m
        return case

    def _compile_cast(self, e: Cast):
        jnp = _jnp()
        inner = self.compile(e.operand)
        t = e.target_type

        if t in ("int", "integer", "bigint", "smallint", "tinyint"):
            def toint(env):
                # float NaN is the in-band NULL; an int64 cast cannot
                # carry it, so it moves into the validity mask (it used
                # to cast to 0 silently).  A float source ALWAYS yields
                # a masked (nullable) int on both host and jit paths —
                # the engine-wide nullable-int-as-f64 convention — so
                # the two modalities cannot disagree on output dtype.
                # Null detection routes through nan_validity, THE single
                # null definition.
                v, m = inner(env)
                if isinstance(v, np.ndarray) and v.dtype == object:
                    nn = np.asarray(nan_validity(v, None))
                    vals = np.asarray(
                        [int(float(x)) if ok else 0
                         for x, ok in zip(v, nn)], dtype=np.int64)
                    return vals, (nn if m is None else (m & nn))
                is_np = isinstance(v, np.ndarray) or not hasattr(v, "dtype")
                arr = np.asarray(v) if is_np else v
                xp = np if is_np else jnp
                if (arr.dtype.kind == "f" if is_np
                        else jnp.issubdtype(arr.dtype, jnp.floating)):
                    nn = nan_validity(arr, None)
                    arr = xp.where(xp.asarray(nn), arr, 0.0)
                    m = nn if m is None else (m & nn)
                return arr.astype(xp.int64), m
            return toint
        if t in ("float", "double", "real", "decimal", "numeric"):
            def tofloat(env):
                v, m = inner(env)
                if isinstance(v, np.ndarray) and v.dtype == object:
                    return np.asarray([float(x) for x in v],
                                      dtype=np.float32), m
                return jnp.asarray(v).astype(jnp.float32), m
            return tofloat
        if t in ("bool", "boolean"):
            return lambda env: ((lambda v, m: (jnp.asarray(v).astype(bool), m))
                                (*inner(env)))
        if t in ("text", "varchar", "string", "char"):
            self.needs_host = True

            def tostr(env):
                v, m = inner(env)
                arr = np.asarray(v)
                return np.asarray([str(x) for x in arr.tolist()],
                                  dtype=object), m
            return tostr
        if t in ("timestamp", "datetime", "timestamptz", "date"):
            def tots(env):
                v, m = inner(env)
                arr = np.asarray(v) if not hasattr(v, "dtype") or \
                    isinstance(v, np.ndarray) else v
                if isinstance(arr, np.ndarray) and arr.dtype == object:
                    import pandas as pd

                    parsed = pd.to_datetime(list(arr), errors="coerce", utc=True)
                    vals = parsed.view("int64") // 1000  # ns -> us
                    ok = ~parsed.isna().to_numpy()
                    return vals.to_numpy() if hasattr(vals, "to_numpy") else np.asarray(vals), \
                        _mask_and(m, ok)
                return jnp.asarray(v).astype(jnp.int64), m
            if isinstance(e.operand, ColumnRef):
                kind, target = self.schema.resolve(e.operand)
                if kind == "col" and self.schema.is_string(target):
                    self.needs_host = True
            return tots
        raise SqlCompileError(f"unsupported cast target {t}")

    def _compile_function(self, e: FunctionCall):
        name = e.name
        if e.over is not None:
            raise SqlCompileError(
                f"window function {name}() OVER (...) is only supported "
                "as the ROW_NUMBER TopN shape")
        if name in ("hop", "tumble", "session"):
            raise SqlCompileError(
                f"{name}() is only valid in GROUP BY (window assignment)")
        if name in ("count", "sum", "min", "max", "avg"):
            raise SqlCompileError(
                f"aggregate {name}() outside of aggregation context")
        if name == "date_trunc":
            from .functions import CAL_TRUNC_PRECISIONS

            precision = e.args[0]
            if not isinstance(precision, Literal):
                raise SqlCompileError("date_trunc precision must be a literal")
            inner = self.compile(e.args[1])
            p = str(precision.value).lower()
            if p in CAL_TRUNC_PRECISIONS:
                # calendar arithmetic (variable month lengths): host path
                self.needs_host = True
                fn = HOST_FUNCTIONS["__date_trunc_host"]
            else:
                fn = DEVICE_FUNCTIONS["__date_trunc"]
            return lambda env: fn(inner(env), p)
        if name == "date_part" or name == "extract":
            from .functions import CAL_EXTRACT_FIELDS

            fld = e.args[0]
            if not isinstance(fld, Literal):
                raise SqlCompileError("date_part field must be a literal")
            inner = self.compile(e.args[1])
            f = str(fld.value).lower()
            if f in CAL_EXTRACT_FIELDS:
                self.needs_host = True
                fn = HOST_FUNCTIONS["__extract_host"]
            else:
                fn = DEVICE_FUNCTIONS["__extract"]
            return lambda env: fn(inner(env), f)
        args = [self.compile(a) for a in e.args]
        if name in DEVICE_FUNCTIONS:
            fn = DEVICE_FUNCTIONS[name]
            return lambda env: fn([a(env) for a in args])
        if name in HOST_FUNCTIONS:
            self.needs_host = True
            fn = HOST_FUNCTIONS[name]
            if getattr(fn, "needs_env", False):
                # per-row zero-arg fns (uuid, random) need the batch length
                return lambda env: fn([a(env) for a in args], env)
            return lambda env: fn([a(env) for a in args])
        from .functions import SCALAR_UDFS

        if name in SCALAR_UDFS:
            self.needs_host = True
            udf = SCALAR_UDFS[name]

            def call_udf(env):
                pairs = [a(env) for a in args]
                vals = [np.asarray(v) for v, _m in pairs]
                out = np.asarray(udf(*vals))
                mask = None
                for _v, m in pairs:
                    if m is not None:
                        mask = np.asarray(m) if mask is None \
                            else (mask & np.asarray(m))
                return out, mask

            return call_udf
        raise SqlCompileError(f"unknown function {name}()")


def compile_scalar(e: Expr, schema: Schema, sql: str = "") -> Compiled:
    c = ExprCompiler(schema)
    fn = c.compile(e)
    return Compiled(fn, c.needs_host, sql, frozenset(c.used_cols))
