"""URL-dispatched object storage — analog of the reference's ``arroyo-storage``
crate (``StorageProvider::{for_url, get, put, delete_if_present}``,
arroyo-storage/src/lib.rs:135-389).

Schemes: ``file://`` (and bare paths), ``memory://`` (tests), with ``gs://`` /
``s3://`` gated behind optional gcsfs/s3fs imports (not installed in this
image — the provider raises a clear error rather than failing at import)."""

from __future__ import annotations

import os
import shutil
import threading
from typing import Dict, List, Optional
from urllib.parse import urlparse

_MEMORY_STORES: Dict[str, Dict[str, bytes]] = {}
_MEMORY_LOCK = threading.Lock()


class StorageProvider:
    def __init__(self, scheme: str, root: str):
        self.scheme = scheme
        self.root = root

    # -- constructors ------------------------------------------------------

    @staticmethod
    def for_url(url: str) -> "StorageProvider":
        parsed = urlparse(url)
        scheme = parsed.scheme or "file"
        if scheme == "file":
            path = parsed.path if parsed.scheme else url
            return LocalStorage("file", path)
        if scheme == "memory":
            return MemoryStorage("memory", parsed.netloc + parsed.path)
        if scheme in ("gs", "s3"):
            return _fsspec_storage(scheme, url)
        raise ValueError(f"unsupported storage scheme: {scheme} ({url})")

    # -- interface ---------------------------------------------------------

    def put(self, key: str, data: bytes) -> str:
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    def delete_if_present(self, key: str) -> None:
        raise NotImplementedError

    def delete_prefix(self, prefix: str) -> None:
        raise NotImplementedError

    def list(self, prefix: str) -> List[str]:
        raise NotImplementedError

    def size(self, key: str) -> int:
        """Object size in bytes without reading the payload."""
        return len(self.get(key))

    def url_for(self, key: str) -> str:
        return f"{self.scheme}://{os.path.join(self.root, key)}"

    def local_path(self, key: str) -> Optional[str]:
        """Filesystem path if this is local storage (for pyarrow direct IO)."""
        return None


class LocalStorage(StorageProvider):
    def _path(self, key: str) -> str:
        return os.path.join(self.root, key)

    def put(self, key: str, data: bytes) -> str:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        return path

    def get(self, key: str) -> bytes:
        with open(self._path(key), "rb") as f:
            return f.read()

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def delete_if_present(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def delete_prefix(self, prefix: str) -> None:
        shutil.rmtree(self._path(prefix), ignore_errors=True)

    def list(self, prefix: str) -> List[str]:
        base = self._path(prefix)
        out: List[str] = []
        if not os.path.isdir(base):
            return out
        for dirpath, _, files in os.walk(base):
            for fn in files:
                full = os.path.join(dirpath, fn)
                out.append(os.path.relpath(full, self.root))
        return sorted(out)

    def size(self, key: str) -> int:
        return os.path.getsize(self._path(key))

    def local_path(self, key: str) -> Optional[str]:
        return self._path(key)


class MemoryStorage(StorageProvider):
    def __init__(self, scheme: str, root: str):
        super().__init__(scheme, root)
        with _MEMORY_LOCK:
            self._store = _MEMORY_STORES.setdefault(root, {})

    def put(self, key: str, data: bytes) -> str:
        self._store[key] = bytes(data)
        return key

    def get(self, key: str) -> bytes:
        return self._store[key]

    def exists(self, key: str) -> bool:
        return key in self._store

    def delete_if_present(self, key: str) -> None:
        self._store.pop(key, None)

    def delete_prefix(self, prefix: str) -> None:
        for k in [k for k in self._store if k.startswith(prefix)]:
            del self._store[k]

    def list(self, prefix: str) -> List[str]:
        return sorted(k for k in self._store if k.startswith(prefix))


class FsspecStorage(StorageProvider):
    """gs:// / s3:// via fsspec (gcsfs/s3fs — installed in the deploy
    image; this dev image lacks them, so construction raises a clear
    error instead of failing at import, mirroring arroyo-storage's
    object_store feature flags)."""

    def __init__(self, scheme: str, url: str):
        try:
            import fsspec

            self.fs = fsspec.filesystem(scheme)
        except (ImportError, ValueError) as e:
            raise RuntimeError(
                f"{scheme}:// storage requires "
                f"{'gcsfs' if scheme == 'gs' else 's3fs'}, which is not "
                "installed in this image; use file:// or memory://") from e
        parsed = urlparse(url)
        super().__init__(scheme, parsed.netloc + parsed.path.rstrip("/"))

    def _path(self, key: str) -> str:
        return f"{self.root}/{key}" if key else self.root

    def put(self, key: str, data: bytes) -> str:
        with self.fs.open(self._path(key), "wb") as f:
            f.write(data)
        return self._path(key)

    def get(self, key: str) -> bytes:
        with self.fs.open(self._path(key), "rb") as f:
            return f.read()

    def exists(self, key: str) -> bool:
        return self.fs.exists(self._path(key))

    def delete_if_present(self, key: str) -> None:
        try:
            self.fs.rm(self._path(key))
        except FileNotFoundError:
            pass

    def delete_prefix(self, prefix: str) -> None:
        try:
            self.fs.rm(self._path(prefix), recursive=True)
        except FileNotFoundError:
            pass

    def list(self, prefix: str) -> List[str]:
        base = self._path(prefix)
        try:
            files = self.fs.find(base)
        except FileNotFoundError:
            return []
        return sorted(f[len(self.root) + 1:] for f in files)

    def size(self, key: str) -> int:
        return int(self.fs.size(self._path(key)))


def _fsspec_storage(scheme: str, url: str) -> StorageProvider:
    return FsspecStorage(scheme, url)
