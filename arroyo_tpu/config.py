"""Env-first configuration, mirroring the reference's env-var config system
(arroyo-types/src/lib.rs:78-201: TASK_SLOTS, CONTROLLER_ADDR, CHECKPOINT_URL,
ARTIFACT_URL, ``{SERVICE}__GRPC_PORT``...).  No config files; a typed settings
object reads the environment once, with the same defaults where the reference
defines them."""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v else default


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v else default


def _env_str(name: str, default: str) -> str:
    return os.environ.get(name) or default


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.lower() in ("1", "true", "yes", "on")


def grpc_port(service: str, default: int) -> int:
    """``{SERVICE}__GRPC_PORT`` override pattern (arroyo-types lib.rs:195-201)."""
    return _env_int(f"{service.upper()}__GRPC_PORT", default)


@dataclass
class Config:
    # Worker / engine
    task_slots: int = field(default_factory=lambda: _env_int("TASK_SLOTS", 16))
    queue_size: int = field(default_factory=lambda: _env_int("QUEUE_SIZE", 64))
    # Batching policy for the columnar data plane (no reference analog: the
    # reference is per-record; these bound batch size/latency at the source).
    target_batch_size: int = field(
        default_factory=lambda: _env_int("BATCH_SIZE", 8192)
    )
    batch_linger_micros: int = field(
        default_factory=lambda: _env_int("BATCH_LINGER_MICROS", 10_000)
    )
    # Input-side micro-batch coalescing (engine/coalesce.py): merge
    # sub-target fragments at task inputs before dispatch.  Target rows
    # (0 = use target_batch_size) and the bounded linger a partial
    # buffer may wait for more input.  ARROYO_COALESCE=0 disables.
    coalesce_target: int = field(
        default_factory=lambda: _env_int("COALESCE_TARGET", 0)
    )
    coalesce_linger_micros: int = field(
        default_factory=lambda: _env_int("COALESCE_LINGER_MICROS", 2_000)
    )

    # Control plane
    controller_addr: str = field(
        default_factory=lambda: _env_str("CONTROLLER_ADDR", "http://localhost:9190")
    )
    node_id: Optional[str] = field(default_factory=lambda: os.environ.get("NODE_ID"))
    job_id: Optional[str] = field(default_factory=lambda: os.environ.get("JOB_ID"))
    run_id: Optional[str] = field(default_factory=lambda: os.environ.get("RUN_ID"))

    # Storage
    checkpoint_url: str = field(
        default_factory=lambda: _env_str("CHECKPOINT_URL", "file:///tmp/arroyo_tpu/checkpoints")
    )
    artifact_url: str = field(
        default_factory=lambda: _env_str("ARTIFACT_URL", "file:///tmp/arroyo_tpu/artifacts")
    )
    # JAX persistent compilation cache (engine/aot.py): '' = the
    # env-signature-keyed default under the /tmp scratch dir, 'off'
    # disables, anything else is used verbatim.  ARROYO_COMPILE_CACHE
    # accepted as a legacy alias.
    compile_cache_dir: str = field(
        default_factory=lambda: _env_str(
            "COMPILE_CACHE_DIR", _env_str("ARROYO_COMPILE_CACHE", ""))
    )

    # Supervision (job_controller/mod.rs:30-32 defaults)
    # checkpoint retention: prune to the last N completed epochs after
    # every successful checkpoint and after every rescale restore point
    # (CHECKPOINTS_TO_KEEP accepted as a legacy alias)
    checkpoint_retention: int = field(
        default_factory=lambda: _env_int(
            "CHECKPOINT_RETENTION", _env_int("CHECKPOINTS_TO_KEEP", 3))
    )
    compact_every: int = field(default_factory=lambda: _env_int("COMPACT_EVERY", 2))
    heartbeat_interval_secs: float = field(
        default_factory=lambda: _env_float("HEARTBEAT_INTERVAL_SECS", 5.0)
    )
    heartbeat_timeout_secs: float = field(
        default_factory=lambda: _env_float("HEARTBEAT_TIMEOUT_SECS", 30.0)
    )
    checkpoint_interval_secs: float = field(
        default_factory=lambda: _env_float("CHECKPOINT_INTERVAL_SECS", 10.0)
    )

    # Device execution
    device_platform: str = field(
        default_factory=lambda: _env_str("ARROYO_TPU_PLATFORM", "")
    )  # '' = jax default
    state_capacity: int = field(
        default_factory=lambda: _env_int("STATE_CAPACITY", 1 << 12)
    )  # initial per-subtask keyed-state slots (doubles on overflow;
    # benchmarks pre-size via STATE_CAPACITY to avoid growth recompiles)

    # Autoscaling (arroyo_tpu/autoscale): ARROYO_AUTOSCALE=0 is the
    # global escape hatch — no per-job control loops run at all.  With
    # the subsystem enabled, jobs still start with the loop inactive
    # unless ARROYO_AUTOSCALE_DEFAULT=1 (or the REST PUT enables them).
    autoscale_enabled: bool = field(
        default_factory=lambda: _env_bool("ARROYO_AUTOSCALE", True)
    )
    autoscale_default_on: bool = field(
        default_factory=lambda: _env_bool("ARROYO_AUTOSCALE_DEFAULT", False)
    )
    autoscale_interval_secs: float = field(
        default_factory=lambda: _env_float("AUTOSCALE_INTERVAL_SECS", 15.0)
    )

    # End-to-end latency observatory (obs/latency.py): deterministic
    # 1-in-N record-level sampling at sources (0 = observatory off), and
    # the per-pipeline declarative SLO the controller evaluates against
    # rollup quantiles (0 = that SLO dimension unset).  REST can override
    # the SLO per job after start.
    latency_sample_n: int = field(
        default_factory=lambda: _env_int("ARROYO_LATENCY_SAMPLE_N", 0)
    )
    slo_p99_ms: float = field(
        default_factory=lambda: _env_float("ARROYO_SLO_P99_MS", 0.0)
    )
    slo_staleness_ms: float = field(
        default_factory=lambda: _env_float("ARROYO_SLO_STALENESS_MS", 0.0)
    )
    slo_burn_window_secs: float = field(
        default_factory=lambda: _env_float("ARROYO_SLO_BURN_WINDOW_SECS", 60.0)
    )

    # Telemetry
    disable_telemetry: bool = field(
        default_factory=lambda: _env_bool("DISABLE_TELEMETRY", True)
    )

    # Admin/metrics
    admin_port: int = field(default_factory=lambda: _env_int("ADMIN_PORT", 9191))


_config: Optional[Config] = None


def config() -> Config:
    global _config
    if _config is None:
        _config = Config()
    return _config


def reset_config() -> None:
    """Testing hook: force re-read of the environment."""
    global _config
    _config = None
