"""Logical dataflow graph — the analog of the reference's ``arroyo-datastream``
crate (/root/reference/arroyo-datastream/src/lib.rs).

Reproduces the full operator taxonomy (``Operator`` enum, lib.rs:321-372), the
window types (lib.rs:102-108), ``StreamNode``/``StreamEdge``/``EdgeType``
(lib.rs:497-553), the fluent ``Stream`` builder API (lib.rs:559-986), graph
validation (window-needs-watermark, lib.rs:1099-1117) and the graph hash used
for artifact caching (lib.rs:1140-1154).

Where the reference's operators carry *Rust source strings* to be spliced into
a generated binary (``make_graph_function``, lib.rs:1216-1700), ours carry
Python callables over columnar batches: element-wise expressions are functions
``cols -> cols`` traced by jax.jit inside the physical operators, so "compiling
a pipeline" is tracing, not cargo.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import networkx as nx

MICROS = 1_000_000


# ---------------------------------------------------------------------------
# Window types (arroyo-datastream/src/lib.rs:102-108)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TumblingWindow:
    width_micros: int


@dataclass(frozen=True)
class SlidingWindow:
    width_micros: int
    slide_micros: int


@dataclass(frozen=True)
class InstantWindow:
    pass


@dataclass(frozen=True)
class SessionWindow:
    gap_micros: int


WindowType = Any  # union of the four dataclasses above


def window_label(w: WindowType) -> str:
    if isinstance(w, TumblingWindow):
        return f"tumbling({w.width_micros}us)"
    if isinstance(w, SlidingWindow):
        return f"sliding({w.width_micros}us,{w.slide_micros}us)"
    if isinstance(w, InstantWindow):
        return "instant"
    if isinstance(w, SessionWindow):
        return f"session({w.gap_micros}us)"
    raise TypeError(w)


# ---------------------------------------------------------------------------
# Aggregates & expressions
# ---------------------------------------------------------------------------


class AggKind(Enum):
    COUNT = "count"
    COUNT_DISTINCT = "count_distinct"
    SUM = "sum"
    MIN = "min"
    MAX = "max"
    AVG = "avg"
    VEC = "vec"  # collect values (WindowAgg::Expression / flatten path)
    UDAF = "udaf"  # user aggregate fn(values)->scalar; buffered paths only


@dataclass(frozen=True)
class AggSpec:
    """One aggregate: kind + input column + output column name.

    ``fn`` carries the Python callable for UDAF kinds (user aggregates,
    the analog of the reference's registered UDFs executed in the worker,
    arroyo-sql/src/lib.rs:196-290 + operators/mod.rs:347-494).  UDAFs are
    not mergeable, so they plan onto the buffered window paths only —
    matching the reference's two-phase exclusion (operators.rs:165-167).
    """

    kind: AggKind
    column: Optional[str]  # None for COUNT(*)
    output: str
    fn: Optional[Any] = None


class ExprReturnType(Enum):
    """ExpressionReturnType (arroyo-datastream/src/lib.rs:549-553)."""

    PREDICATE = "predicate"
    RECORD = "record"
    OPTIONAL_RECORD = "optional_record"


@dataclass
class ColumnExpr:
    """A columnar expression: ``fn(cols: dict[str, array]) -> dict | array``.

    ``fn`` must be jnp-traceable (no data-dependent Python control flow); the
    physical ExpressionOperator jits it over the batch columns.  ``name`` keys
    the jit cache and the graph hash.
    """

    name: str
    fn: Callable[[Dict[str, Any]], Any]
    return_type: ExprReturnType = ExprReturnType.RECORD
    output_schema: Optional[Dict[str, Any]] = None
    sql: str = ""  # original SQL text when planner-generated (for hashing/UI)

    def hash_token(self) -> str:
        return self.sql or self.name


# ---------------------------------------------------------------------------
# Operator taxonomy (Operator enum, arroyo-datastream/src/lib.rs:321-372)
# ---------------------------------------------------------------------------


class OpKind(Enum):
    CONNECTOR_SOURCE = "connector_source"
    CONNECTOR_SINK = "connector_sink"
    EXPRESSION = "expression"  # map / filter / option-map
    FLAT_MAP = "flat_map"
    FLATTEN = "flatten"
    UDF = "udf"  # python UDF (reference: FusedWasmUDFs)
    WATERMARK = "watermark"
    KEY_BY = "key_by"
    GLOBAL_KEY = "global_key"
    WINDOW = "window"  # KeyedWindowFunc / SessionWindowFunc
    COUNT = "count"
    AGGREGATE = "aggregate"  # AggregateBehavior Max/Min/Sum
    WINDOW_JOIN = "window_join"
    SLIDING_WINDOW_AGGREGATOR = "sliding_window_aggregator"
    TUMBLING_WINDOW_AGGREGATOR = "tumbling_window_aggregator"
    TUMBLING_TOP_N = "tumbling_top_n"
    SLIDING_AGGREGATING_TOP_N = "sliding_aggregating_top_n"
    JOIN_WITH_EXPIRATION = "join_with_expiration"
    UPDATING = "updating"
    NON_WINDOW_AGGREGATOR = "non_window_aggregator"
    UPDATING_KEY = "updating_key"
    UNION = "union"  # N-ary stream merge (the reference bails on unions)
    WINDOW_ARGMAX = "window_argmax"  # fused self-join-on-window-max
    MULTI_WAY_JOIN = "multi_way_join"  # N-ary shared-key equi-join
    # factor-window sharing (graph/factor_windows.py, "Factor Windows"
    # PAPERS.md): ONE shared pane ring feeding per-query derived windows
    WINDOW_FACTOR = "window_factor"  # shared factor-pane aggregate
    DERIVED_WINDOW = "derived_window"  # rolls factor panes into a query window


class JoinType(Enum):
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    FULL = "full"
    SEMI = "semi"  # IN (SELECT ...): left rows emit once on first match


@dataclass
class PeriodicWatermarkSpec:
    """Operator::Watermark(PeriodicWatermark) — fixed-lateness or expression
    watermark with idle detection (operators/mod.rs:97-233)."""

    max_lateness_micros: int = 0
    idle_time_micros: Optional[int] = None
    expression: Optional[ColumnExpr] = None  # row -> watermark timestamp


@dataclass
class WindowSpec:
    """Operator::Window{typ, agg, flatten}."""

    typ: WindowType
    aggs: Tuple[AggSpec, ...] = ()
    flatten: bool = False
    # post-aggregate projection applied to {key cols + agg outputs + window bounds}
    projection: Optional[ColumnExpr] = None


@dataclass
class SlidingAggregatorSpec:
    """Operator::SlidingWindowAggregator — two-phase bin-merged sliding
    aggregate (arroyo-datastream/src/lib.rs:224-241;
    aggregating_window.rs:14-258)."""

    width_micros: int
    slide_micros: int
    aggs: Tuple[AggSpec, ...] = ()
    projection: Optional[ColumnExpr] = None
    # (agg output, 'max'|'min') when emission may pre-filter to local
    # per-pane argmax candidates (set by the planner only when the sole
    # consumer is a WindowArgmax stage, which settles the global answer)
    argmax_local: Optional[Tuple[str, str]] = None


@dataclass
class TumblingAggregatorSpec:
    width_micros: int
    aggs: Tuple[AggSpec, ...] = ()
    projection: Optional[ColumnExpr] = None
    argmax_local: Optional[Tuple[str, str]] = None  # see SlidingAggregatorSpec


@dataclass
class FactorPaneSpec:
    """Operator::WindowFactor — the shared half of a factor-window rewrite
    (graph/factor_windows.py).  One BinAggOperator ring of ``pane_micros``
    tumbling panes maintains the UNION of the member queries' decomposed
    partial aggregates (``__f_*`` columns) once per pane; the member
    queries consume the fired panes as lightweight derived windows."""

    pane_micros: int
    aggs: Tuple[AggSpec, ...] = ()


@dataclass
class DerivedWindowSpec:
    """Operator::DerivedWindow — the per-query half of a factor-window
    rewrite: rolls fired factor panes of ``pane_micros`` into this
    query's (width, slide) windows on the same device bin-ring kernels
    (merge-input mode), emitting exactly the rows the original
    sliding/tumbling aggregate would.  ``aggs``/``projection`` are the
    ORIGINAL member spec's, so checkpoint state tables keep the member's
    channel layout and epochs interchange with unfactored plans."""

    width_micros: int
    slide_micros: int
    pane_micros: int
    aggs: Tuple[AggSpec, ...] = ()
    projection: Optional[ColumnExpr] = None


@dataclass
class TopNSpec:
    """Operator::TumblingTopN (tumbling_top_n_window.rs).

    ``max_elements=None`` ranks without pruning; ``rank_column`` emits
    the 1-based per-partition rank (a materialized ROW_NUMBER())."""

    width_micros: int
    max_elements: Optional[int]
    # expression extracting the sort key column(s); descending order
    sort_column: str = ""
    partition_cols: Tuple[str, ...] = ()
    projection: Optional[ColumnExpr] = None
    rank_column: Optional[str] = None


@dataclass
class SlidingAggregatingTopNSpec:
    """Operator::SlidingAggregatingTopN — fused sliding aggregate + TopN
    (sliding_top_n_aggregating_window.rs; datastream lib.rs:242-262)."""

    width_micros: int
    slide_micros: int
    aggs: Tuple[AggSpec, ...] = ()
    partition_cols: Tuple[str, ...] = ()
    sort_column: str = ""
    max_elements: int = 10
    projection: Optional[ColumnExpr] = None


@dataclass
class JoinWithExpirationSpec:
    left_expiration_micros: int
    right_expiration_micros: int
    join_type: JoinType = JoinType.INNER
    # visible (name, kind) column schemas per side so outer joins can
    # null-pad the missing side even before any batch has arrived from it
    left_cols: Tuple[Tuple[str, str], ...] = ()
    right_cols: Tuple[Tuple[str, str], ...] = ()


@dataclass
class WindowArgmaxSpec:
    """Operator::WindowArgmax — the optimizer's fusion of
    ``A JOIN (SELECT max(x), window FROM A GROUP BY window) ON x = mx``
    (nexmark q5's hot-items shape): buffer A's rows per window, emit the
    rows achieving the window's max (ties included, exactly as the
    self-join emits them), and synthesize the pruned side's columns
    (mx := x).  ``minmax`` is 'max' or 'min'; ``synth_cols`` maps each
    pruned-side output column to the left column it copies."""

    value_col: str
    minmax: str
    synth_cols: Tuple[Tuple[str, str], ...]  # (out_name, left_col)
    width_micros: int  # buffer retention: one window span
    # the upstream aggregate output (__aggN) the value column carries —
    # lets the plan finalizer push a LOCAL candidate pre-filter into the
    # aggregate's emission kernel when this operator is its only consumer
    agg_out: str = ""
    # raw-stream mode (q7's shape: bids JOIN per-window max ON price=mx
    # with a window-range WHERE): inputs are raw rows rather than
    # aggregate outputs, so the operator (a) pre-filters each batch to
    # rows >= the window's running extremum before buffering (the max
    # only grows, so dominated rows can never be final candidates) and
    # (b) matches genuinely-late rows against the released window's
    # FINAL extremum, retained for late_ttl_micros — exactly how the
    # TTL'd join this fusion replaces would still hold the max row and
    # emit a late tying probe (and, like that join, drops the row once
    # the TTL passes)
    raw: bool = False
    late_ttl_micros: int = 0


@dataclass
class WindowJoinSpec:
    """Operator::WindowJoin — windowed stream-stream hash join; outer
    kinds null-pad the unmatched side per fired window (append-only, no
    retractions — each window fires once), matching the reference's
    list-merge codegen (arroyo-sql/src/expressions.rs:134-230)."""

    typ: WindowType
    join_type: JoinType = JoinType.INNER
    left_cols: Tuple[Tuple[str, str], ...] = ()
    right_cols: Tuple[Tuple[str, str], ...] = ()


@dataclass
class MultiWayJoinSpec:
    """Operator::MultiWayJoin — one N-ary INNER equi-join over sides that
    share one join key (the planner's cascaded-join rewrite, after
    "Streaming SQL Multi-Way Join Method for Long State Streams",
    PAPERS.md).  All sides are keyed identically; per fire the operator
    intersects the sides' sorted runs and expands the per-key cross
    product directly — the pairwise intermediates a nested join plan
    would materialize (|A⋈B| rows re-buffered, re-keyed, re-probed
    against C) never exist.

    ``typ`` set: windowed fire (each side buffered for one window span);
    ``typ`` None: TTL'd state probed on every arriving batch."""

    typ: Optional[WindowType] = None
    ttl_micros: int = 0
    side_cols: Tuple[Tuple[Tuple[str, str], ...], ...] = ()


@dataclass
class NonWindowAggregatorSpec:
    """Operator::NonWindowAggregator — updating aggregate with TTL
    (updating_aggregate.rs; datastream lib.rs:264-273)."""

    expiration_micros: int
    aggs: Tuple[AggSpec, ...] = ()
    projection: Optional[ColumnExpr] = None
    # when set (to a key-column name holding an event-time bound, e.g.
    # "window_end"): consolidate refinements in state and emit each key's
    # FINAL row once, when the watermark passes that bound — append-only
    # output instead of create/update refinements
    flush_key: Optional[str] = None


@dataclass
class ConnectorOpSpec:
    """ConnectorOp{operator, config, description}
    (arroyo-datastream/src/lib.rs:281-319)."""

    connector: str  # registry name, e.g. 'impulse', 'nexmark', 'kafka'
    config: Dict[str, Any] = field(default_factory=dict)
    description: str = ""


@dataclass
class LogicalOperator:
    kind: OpKind
    name: str
    spec: Any = None
    expr: Optional[ColumnExpr] = None
    key_cols: Tuple[str, ...] = ()

    def hash_token(self) -> str:
        tok: Dict[str, Any] = {"kind": self.kind.value, "name": self.name}
        if self.expr is not None:
            tok["expr"] = self.expr.hash_token()
            if self.expr.sql:
                # a structural sql token fully describes the computation;
                # the generated display name (agg_input_<n>) must not
                # break equality between duplicated subplans
                del tok["name"]
        if self.key_cols:
            tok["key"] = list(self.key_cols)
        if self.spec is not None:
            tok["spec"] = repr(self.spec)
        return json.dumps(tok, sort_keys=True)


# ---------------------------------------------------------------------------
# Graph
# ---------------------------------------------------------------------------


class EdgeType(Enum):
    FORWARD = "forward"
    SHUFFLE = "shuffle"
    SHUFFLE_JOIN_LEFT = "shuffle_join_0"
    SHUFFLE_JOIN_RIGHT = "shuffle_join_1"
    # additional multi-way join sides (the planner's cascaded-equi-join
    # rewrite feeds one N-ary operator instead of nesting pairwise joins)
    SHUFFLE_JOIN_2 = "shuffle_join_2"
    SHUFFLE_JOIN_3 = "shuffle_join_3"
    SHUFFLE_JOIN_4 = "shuffle_join_4"
    SHUFFLE_JOIN_5 = "shuffle_join_5"
    SHUFFLE_JOIN_6 = "shuffle_join_6"
    SHUFFLE_JOIN_7 = "shuffle_join_7"

    @property
    def is_shuffle(self) -> bool:
        return self is not EdgeType.FORWARD

    @property
    def join_side(self) -> Optional[int]:
        """Input-side index carried by shuffle_join_N edges, else None."""
        if self.value.startswith("shuffle_join_"):
            return int(self.value.rsplit("_", 1)[1])
        return None


def join_side_edge(i: int) -> EdgeType:
    """The shuffle_join edge type for side ``i`` (0-based)."""
    return EdgeType(f"shuffle_join_{i}")


@dataclass
class StreamNode:
    """StreamNode{operator_id, operator, parallelism} (lib.rs:497-502).

    ``max_parallelism`` pins operators whose semantics require a bounded
    subtask count (e.g. a global TopN merge stage must stay at 1) across
    rescales."""

    operator_id: str
    operator: LogicalOperator
    parallelism: int = 1
    max_parallelism: Optional[int] = None


@dataclass
class StreamEdge:
    """StreamEdge{key, value, typ} (lib.rs:517-522); key/value are schema
    descriptions used for display + hashing."""

    typ: EdgeType
    key_schema: str = "()"
    value_schema: str = ""


class Program:
    """Program{graph: DiGraph<StreamNode, StreamEdge>} (lib.rs:1068-1074)."""

    def __init__(self, name: str = "pipeline"):
        self.name = name
        self.graph = nx.DiGraph()
        self._counter = 0

    # -- construction ------------------------------------------------------

    def add_node(self, op: LogicalOperator, parallelism: int = 1) -> str:
        op_id = f"{self._counter}_{op.kind.value}"
        self._counter += 1
        self.graph.add_node(op_id, node=StreamNode(op_id, op, parallelism))
        return op_id

    def add_edge(self, src: str, dst: str, typ: EdgeType,
                 key_schema: str = "()", value_schema: str = "") -> None:
        self.graph.add_edge(src, dst, edge=StreamEdge(typ, key_schema, value_schema))

    def node(self, op_id: str) -> StreamNode:
        return self.graph.nodes[op_id]["node"]

    def edge(self, src: str, dst: str) -> StreamEdge:
        return self.graph.edges[src, dst]["edge"]

    def nodes(self) -> List[StreamNode]:
        return [self.graph.nodes[n]["node"] for n in self.graph.nodes]

    def sources(self) -> List[StreamNode]:
        return [self.node(n) for n in self.graph.nodes if self.graph.in_degree(n) == 0]

    def sinks(self) -> List[StreamNode]:
        return [self.node(n) for n in self.graph.nodes if self.graph.out_degree(n) == 0]

    def topo_order(self) -> List[str]:
        return list(nx.topological_sort(self.graph))

    # -- validation (lib.rs:1099-1117) ------------------------------------

    WINDOWED_KINDS = {
        OpKind.WINDOW,
        OpKind.WINDOW_JOIN,
        OpKind.SLIDING_WINDOW_AGGREGATOR,
        OpKind.TUMBLING_WINDOW_AGGREGATOR,
        OpKind.TUMBLING_TOP_N,
        OpKind.SLIDING_AGGREGATING_TOP_N,
        OpKind.WINDOW_FACTOR,
        OpKind.DERIVED_WINDOW,
    }

    def validate(self) -> List[str]:
        """Window operators require a watermark generator upstream."""
        errors: List[str] = []
        for op_id in self.graph.nodes:
            node = self.node(op_id)
            if node.operator.kind in self.WINDOWED_KINDS:
                if not self._has_upstream(op_id, OpKind.WATERMARK):
                    errors.append(
                        f"{op_id} ({node.operator.kind.value}) requires a "
                        "watermark-assigning operator upstream"
                    )
        return errors

    def _has_upstream(self, op_id: str, kind: OpKind) -> bool:
        for anc in nx.ancestors(self.graph, op_id):
            if self.node(anc).operator.kind == kind:
                return True
        return False

    # -- common-subplan elimination ----------------------------------------

    # sources whose output is a deterministic function of their config
    # AND whose config is faithfully comparable by repr: two scans of
    # the same definition are interchangeable with one scan fanned out,
    # so the dedup pass may merge them.  Anything with consumption
    # state (kafka/kinesis offsets, consumer groups, sse/webhook/
    # polling network reads) must NOT be here.  'memory' is
    # deliberately absent: its config embeds raw numpy batches whose
    # reprs TRUNCATE past 1000 elements, so equal reprs would not prove
    # equal data.
    #
    # Wall-clock caveat: when the config does NOT pin the time base
    # (nexmark base_time_micros / impulse event-time interval), each
    # UNMERGED scan samples its own now() a few ms apart, so the two
    # sides of a self-join were never bit-consistent to begin with;
    # merging gives both consumers one shared base — the semantically
    # intended reading of "the same table".  Exact merged==unmerged
    # parity therefore holds when the base is pinned (what the tests
    # assert) and is *approached from the consistent side* when not.
    # memory tables are fixed batch lists (the test workhorse): two scans
    # of the same table object replay identically, so they merge/compare
    # like the deterministic generators do
    _REPLAYABLE_SOURCES = frozenset({"nexmark", "impulse", "memory"})

    def eliminate_common_subplans(self) -> int:
        """Merge operators that compute the same thing over the same
        inputs (equal structural hash token + equal predecessor set with
        equal edge types), redirecting the duplicate's out-edges to the
        kept node — downstream fan-out is one Collector edge group per
        consumer, so both consumers see identical batches/watermarks.

        SQL with textually repeated subqueries (nexmark q5's
        AuctionBids/CountBids, WITH-clause reuse across the reference
        ledger) otherwise runs the whole duplicated chain twice — twice
        the device updates AND twice the pane-emission readbacks, which
        on a tunneled TPU is the dominant cost.  The reference planner
        leans on DataFusion, which does not dedupe across the join
        inputs either — this pass is a genuine win over it.

        Sinks (side effects) never merge.  Sources merge only when the
        connector is in ``_REPLAYABLE_SOURCES`` (deterministic output,
        repr-comparable config — e.g. q8's two nexmark scans become one
        generation pass with the union of their projections); anything
        with consumption state (kafka offsets, consumer groups) never
        does.  A merge that would create a parallel edge (e.g. both
        sides of a self-join collapsing onto one node, which a DiGraph
        cannot represent and the engine's per-(src, dst) queues do not
        support) is skipped.  Returns the number of nodes removed."""
        import os

        if os.environ.get("ARROYO_CSE", "1") in ("0", "off", "false"):
            return 0
        removed = 0
        changed = True
        while changed:
            changed = False
            by_sig: Dict[tuple, str] = {}
            for op_id in self.topo_order():
                node = self.node(op_id)
                preds = tuple(sorted(
                    (s, d["edge"].typ.value, d["edge"].key_schema)
                    for s, _, d in self.graph.in_edges(op_id, data=True)))
                if node.operator.kind == OpKind.CONNECTOR_SINK:
                    continue  # side effects: two sinks are two sinks
                if node.operator.kind == OpKind.CONNECTOR_SOURCE:
                    # two scans of the same DETERMINISTIC table (q8 reads
                    # nexmark twice: persons side + auctions side) merge
                    # into one generation pass; projections union.
                    # Consumption-stateful connectors (kafka offsets,
                    # consumer groups) stay excluded — merging would
                    # change their delivery semantics.
                    spec = node.operator.spec
                    if getattr(spec, "connector", None) \
                            not in self._REPLAYABLE_SOURCES:
                        continue
                    cfg = {k: v for k, v in spec.config.items()
                           if k != "projection"}
                    sig = ("src", spec.connector,
                           repr(sorted(cfg.items(), key=lambda kv: kv[0])),
                           node.parallelism, node.max_parallelism)
                else:
                    sig = (node.operator.hash_token(), node.parallelism,
                           node.max_parallelism, preds)
                keep = by_sig.get(sig)
                if keep is None:
                    by_sig[sig] = op_id
                    continue
                # expression tokens without a structural sql form are just
                # display names ("map"): equality proves nothing about the
                # wrapped fn, so only merge when the fns are literally the
                # same object (Stream-API callers need not discipline
                # their names for the pass to stay sound)
                expr = node.operator.expr
                if expr is not None and not expr.sql:
                    kept_expr = self.node(keep).operator.expr
                    if kept_expr is None or kept_expr.fn is not expr.fn:
                        continue
                # candidate duplicate: every out-edge must be movable
                outs = list(self.graph.out_edges(op_id, data=True))
                if any(self.graph.has_edge(keep, dst) for _, dst, _ in outs):
                    continue
                if node.operator.kind == OpKind.CONNECTOR_SOURCE:
                    kcfg = self.node(keep).operator.spec.config
                    pa = kcfg.get("projection")
                    pb = node.operator.spec.config.get("projection")
                    if pa and pb:  # both pruned: keep the union
                        kcfg["projection"] = sorted(set(pa) | set(pb))
                    else:  # either side needs every column
                        kcfg.pop("projection", None)
                for _, dst, data in outs:
                    self.graph.add_edge(keep, dst, **data)
                self.graph.remove_node(op_id)
                removed += 1
                changed = True
                break  # graph changed: recompute signatures
        return removed

    def subplan_equal(self, a: str, b: str) -> bool:
        """True when the subplans ending at ``a`` and ``b`` provably
        compute the same stream: identical structural tokens and
        identical (recursively equal) inputs.  Shared nodes short-
        circuit, so chains diverging off a common CTE compare in O(tail).
        Used by the argmax fusion to prove a self-join's two sides are
        the same aggregate; false negatives only cost the optimization."""
        if a == b:
            return True
        na, nb = self.node(a), self.node(b)
        if (na.operator.hash_token() != nb.operator.hash_token()
                or na.parallelism != nb.parallelism):
            return False
        if na.operator.kind == OpKind.CONNECTOR_SOURCE:
            # two DISTINCT scans are "the same stream" only for
            # deterministic replayable sources — kafka/sse scans are
            # independent consumers whose reads diverge even at equal
            # config (same policy as eliminate_common_subplans)
            if getattr(na.operator.spec, "connector", None) \
                    not in self._REPLAYABLE_SOURCES:
                return False
        ea_, eb_ = (na.operator.expr, nb.operator.expr)
        if ea_ is not None and not ea_.sql and ea_.fn is not (
                eb_.fn if eb_ is not None else None):
            return False  # name-only expr tokens prove nothing about fns
        key = lambda e: (e[2]["edge"].typ.value, e[2]["edge"].key_schema)
        pa = sorted(self.graph.in_edges(a, data=True), key=key)
        pb = sorted(self.graph.in_edges(b, data=True), key=key)
        if len(pa) != len(pb) or [key(e) for e in pa] != [key(e) for e in pb]:
            return False
        return all(self.subplan_equal(sa, sb)
                   for (sa, _, _), (sb, _, _) in zip(pa, pb))

    def prune_dead(self) -> int:
        """Remove operators whose output reaches no sink (subplans the
        optimizer bypassed, e.g. the pruned max side of an argmax
        fusion).  Returns the number of nodes removed."""
        removed = 0
        changed = True
        while changed:
            changed = False
            for nid in list(self.graph.nodes):
                if self.node(nid).operator.kind == OpKind.CONNECTOR_SINK:
                    continue
                if self.graph.out_degree(nid) == 0:
                    self.graph.remove_node(nid)
                    removed += 1
                    changed = True
        return removed

    # -- hashing (lib.rs:1140-1154) ---------------------------------------

    def get_hash(self) -> str:
        h = hashlib.sha256()
        for op_id in self.topo_order():
            node = self.node(op_id)
            h.update(node.operator.hash_token().encode())
            h.update(str(node.parallelism).encode())
            for _, dst, data in self.graph.out_edges(op_id, data=True):
                e: StreamEdge = data["edge"]
                h.update(f"{dst}:{e.typ.value}:{e.key_schema}:{e.value_schema}".encode())
        return h.hexdigest()[:16]

    # -- display -----------------------------------------------------------

    def dot(self) -> str:
        lines = ["digraph program {"]
        for op_id in self.graph.nodes:
            n = self.node(op_id)
            lines.append(f'  "{op_id}" [label="{n.operator.name} (p={n.parallelism})"];')
        for s, d, data in self.graph.edges(data=True):
            lines.append(f'  "{s}" -> "{d}" [label="{data["edge"].typ.value}"];')
        lines.append("}")
        return "\n".join(lines)

    def update_parallelism(self, overrides: Dict[str, int]) -> None:
        """Rescaling entry point (states/mod.rs:203-211)."""
        for op_id, p in overrides.items():
            node = self.node(op_id)
            if node.max_parallelism is not None:
                p = min(p, node.max_parallelism)
            node.parallelism = p


# ---------------------------------------------------------------------------
# Fluent builder (Stream<T>/KeyedStream<K,T>, lib.rs:559-986)
# ---------------------------------------------------------------------------


class Stream:
    """Fluent pipeline builder over a Program.

    ``Stream.source(...).map(...).key_by(...).window(...).sink(...)``
    """

    def __init__(self, program: Program, tail: str, keyed: Tuple[str, ...] = ()):
        self.program = program
        self.tail = tail
        self.keyed = keyed

    # -- sources -----------------------------------------------------------

    @staticmethod
    def source(connector: str, config: Optional[Dict[str, Any]] = None,
               parallelism: int = 1, program: Optional[Program] = None,
               name: Optional[str] = None) -> "Stream":
        from ..connectors.registry import get_connector, validate_config

        meta = get_connector(connector)
        if not meta.supports_source:
            raise ValueError(f"connector {connector!r} does not support sources")
        cfg = validate_config(connector, config or {})
        p = program or Program()
        op = LogicalOperator(
            OpKind.CONNECTOR_SOURCE,
            name or f"{connector}_source",
            spec=ConnectorOpSpec(connector, cfg),
        )
        return Stream(p, p.add_node(op, parallelism))

    # -- plumbing ----------------------------------------------------------

    def _chain(self, op: LogicalOperator, parallelism: Optional[int] = None,
               edge: EdgeType = EdgeType.FORWARD,
               keyed: Optional[Tuple[str, ...]] = None) -> "Stream":
        par = parallelism if parallelism is not None else self.program.node(self.tail).parallelism
        nid = self.program.add_node(op, par)
        key_schema = ",".join(self.keyed) if self.keyed else "()"
        self.program.add_edge(self.tail, nid, edge, key_schema=key_schema)
        return Stream(self.program, nid, self.keyed if keyed is None else keyed)

    # -- element-wise ------------------------------------------------------

    def map(self, fn: Callable, name: str = "map",
            sql: str = "", output_schema: Optional[Dict[str, Any]] = None
            ) -> "Stream":
        # output_schema ({col -> kind char}) is optional metadata the
        # SQL planner attaches from its compile-time schema so plan-time
        # analyses (shardcheck's sticky string-column checks) can see
        # through projections; execution never reads it
        expr = ColumnExpr(name, fn, ExprReturnType.RECORD, output_schema,
                          sql=sql)
        return self._chain(LogicalOperator(OpKind.EXPRESSION, name, expr=expr))

    def filter(self, fn: Callable, name: str = "filter") -> "Stream":
        expr = ColumnExpr(name, fn, ExprReturnType.PREDICATE)
        return self._chain(LogicalOperator(OpKind.EXPRESSION, name, expr=expr))

    def option_map(self, fn: Callable, name: str = "option_map") -> "Stream":
        expr = ColumnExpr(name, fn, ExprReturnType.OPTIONAL_RECORD)
        return self._chain(LogicalOperator(OpKind.EXPRESSION, name, expr=expr))

    def flat_map(self, fn: Callable, name: str = "flat_map") -> "Stream":
        expr = ColumnExpr(name, fn, ExprReturnType.RECORD)
        return self._chain(LogicalOperator(OpKind.FLAT_MAP, name, expr=expr))

    def flatten(self, name: str = "flatten") -> "Stream":
        return self._chain(LogicalOperator(OpKind.FLATTEN, name))

    def udf(self, fn: Callable, name: str = "udf",
            sql: str = "", output_schema: Optional[Dict[str, Any]] = None
            ) -> "Stream":
        expr = ColumnExpr(name, fn, ExprReturnType.RECORD, output_schema,
                          sql=sql)
        return self._chain(LogicalOperator(OpKind.UDF, name, expr=expr))

    # -- time --------------------------------------------------------------

    def watermark(self, max_lateness_micros: int = 0,
                  idle_time_micros: Optional[int] = None,
                  expression: Optional[Callable] = None,
                  name: str = "watermark") -> "Stream":
        expr = None
        if expression is not None:
            expr = ColumnExpr(f"{name}_expr", expression, ExprReturnType.RECORD)
        spec = PeriodicWatermarkSpec(max_lateness_micros, idle_time_micros, expr)
        return self._chain(LogicalOperator(OpKind.WATERMARK, name, spec=spec))

    # -- keying ------------------------------------------------------------

    def key_by(self, *cols: str, name: str = "key_by") -> "Stream":
        op = LogicalOperator(OpKind.KEY_BY, name, key_cols=tuple(cols))
        return self._chain(op, keyed=tuple(cols))

    def global_key(self, name: str = "global_key") -> "Stream":
        op = LogicalOperator(OpKind.GLOBAL_KEY, name)
        return self._chain(op, keyed=("__global",))

    # -- windows / aggregates (keyed) -------------------------------------

    def window(self, typ: WindowType, aggs: Sequence[AggSpec] = (),
               flatten: bool = False, projection: Optional[Callable] = None,
               name: Optional[str] = None, parallelism: Optional[int] = None) -> "Stream":
        proj = ColumnExpr(f"{name or 'window'}_proj", projection) if projection else None
        spec = WindowSpec(typ, tuple(aggs), flatten, proj)
        op = LogicalOperator(OpKind.WINDOW, name or f"window_{window_label(typ)}", spec=spec)
        return self._chain(op, parallelism, EdgeType.SHUFFLE)

    def sliding_aggregate(self, width_micros: int, slide_micros: int,
                          aggs: Sequence[AggSpec],
                          projection: Optional[Callable] = None,
                          name: str = "sliding_agg",
                          parallelism: Optional[int] = None) -> "Stream":
        proj = ColumnExpr(f"{name}_proj", projection) if projection else None
        spec = SlidingAggregatorSpec(width_micros, slide_micros, tuple(aggs), proj)
        op = LogicalOperator(OpKind.SLIDING_WINDOW_AGGREGATOR, name, spec=spec)
        return self._chain(op, parallelism, EdgeType.SHUFFLE)

    def tumbling_aggregate(self, width_micros: int, aggs: Sequence[AggSpec],
                           projection: Optional[Callable] = None,
                           name: str = "tumbling_agg",
                           parallelism: Optional[int] = None) -> "Stream":
        proj = ColumnExpr(f"{name}_proj", projection) if projection else None
        spec = TumblingAggregatorSpec(width_micros, tuple(aggs), proj)
        op = LogicalOperator(OpKind.TUMBLING_WINDOW_AGGREGATOR, name, spec=spec)
        return self._chain(op, parallelism, EdgeType.SHUFFLE)

    def tumbling_top_n(self, width_micros: int, max_elements: int, sort_column: str,
                       partition_cols: Sequence[str] = (),
                       projection: Optional[Callable] = None,
                       name: str = "tumbling_top_n",
                       parallelism: Optional[int] = None) -> "Stream":
        proj = ColumnExpr(f"{name}_proj", projection) if projection else None
        spec = TopNSpec(width_micros, max_elements, sort_column, tuple(partition_cols), proj)
        op = LogicalOperator(OpKind.TUMBLING_TOP_N, name, spec=spec)
        return self._chain(op, parallelism, EdgeType.SHUFFLE)

    def sliding_aggregating_top_n(self, width_micros: int, slide_micros: int,
                                  aggs: Sequence[AggSpec], partition_cols: Sequence[str],
                                  sort_column: str, max_elements: int,
                                  projection: Optional[Callable] = None,
                                  name: str = "sliding_topn",
                                  parallelism: Optional[int] = None) -> "Stream":
        proj = ColumnExpr(f"{name}_proj", projection) if projection else None
        spec = SlidingAggregatingTopNSpec(
            width_micros, slide_micros, tuple(aggs), tuple(partition_cols),
            sort_column, max_elements, proj)
        op = LogicalOperator(OpKind.SLIDING_AGGREGATING_TOP_N, name, spec=spec)
        return self._chain(op, parallelism, EdgeType.SHUFFLE)

    def count(self, name: str = "count") -> "Stream":
        return self._chain(LogicalOperator(OpKind.COUNT, name), edge=EdgeType.SHUFFLE)

    def aggregate(self, agg: AggSpec, name: str = "aggregate") -> "Stream":
        op = LogicalOperator(OpKind.AGGREGATE, name, spec=agg)
        return self._chain(op, edge=EdgeType.SHUFFLE)

    def non_window_aggregate(self, expiration_micros: int, aggs: Sequence[AggSpec],
                             projection: Optional[Callable] = None,
                             name: str = "updating_agg",
                             flush_key: Optional[str] = None) -> "Stream":
        proj = ColumnExpr(f"{name}_proj", projection) if projection else None
        spec = NonWindowAggregatorSpec(expiration_micros, tuple(aggs), proj,
                                       flush_key)
        op = LogicalOperator(OpKind.NON_WINDOW_AGGREGATOR, name, spec=spec)
        return self._chain(op, edge=EdgeType.SHUFFLE)

    # -- joins -------------------------------------------------------------

    def window_join(self, other: "Stream", window: WindowType,
                    join_type: JoinType = JoinType.INNER,
                    left_cols: Tuple[Tuple[str, str], ...] = (),
                    right_cols: Tuple[Tuple[str, str], ...] = (),
                    name: str = "window_join",
                    parallelism: Optional[int] = None) -> "Stream":
        assert self.program is other.program, "join streams must share a Program"
        spec = WindowJoinSpec(window, join_type, tuple(left_cols),
                              tuple(right_cols))
        op = LogicalOperator(OpKind.WINDOW_JOIN, name, spec=spec)
        par = parallelism or self.program.node(self.tail).parallelism
        nid = self.program.add_node(op, par)
        ks = ",".join(self.keyed) if self.keyed else "()"
        self.program.add_edge(self.tail, nid, EdgeType.SHUFFLE_JOIN_LEFT, key_schema=ks)
        self.program.add_edge(other.tail, nid, EdgeType.SHUFFLE_JOIN_RIGHT, key_schema=ks)
        return Stream(self.program, nid, self.keyed)

    def multi_way_join(self, others: Sequence["Stream"],
                       typ: Optional[WindowType] = None,
                       ttl_micros: int = 0,
                       side_cols: Tuple[Tuple[Tuple[str, str], ...], ...] = (),
                       name: str = "multi_way_join",
                       parallelism: Optional[int] = None) -> "Stream":
        """N-ary INNER equi-join over sides keyed by the same columns
        (``self`` is side 0).  See :class:`MultiWayJoinSpec`."""
        sides = [self] + list(others)
        assert 2 <= len(sides) <= 8, "multi-way join supports 2..8 sides"
        assert len({s.tail for s in sides}) == len(sides), \
            "multi-way join sides must be distinct nodes (a DiGraph " \
            "would collapse duplicate edges)"
        for o in sides[1:]:
            assert self.program is o.program, \
                "join streams must share a Program"
        # side_cols doubles as the side-count record the physical builder
        # and plan validator read — synthesize empty per-side specs when
        # the caller has none (Stream-API inner joins need no pads)
        if not side_cols:
            side_cols = tuple(() for _ in sides)
        assert len(side_cols) == len(sides), \
            "side_cols must have one entry per join side"
        spec = MultiWayJoinSpec(typ, ttl_micros, tuple(side_cols))
        op = LogicalOperator(OpKind.MULTI_WAY_JOIN, name, spec=spec)
        par = parallelism or self.program.node(self.tail).parallelism
        nid = self.program.add_node(op, par)
        ks = ",".join(self.keyed) if self.keyed else "()"
        for i, s in enumerate(sides):
            self.program.add_edge(s.tail, nid, join_side_edge(i),
                                  key_schema=ks)
        return Stream(self.program, nid, self.keyed)

    def window_argmax(self, value_col: str, minmax: str,
                      synth_cols: Tuple[Tuple[str, str], ...],
                      width_micros: int,
                      name: str = "window_argmax",
                      parallelism: Optional[int] = None,
                      agg_out: str = "", raw: bool = False,
                      late_ttl_micros: int = 0) -> "Stream":
        """Per-window argmax/argmin filter (see WindowArgmaxSpec).  The
        stream must be keyed by the window column so every row of one
        window lands on one subtask — the filter is then global."""
        spec = WindowArgmaxSpec(value_col, minmax, tuple(synth_cols),
                                width_micros, agg_out, raw, late_ttl_micros)
        op = LogicalOperator(OpKind.WINDOW_ARGMAX, name, spec=spec)
        return self._chain(op, parallelism, EdgeType.SHUFFLE)

    def join_with_expiration(self, other: "Stream", left_expiration_micros: int,
                             right_expiration_micros: int,
                             join_type: JoinType = JoinType.INNER,
                             left_cols: Tuple[Tuple[str, str], ...] = (),
                             right_cols: Tuple[Tuple[str, str], ...] = (),
                             name: str = "join", parallelism: Optional[int] = None) -> "Stream":
        assert self.program is other.program
        spec = JoinWithExpirationSpec(left_expiration_micros,
                                      right_expiration_micros, join_type,
                                      tuple(left_cols), tuple(right_cols))
        op = LogicalOperator(OpKind.JOIN_WITH_EXPIRATION, name, spec=spec)
        par = parallelism or self.program.node(self.tail).parallelism
        nid = self.program.add_node(op, par)
        ks = ",".join(self.keyed) if self.keyed else "()"
        self.program.add_edge(self.tail, nid, EdgeType.SHUFFLE_JOIN_LEFT, key_schema=ks)
        self.program.add_edge(other.tail, nid, EdgeType.SHUFFLE_JOIN_RIGHT, key_schema=ks)
        return Stream(self.program, nid, self.keyed)

    def union(self, other: "Stream", name: str = "union",
              parallelism: Optional[int] = None) -> "Stream":
        """Merge two streams (UNION ALL): batches from both flow through
        unchanged; the watermark is the min across inputs (WatermarkHolder
        semantics).  The reference has no union support
        (arroyo-sql/src/pipeline.rs:393)."""
        assert self.program is other.program, "union streams must share a Program"
        if other.tail == self.tail:
            # self-union: nx.DiGraph would collapse the duplicate (src,
            # dst) edge and silently drop the duplication — route one side
            # through a pass-through node
            dup = LogicalOperator(OpKind.UNION, f"{name}_dup")
            dup_id = self.program.add_node(
                dup, self.program.node(other.tail).parallelism)
            self.program.add_edge(other.tail, dup_id, EdgeType.FORWARD,
                                  key_schema="()")
            other = Stream(self.program, dup_id, None)
        op = LogicalOperator(OpKind.UNION, name)
        par = parallelism or self.program.node(self.tail).parallelism
        nid = self.program.add_node(op, par)
        self.program.add_edge(self.tail, nid, EdgeType.SHUFFLE,
                              key_schema="()")
        self.program.add_edge(other.tail, nid, EdgeType.SHUFFLE,
                              key_schema="()")
        return Stream(self.program, nid, None)

    # -- updating ----------------------------------------------------------

    def updating(self, fn: Callable, name: str = "updating") -> "Stream":
        expr = ColumnExpr(name, fn, ExprReturnType.OPTIONAL_RECORD)
        return self._chain(LogicalOperator(OpKind.UPDATING, name, expr=expr))

    def updating_key(self, *cols: str, name: str = "updating_key") -> "Stream":
        op = LogicalOperator(OpKind.UPDATING_KEY, name, key_cols=tuple(cols))
        return self._chain(op, keyed=tuple(cols))

    # -- sinks -------------------------------------------------------------

    def sink(self, connector: str, config: Optional[Dict[str, Any]] = None,
             parallelism: Optional[int] = None, name: Optional[str] = None,
             max_parallelism: Optional[int] = None) -> Program:
        from ..connectors.registry import get_connector, validate_config

        meta = get_connector(connector)
        if not meta.supports_sink:
            raise ValueError(f"connector {connector!r} does not support sinks")
        cfg = validate_config(connector, config or {})
        op = LogicalOperator(
            OpKind.CONNECTOR_SINK,
            name or f"{connector}_sink",
            spec=ConnectorOpSpec(connector, cfg),
        )
        tail = self._chain(op, parallelism)
        if max_parallelism is not None:
            # sinks that must stay single-writer (e.g. single_file) pin
            # here so rescales can never fan them out
            self.program.node(tail.tail).max_parallelism = max_parallelism
        return self.program
