"""Operator chaining — the planner pass that fuses maximal linear runs
of same-parallelism, forward-edge operators into one task.

The reference compiles consecutive operators into a single native binary
where they run fused in one task; our port historically ran *every*
operator as its own TaskRunner with an asyncio queue hop, a ``Batch``
re-materialization and a separate kernel dispatch per hop.  This pass
computes, over the **logical** graph (which it never mutates), the
groups of operators the engine may execute inside a single
:class:`~arroyo_tpu.engine.chained.ChainedOperator`:

* every edge inside a chain is ``FORWARD`` with equal parallelism on
  both ends (a strict 1:1 subtask pairing — no rebalance, no shuffle);
* interior connectivity is linear: the upstream end has exactly one
  out-edge and the downstream end exactly one in-edge, so no fan-in/
  fan-out is hidden inside a chain;
* sources and sinks never chain (sources drive their own loop and are
  where barriers enter the graph; sinks carry two-phase commit
  semantics and their own control handling).

What breaks a chain, therefore: shuffle edges, parallelism changes,
fan-in/fan-out, and sources/sinks.

One refinement (this PR): a **parallelism-1 SHUFFLE edge is routing-
trivial** — every row hashes to the single downstream subtask — so the
edge carries exactly the rows a FORWARD edge would, in the same order.
Such edges may live *inside* a chain (``ARROYO_CHAIN_SHUFFLE1=0``
restores the old break), which lets the ingest spine
(source→project→key_by→window) fuse into one task: the per-batch
queue hop, envelope and alignment between the keyed map and the window
vanish.  Keying is unchanged — the KeyByOperator still computes
``key_hash`` as a chain member, so window state partitioning, rescale
key-range filtering and checkpoint layouts are identical.  At any
other parallelism the shuffle routes for real and breaks the chain
exactly as before (a rescale that widens a chain re-plans and splits
it at the shuffle).

Chain identity is *per member*: checkpoint state tables, metrics labels
and rollups keep each member's own operator_id, so a checkpoint taken
chained restores un-chained and vice versa.  ``ARROYO_CHAIN=0`` disables
the pass entirely and reproduces the per-operator task topology
bit-for-bit.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .logical import EdgeType, OpKind, Program

# operator kinds that never join a chain
_UNCHAINABLE = (OpKind.CONNECTOR_SOURCE, OpKind.CONNECTOR_SINK)


def chaining_enabled() -> bool:
    """``ARROYO_CHAIN=0`` is the full escape hatch (read per call so
    tests and the smoke gate can toggle it without a config reset)."""
    return os.environ.get("ARROYO_CHAIN", "1") not in ("0", "off", "false")


def shuffle1_chaining_enabled() -> bool:
    """``ARROYO_CHAIN_SHUFFLE1=0`` stops chains from crossing
    parallelism-1 shuffle edges (the pre-ingest-fusion behavior)."""
    return os.environ.get("ARROYO_CHAIN_SHUFFLE1", "1") not in (
        "0", "off", "false")


@dataclass
class ChainPlan:
    """The chaining decision for one Program.

    ``groups`` holds only multi-member chains (head first, topo order);
    ``head_of`` maps every member of a multi-member chain to its head;
    ``members_of`` maps each head to its full member list.
    ``shuffle_edges`` lists the chain-interior SHUFFLE edges (the
    routing-trivial parallelism-1 crossings): when the mesh is active,
    these are exactly the edges whose keyed exchange is carried by the
    downstream state's on-device ``all_to_all`` instead of a queue hop
    or data-plane frame — the engine exports the count as
    ``arroyo_mesh_carried_shuffles`` so "the SHUFFLE edge rode the
    mesh" is observable, not inferred."""

    groups: List[List[str]] = field(default_factory=list)
    head_of: Dict[str, str] = field(default_factory=dict)
    members_of: Dict[str, List[str]] = field(default_factory=dict)
    shuffle_edges: List[tuple] = field(default_factory=list)

    def group_for(self, op_id: str) -> Optional[List[str]]:
        head = self.head_of.get(op_id)
        return self.members_of.get(head) if head is not None else None


def _chainable_node(program: Program, op_id: str) -> bool:
    return program.node(op_id).operator.kind not in _UNCHAINABLE


def _chainable_edge(program: Program, u: str, v: str) -> bool:
    g = program.graph
    typ = program.edge(u, v).typ
    if typ is not EdgeType.FORWARD:
        # a parallelism-1 plain SHUFFLE is identity routing: the single
        # downstream subtask receives every row in order, exactly as a
        # FORWARD edge would.  Join-side shuffles never qualify (their
        # side tag carries semantics, and fan-in blocks them below).
        if not (typ is EdgeType.SHUFFLE and shuffle1_chaining_enabled()
                and program.node(u).parallelism == 1
                and program.node(v).parallelism == 1):
            return False
    if not (_chainable_node(program, u) and _chainable_node(program, v)):
        return False
    if program.node(u).parallelism != program.node(v).parallelism:
        return False
    # strictly linear: no fan-out at u, no fan-in at v
    return g.out_degree(u) == 1 and g.in_degree(v) == 1


def plan_chains(program: Program) -> ChainPlan:
    """Compute maximal linear chains over the logical graph.  Returns an
    empty plan when chaining is disabled."""
    plan = ChainPlan()
    if not chaining_enabled():
        return plan
    nxt: Dict[str, str] = {}
    prev: Dict[str, str] = {}
    for u, v in program.graph.edges:
        if _chainable_edge(program, u, v):
            nxt[u] = v
            prev[v] = u
    for op_id in program.topo_order():
        if op_id in prev or op_id not in nxt:
            continue  # not a chain head (interior member, or unchained)
        run = [op_id]
        while run[-1] in nxt:
            run.append(nxt[run[-1]])
        plan.groups.append(run)
        plan.members_of[op_id] = run
        for m in run:
            plan.head_of[m] = op_id
        for u, v in zip(run, run[1:]):
            if program.edge(u, v).typ is not EdgeType.FORWARD:
                plan.shuffle_edges.append((u, v))
    return plan


def validate_chain_plan(program: Program, plan: ChainPlan) -> None:
    """Plan-validator hook for the chaining pass: re-check every chain's
    invariants against the graph and raise ``ValueError`` on violation.
    Cheap (O(edges)); run by the engine before building chained tasks so
    a buggy pass can never silently mis-wire a topology."""
    problems: List[str] = []
    for grp in plan.groups:
        if len(grp) < 2:
            problems.append(f"degenerate chain {grp}")
            continue
        for m in grp:
            if not _chainable_node(program, m):
                problems.append(f"{m}: sources/sinks cannot chain")
        for u, v in zip(grp, grp[1:]):
            if not program.graph.has_edge(u, v):
                problems.append(f"chain edge {u}->{v} missing from graph")
            elif not _chainable_edge(program, u, v):
                problems.append(
                    f"chain edge {u}->{v} is not chainable (shuffle, "
                    "parallelism change, or fan-in/fan-out)")
    if problems:
        raise ValueError("invalid chain plan: " + "; ".join(problems))


def expand_overrides(program: Program,
                     overrides: Dict[str, int]) -> Dict[str, int]:
    """Rescale-path awareness: a chain is the unit of parallelism, so a
    parallelism override addressed to any member applies to the whole
    chain (otherwise the rescale would split the chain and silently lose
    the fusion).  The target is capped at the smallest member
    ``max_parallelism`` so the chain stays uniform after
    ``Program.update_parallelism``'s per-node caps.  When two overrides
    land on the same chain, the larger target wins (scale-up safety).
    No-op when chaining is disabled."""
    plan = plan_chains(program)
    if not plan.groups:
        return dict(overrides)
    out: Dict[str, int] = {}
    for op_id, p in overrides.items():
        group = plan.group_for(op_id)
        if group is None:
            out[op_id] = max(out.get(op_id, 0), p) if op_id in out else p
            continue
        caps = [program.node(m).max_parallelism for m in group
                if program.node(m).max_parallelism is not None]
        target = min([p] + caps)
        for m in group:
            out[m] = max(out.get(m, 0), target)
    return out


def chain_annotations(program: Program) -> Dict[str, str]:
    """{member op_id -> chain head op_id} for multi-member chains — the
    console's DAG grouping payload.  Empty when chaining is disabled."""
    return dict(plan_chains(program).head_of)
