"""Factor-window sharing — cost-based rewrite of correlated window
aggregates onto shared pane state ("Factor Windows", PAPERS.md:
arXiv:2008.12379).

Correlated window aggregates — same upstream input, same key schema,
decomposable aggregates, DIFFERENT widths/slides — each instantiate a
private ``BinAggOperator`` ring today, so K overlapping windows pay K×
the per-event pane-update cost (K scatter dispatches per batch, K
emission readbacks).  This pass detects such sets over the logical
graph and rewrites them so ONE **factor** operator maintains a shared
tumbling pane ring of ``gcd(widths ∪ slides)`` micros, while each
member query becomes a lightweight **derived window** consumer that
rolls the fired factor panes into its own (width, slide) output —
reusing the existing device bin-ring kernels on both halves, so
derivation is a device-side scatter/segment-reduce over fired panes,
never a host loop.

Two correlated shapes are recognized:

* **direct** — members fan out from one shared upstream node (the
  Stream-API shape: ``keyed.sliding_aggregate(...)`` twice off the
  same keyed stream).  The factor hangs off that node; member
  aggregate input columns are shared by name.
* **private-tail** — the SQL planner gives every query its own
  ``agg_input_*`` projection + ``key_by`` below a common ancestor, so
  members NEVER share an immediate upstream.  Members whose tails hang
  off the same ancestor with structurally identical key expressions
  (the ``aggin:`` canonical token) group; the rewrite synthesizes ONE
  union projection (running each member's projection and renaming its
  private ``__ain*`` aggregate inputs to token-keyed shared names, so
  two queries aggregating the same expression share one input column
  AND one factor partial) + one key_by + the factor, and the old
  per-member tails are removed.

Eligibility (all must hold per member):

* kind is SLIDING_WINDOW_AGGREGATOR or TUMBLING_WINDOW_AGGREGATOR fed
  by exactly one plain SHUFFLE edge (join sides and fan-in never
  qualify);
* every aggregate is bin-mergeable — the set ``ops/keyed_bins.py``
  already maintains (COUNT/SUM/MIN/MAX/AVG, no UDAF/VEC/DISTINCT);
* no ``argmax_local`` emission coupling (the argmax fusion owns that
  operator's emission contract);
* ``width % slide == 0`` (the bin-merged fast-path contract).

Cost model: factoring trades K per-event ring updates for ONE update
plus per-pane derivation work.  The factor pane is ``g = gcd(widths ∪
slides)``; the rewrite wins unless ``g`` is pathologically small
relative to the members' own firing cadence — the decision input is
``ratio = min(slides) / g`` (how many times MORE often the factor ring
fires than the finest member would have).  ``ratio <=
ARROYO_FACTOR_MAX_RATIO`` (default 64) shares; a gcd-of-coprime-slides
1 us pane is refused.  Every decision (shared or not) is recorded with
its inputs for the bench/console.

Checkpoint interchange: each derived node KEEPS its member's operator
id and channel layout, and the factor operator drains its pending
panes downstream at every checkpoint barrier (its own snapshot then
holds no un-shipped mass) — so a factored checkpoint restores into an
unfactored plan and vice versa, epoch for epoch (mirroring the PR 4
chained/un-chained contract).

``ARROYO_FACTOR_WINDOWS=0`` disables the pass entirely and reproduces
the unfactored topology bit-for-bit (pinned by test).
"""

from __future__ import annotations

import hashlib
import math
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .logical import (
    AggKind,
    AggSpec,
    ColumnExpr,
    DerivedWindowSpec,
    EdgeType,
    ExprReturnType,
    FactorPaneSpec,
    LogicalOperator,
    OpKind,
    Program,
    SlidingAggregatorSpec,
    TumblingAggregatorSpec,
)

# the bin-mergeable aggregate set (exactly what ops/keyed_bins maintains)
MERGEABLE = frozenset({AggKind.COUNT, AggKind.SUM, AggKind.MIN,
                       AggKind.MAX, AggKind.AVG})

# the factor operator's per-pane row-mass column: COUNT(*) over the pane,
# read from the counts plane (no extra transfer channel) and used by the
# derived ring as the per-cell row count so COUNT(*) members stay exact
ROWS_COLUMN = "__f_rows"

_MEMBER_KINDS = (OpKind.SLIDING_WINDOW_AGGREGATOR,
                 OpKind.TUMBLING_WINDOW_AGGREGATOR)


def factor_windows_enabled() -> bool:
    """``ARROYO_FACTOR_WINDOWS=0`` is the full escape hatch (read per
    call so tests/smoke can toggle without a config reset; ``auto`` and
    ``1`` both mean cost-model-decided sharing)."""
    return os.environ.get("ARROYO_FACTOR_WINDOWS", "auto") not in (
        "0", "off", "false")


def max_pane_ratio() -> int:
    """Largest acceptable ``min(slide) / pane`` blow-up before sharing
    loses to per-query panes (``ARROYO_FACTOR_MAX_RATIO``)."""
    return int(os.environ.get("ARROYO_FACTOR_MAX_RATIO", 64))


@dataclass
class FactorDecision:
    """One cost-model evaluation over a correlated-window group."""

    upstream: str  # the anchor node the shared input hangs off
    members: List[str]
    pane_micros: int
    shared: bool
    reason: str  # 'shared' | refusal cause
    inputs: Dict[str, object] = field(default_factory=dict)
    factor_node: Optional[str] = None  # set once the rewrite applied

    def to_json(self) -> Dict[str, object]:
        return {
            "upstream": self.upstream, "members": list(self.members),
            "pane_micros": self.pane_micros, "shared": self.shared,
            "reason": self.reason, "inputs": dict(self.inputs),
            "factor_node": self.factor_node,
        }


@dataclass
class _Candidate:
    """One eligible member plus its (possibly private) input tail."""

    member: str
    anchor: str  # node the shared factor input will hang off
    tail: Tuple[str, ...]  # private nodes anchor -> member, removed on rewrite
    key_schema: str  # member in-edge key schema
    key_token: str  # structural identity of the keying (groups members)
    rename: Dict[str, str] = field(default_factory=dict)  # agg col renames


def _member_params(spec) -> Tuple[int, int]:
    """(width, slide) micros of a member aggregator spec."""
    if isinstance(spec, TumblingAggregatorSpec):
        return spec.width_micros, spec.width_micros
    return spec.width_micros, spec.slide_micros


def _aggin_parts(sql: str) -> Optional[Tuple[str, List[str]]]:
    """Split an ``aggin:`` structural token into (key-exprs part, list of
    canonical aggregate tokens) — None when not an aggin token."""
    if not sql.startswith("aggin:") or "|" not in sql:
        return None
    keys_part, aggs_part = sql[len("aggin:"):].split("|", 1)
    try:
        import ast

        toks = ast.literal_eval(aggs_part)
    except (ValueError, SyntaxError):
        return None
    if not isinstance(toks, list):
        return None
    return keys_part, [str(t) for t in toks]


def _shared_input_name(fc_token: str) -> str:
    """Deterministic shared name for a member aggregate's input column,
    keyed by the planner's canonical FunctionCall token.  Two queries
    aggregating the same expression map to ONE column (and so one
    factor partial).  AVG and SUM normalize together: their input
    computations are identical (0.0-filled operand)."""
    t = fc_token.replace("FunctionCall(name='avg'",
                         "FunctionCall(name='sum'", 1)
    return "__fin_" + hashlib.sha1(t.encode()).hexdigest()[:10]


def _candidate(program: Program, op_id: str) -> Optional[_Candidate]:
    """Build the member's candidate record, walking up through a
    private [agg_input projection ->] key_by tail when present."""
    g = program.graph
    node = program.node(op_id)
    if node.operator.kind not in _MEMBER_KINDS:
        return None
    spec = node.operator.spec
    if getattr(spec, "argmax_local", None) is not None:
        return None  # emission is coupled to a WindowArgmax consumer
    width, slide = _member_params(spec)
    if width <= 0 or slide <= 0 or width % slide != 0:
        return None
    for a in spec.aggs:
        if a.kind not in MERGEABLE or a.fn is not None:
            return None  # not bin-mergeable (UDAF/VEC/COUNT_DISTINCT)
        if a.output.startswith("__f"):
            return None  # would collide with factor partial naming
    in_edges = list(g.in_edges(op_id, data=True))
    if len(in_edges) != 1:
        return None
    src, _, data = in_edges[0]
    edge = data["edge"]
    if edge.typ is not EdgeType.SHUFFLE:
        return None  # join sides / forwards never qualify

    up = program.node(src)
    if not (up.operator.kind is OpKind.KEY_BY and g.out_degree(src) == 1
            and g.in_degree(src) == 1):
        # direct shape: members share this upstream node (whatever it is)
        return _Candidate(op_id, src, (), edge.key_schema,
                          f"node:{src}:{edge.key_schema}")
    kb_src, _, kb_data = next(iter(g.in_edges(src, data=True)))
    if kb_data["edge"].typ is not EdgeType.FORWARD:
        return _Candidate(op_id, src, (), edge.key_schema,
                          f"node:{src}:{edge.key_schema}")
    proj = program.node(kb_src)
    parts = (_aggin_parts(proj.operator.expr.sql)
             if proj.operator.kind in (OpKind.EXPRESSION, OpKind.UDF)
             and proj.operator.expr is not None else None)
    if (parts is not None and g.out_degree(kb_src) == 1
            and g.in_degree(kb_src) == 1
            and proj.operator.expr.return_type is ExprReturnType.RECORD):
        anchor = next(iter(g.predecessors(kb_src)))
        # member aggregate inputs rename to token-keyed shared names so
        # per-query __ain indices can never collide across members
        rename: Dict[str, str] = {}
        for j, a in enumerate(spec.aggs):
            if a.column is not None and j < len(parts[1]):
                rename[a.column] = _shared_input_name(parts[1][j])
        if any(a.column is not None and a.column not in rename
               for a in spec.aggs):
            # aggregate inputs not traceable to aggin tokens (renames
            # would be unsound): fall back to requiring a shared node
            return _Candidate(op_id, src, (), edge.key_schema,
                              f"node:{src}:{edge.key_schema}")
        return _Candidate(op_id, anchor, (kb_src, src), edge.key_schema,
                          f"aggin:{parts[0]}", rename)
    # private key_by without a recognizable projection: members sharing
    # the key_by's own upstream and key columns can still group
    anchor = kb_src
    return _Candidate(op_id, anchor, (src,), edge.key_schema,
                      f"keyby:{up.operator.key_cols}:{edge.key_schema}")


def plan_factor_windows(program: Program) -> List[FactorDecision]:
    """Pure analysis: group correlated members and run the cost model.
    Returns every evaluated decision (shared AND refused) so the
    bench/console can explain why a plan did or did not factor.  Empty
    when the pass is disabled."""
    return [d for d, _ in _plan(program)]


def _plan(program: Program) -> List[Tuple[FactorDecision,
                                          List[_Candidate]]]:
    if not factor_windows_enabled():
        return []
    groups: Dict[Tuple, List[_Candidate]] = {}
    for op_id in program.topo_order():
        cand = _candidate(program, op_id)
        if cand is None:
            continue
        node = program.node(op_id)
        sig = (cand.anchor, len(cand.tail), cand.key_token,
               node.parallelism, node.max_parallelism)
        groups.setdefault(sig, []).append(cand)

    out: List[Tuple[FactorDecision, List[_Candidate]]] = []
    for (anchor, _tl, key_token, par, _mp), cands in groups.items():
        if len(cands) < 2:
            continue  # nothing to share
        members = [c.member for c in cands]
        params = [_member_params(program.node(m).operator.spec)
                  for m in members]
        widths = [w for w, _ in params]
        slides = [s for _, s in params]
        g = math.gcd(*(widths + slides))
        ratio = min(slides) // max(g, 1)
        inputs = {"k": len(members), "widths_micros": widths,
                  "slides_micros": slides, "pane_micros": g,
                  "pane_ratio": ratio,
                  "max_pane_ratio": max_pane_ratio(),
                  "key_token": key_token, "parallelism": par}
        if ratio > max_pane_ratio():
            # pathological gcd (e.g. coprime slides -> 1 us panes): the
            # factor ring would fire `ratio`x more often than the finest
            # member — per-pane overhead swamps the saved updates
            out.append((FactorDecision(
                anchor, members, g, False, "pane_ratio_exceeded",
                inputs), cands))
            continue
        out.append((FactorDecision(anchor, members, g, True, "shared",
                                   inputs), cands))
    return out


def factor_aggs_for(member_aggs: List[Tuple[AggSpec, ...]]
                    ) -> Tuple[AggSpec, ...]:
    """The factor operator's aggregate set: the DEDUPLICATED union of
    the members' decomposed per-pane partials.  Two members aggregating
    the same column share one partial channel — the sharing the rewrite
    exists to exploit.

    Per member aggregate:
      COUNT(*)        -> the row-mass COUNT(*) partial (always present)
      SUM(c)/AVG(c)   -> __f_sum_<c> (pane partial sum)
      MIN(c)/MAX(c)   -> __f_min_<c> / __f_max_<c>
      any column read -> __f_cnt_<c> (pane non-null count: COUNT(c)'s
                         value AND every null-skipping agg's validity)
    """
    out: Dict[str, AggSpec] = {
        ROWS_COLUMN: AggSpec(AggKind.COUNT, None, ROWS_COLUMN)}
    for aggs in member_aggs:
        for a in aggs:
            if a.column is None:
                continue  # COUNT(*): carried by ROWS_COLUMN
            c = a.column
            if a.kind in (AggKind.SUM, AggKind.AVG):
                out.setdefault(f"__f_sum_{c}",
                               AggSpec(AggKind.SUM, c, f"__f_sum_{c}"))
            elif a.kind == AggKind.MIN:
                out.setdefault(f"__f_min_{c}",
                               AggSpec(AggKind.MIN, c, f"__f_min_{c}"))
            elif a.kind == AggKind.MAX:
                out.setdefault(f"__f_max_{c}",
                               AggSpec(AggKind.MAX, c, f"__f_max_{c}"))
            out.setdefault(f"__f_cnt_{c}",
                           AggSpec(AggKind.COUNT, c, f"__f_cnt_{c}"))
    return tuple(out.values())


def partial_column(a: AggSpec) -> str:
    """The factor partial column a member aggregate's VISIBLE channel
    reads in merge-input mode."""
    if a.column is None:
        return ROWS_COLUMN  # COUNT(*): the per-pane row mass
    if a.kind in (AggKind.SUM, AggKind.AVG):
        return f"__f_sum_{a.column}"
    if a.kind == AggKind.MIN:
        return f"__f_min_{a.column}"
    if a.kind == AggKind.MAX:
        return f"__f_max_{a.column}"
    return f"__f_cnt_{a.column}"  # COUNT(c)


def derived_channel_cols(aggs: Tuple[AggSpec, ...]) -> Dict[int, str]:
    """Channel index -> factor partial column for a derived ring whose
    channel layout is ``build_channels(aggs)`` (the member's own layout,
    so checkpoints stay interchangeable with unfactored plans).  Hidden
    validity channels read the column's non-null-count partial."""
    from ..ops.keyed_bins import build_channels

    _, valid_ch = build_channels(aggs)
    cols: Dict[int, str] = {}
    for i, a in enumerate(aggs):
        cols[i] = partial_column(a)
    for src, j in valid_ch.items():
        cols[j] = f"__f_cnt_{aggs[src].column}"
    return cols


def _union_projection(program: Program,
                      cands: List[_Candidate]) -> Tuple[LogicalOperator,
                                                        OpKind]:
    """ONE projection node running every member's private ``agg_input``
    fn over the shared anchor batch, renaming each member's ``__ain*``
    outputs to their token-keyed shared names.  Key columns are
    structurally identical across members (grouping requires equal
    ``aggin`` key tokens), so first-writer-wins merging is sound."""
    plans: List[Tuple[Callable, Dict[str, str]]] = []
    kinds: List[OpKind] = []
    used: Optional[set] = set()
    for c in cands:
        proj = program.node(c.tail[0]).operator
        plans.append((proj.expr.fn, dict(c.rename)))
        kinds.append(proj.kind)
        u = getattr(proj.expr.fn, "used_cols", None)
        if used is not None and u is not None:
            used |= set(u)
        else:
            used = None

    def union_fn(cols, _plans=tuple(plans)):
        out: Dict[str, Any] = {}
        for fn, ren in _plans:
            o = dict(fn(cols))
            o.pop("__timestamp", None)  # aggin projections never set it
            for k, v in o.items():
                out.setdefault(ren.get(k, k), v)
        return out

    if used is not None:
        union_fn.used_cols = frozenset(used)
    sqls = sorted(program.node(c.tail[0]).operator.expr.sql for c in cands)
    expr = ColumnExpr("factor_input", union_fn, ExprReturnType.RECORD,
                      sql="aggin-union:" + repr(sqls))
    kind = OpKind.UDF if OpKind.UDF in kinds else OpKind.EXPRESSION
    return LogicalOperator(kind, "factor_input", expr=expr), kind


def apply_factor_windows(program: Program) -> List[FactorDecision]:
    """Run the cost model and rewrite every shared group in place: ONE
    new WINDOW_FACTOR node per group (fed through the group's shared —
    possibly newly synthesized — projection/keying) and each member
    node swapped — same operator id, same out-edges — to a
    DERIVED_WINDOW consuming the factor's panes over a FORWARD edge
    (1:1 subtask pairing preserves co-partitioning, so derived
    consumers read pre-partitioned pane arrays with zero reshards).
    Idempotent: already-rewritten plans have no eligible member groups.
    Records the decisions on ``program.factor_decisions``."""
    planned = _plan(program)
    decisions = [d for d, _ in planned]
    for d, cands in planned:
        if not d.shared:
            continue
        members = [program.node(c.member) for c in cands]
        par = members[0].parallelism
        mp = members[0].max_parallelism
        key_schema = cands[0].key_schema

        # shared input chain up to the factor's SHUFFLE edge
        tail_len = len(cands[0].tail)
        if tail_len == 0:
            feed = d.upstream  # members already shared this node
        elif tail_len == 1:
            # private key_bys off a common anchor: ONE key_by suffices
            kb_old = program.node(cands[0].tail[0]).operator
            kb = program.add_node(
                LogicalOperator(OpKind.KEY_BY, kb_old.name,
                                key_cols=kb_old.key_cols), par)
            program.node(kb).max_parallelism = mp
            program.add_edge(d.upstream, kb, EdgeType.FORWARD)
            feed = kb
        else:
            # private [agg_input -> key_by] tails: union projection +
            # one key_by replace them
            proj_op, _k = _union_projection(program, cands)
            anchor_edge = program.edge(d.upstream, cands[0].tail[0])
            proj = program.add_node(proj_op, par)
            program.node(proj).max_parallelism = mp
            program.add_edge(d.upstream, proj, EdgeType.FORWARD,
                             key_schema=anchor_edge.key_schema)
            kb_old = program.node(cands[0].tail[1]).operator
            kb = program.add_node(
                LogicalOperator(OpKind.KEY_BY, kb_old.name,
                                key_cols=kb_old.key_cols), par)
            program.node(kb).max_parallelism = mp
            program.add_edge(proj, kb, EdgeType.FORWARD)
            feed = kb

        f_aggs = factor_aggs_for(
            [tuple(AggSpec(a.kind, c.rename.get(a.column, a.column)
                           if a.column is not None else None, a.output)
                   for a in program.node(c.member).operator.spec.aggs)
             for c in cands])
        f_op = LogicalOperator(
            OpKind.WINDOW_FACTOR, f"factor_panes_{d.pane_micros}us",
            spec=FactorPaneSpec(d.pane_micros, f_aggs))
        fid = program.add_node(f_op, par)
        program.node(fid).max_parallelism = mp
        program.add_edge(feed, fid, EdgeType.SHUFFLE,
                         key_schema=key_schema)
        d.factor_node = fid

        for c in cands:
            m = program.node(c.member)
            spec = m.operator.spec
            width, slide = _member_params(spec)
            aggs = tuple(
                AggSpec(a.kind,
                        c.rename.get(a.column, a.column)
                        if a.column is not None else None,
                        a.output)
                for a in spec.aggs)
            m.operator = LogicalOperator(
                OpKind.DERIVED_WINDOW, m.operator.name,
                spec=DerivedWindowSpec(width, slide, d.pane_micros,
                                       aggs, spec.projection))
            # drop the member's private tail (and with it the old
            # upstream edge), then feed it the factor's panes 1:1
            for t in c.tail:
                program.graph.remove_node(t)
            if program.graph.has_edge(d.upstream, c.member):
                program.graph.remove_edge(d.upstream, c.member)
            program.add_edge(fid, c.member, EdgeType.FORWARD,
                             key_schema=key_schema)
    # idempotent re-application (Engine.__init__ after the planner)
    # re-finds refused groups but NOT already-rewritten shared ones —
    # keep the prior shared records (their factor nodes are in the
    # graph) so the decision log consumers read (bench factor objects,
    # console) survives re-planning; refused groups re-evaluate fresh
    kept = [d for d in getattr(program, "factor_decisions", []) or []
            if d.shared and d.factor_node is not None
            and program.graph.has_node(d.factor_node)]
    decisions = kept + decisions
    program.factor_decisions = decisions  # type: ignore[attr-defined]
    return decisions


def factor_groups(program: Program) -> Dict[str, List[str]]:
    """{factor node id -> derived member ids} over an already-rewritten
    program (rescale-path awareness; empty when nothing factored)."""
    out: Dict[str, List[str]] = {}
    for op_id in program.graph.nodes:
        if program.node(op_id).operator.kind is OpKind.WINDOW_FACTOR:
            out[op_id] = [
                dst for _, dst in program.graph.out_edges(op_id)
                if program.node(dst).operator.kind is OpKind.DERIVED_WINDOW]
    return out


def expand_overrides(program: Program,
                     overrides: Dict[str, int]) -> Dict[str, int]:
    """A factor group is a unit of parallelism: the factor -> derived
    edges are FORWARD (1:1 subtask pairing carries the co-partitioning),
    so a parallelism override addressed to any group member must apply
    to the whole group or the rebalanced edge would break keyed
    routing.  Same contract as ``chaining.expand_overrides``; the
    larger target wins, capped at the group's smallest max_parallelism."""
    groups = factor_groups(program)
    if not groups:
        return dict(overrides)
    member_of: Dict[str, List[str]] = {}
    for fid, derived in groups.items():
        full = [fid] + derived
        for m in full:
            member_of[m] = full
    out: Dict[str, int] = {}
    for op_id, p in overrides.items():
        group = member_of.get(op_id)
        if group is None:
            out[op_id] = max(out.get(op_id, 0), p)
            continue
        caps = [program.node(m).max_parallelism for m in group
                if program.node(m).max_parallelism is not None]
        target = min([p] + caps)
        for m in group:
            out[m] = max(out.get(m, 0), target)
    return out
