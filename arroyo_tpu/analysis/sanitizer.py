"""arroyosan runtime half: streaming-invariant sanitizer.

TSAN/UBSAN analogue for the asyncio runtime — ``ARROYO_SANITIZE=1``
arms invariant assertions at the runtime's protocol choke points:

- **watermark monotonicity** per input edge: an event-time watermark
  must never regress behind the previous one on the same edge;
- **barrier alignment**: no data batch crosses a partially-aligned
  barrier — once an input delivered its barrier for an epoch, records
  from that input must park until alignment completes;
- **snapshot/upload atomicity**: no state-table mutation between the
  checkpoint snapshot and its persistence (a mutation there ships a
  torn epoch);
- **coalescer flush-before-control**: buffered record fragments must be
  flushed before any watermark/barrier/end is handled (PR 4's ordering
  contract);
- **per-edge batch schema stability**: the column layout of record
  batches on one edge must stay stable (a silent layout change
  corrupts the data-plane continuation-frame cache and coalescer);
- **per-edge sharding stability**: an operator's OUTPUT sharding spec
  on one shuffle edge must not flip mid-stream (device all_to_all one
  batch, host route the next) — the resharding analogue of the
  column-layout check: a flip means downstream consumers alternate
  between pre-partitioned device arrays and host-routed rows, which
  silently re-stages state every flip;
- **checkpoint completeness**: each epoch sees exactly one completion
  per distinct (member operator, subtask) — a duplicate means two
  snapshots raced for the same slot.

A violation raises :class:`SanitizerError` carrying a ring of the most
recent protocol events (and the obs/tracing span tail), so the triage
starts from the interleaving that broke the invariant rather than a
bare assert.

Zero steady-state cost when off: every instrumented site holds a local
that is ``None`` unless ``ARROYO_SANITIZE`` was set when the engine was
built, so the disabled path is a single ``is not None`` test (the same
pattern the optional metrics already use).
"""

from __future__ import annotations

import os
import threading
import time as _time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "SanitizerError",
    "Sanitizer",
    "sanitize_enabled",
    "maybe_sanitizer",
    "recent_events",
]

_RING_CAP = int(os.environ.get("ARROYO_SANITIZE_RING", "256"))

# one process-wide event ring (like obs.tracing's span ring): events are
# cheap tuples, and violations in one engine may need events from a
# peer (controller vs worker paths share the process in local mode)
_ring_lock = threading.Lock()
_ring: deque = deque(maxlen=_RING_CAP)


def sanitize_enabled() -> bool:
    """``ARROYO_SANITIZE=1`` arms the sanitizer (read per engine build,
    not at import, so tests and bench can toggle per run)."""
    return os.environ.get("ARROYO_SANITIZE", "0") not in ("0", "off",
                                                          "false", "")


def maybe_sanitizer(scope: str = "job") -> Optional["Sanitizer"]:
    """The instrumentation sites' constructor: a live Sanitizer when
    armed, else ``None`` (the hot paths guard on ``is not None``)."""
    return Sanitizer(scope) if sanitize_enabled() else None


def recent_events(limit: int = 64) -> List[tuple]:
    """Tail of the process-wide sanitizer event ring, oldest first."""
    with _ring_lock:
        out = list(_ring)
    return out[-limit:]


def _reset_ring() -> None:
    """Test hook: clear the shared ring between fixtures."""
    with _ring_lock:
        _ring.clear()


class SanitizerError(AssertionError):
    """A streaming invariant was violated at runtime.

    ``code`` names the invariant; ``events`` is the tail of the
    sanitizer event ring at violation time (oldest first) — the recent
    protocol interleaving that led here."""

    def __init__(self, code: str, message: str,
                 events: Optional[List[tuple]] = None):
        self.code = code
        self.events = events or []
        tail = "\n".join(
            f"  {ts:.6f} {kind:<12} {task} {detail}"
            for ts, kind, task, detail in self.events[-16:])
        super().__init__(
            f"arroyosan[{code}]: {message}\n"
            f"recent events (oldest first):\n{tail or '  (none)'}")


class Sanitizer:
    """Per-engine-run invariant state.  All hooks are cheap dict/tuple
    operations; none dispatches to a device or takes an await point."""

    def __init__(self, scope: str = "job"):
        self.scope = scope
        # (edge key) -> last event-time watermark micros
        self._edge_wm: Dict[Any, int] = {}
        # (edge key) -> (column names, key_cols, has key_hash)
        self._edge_schema: Dict[Any, Tuple] = {}
        # (edge key) -> output sharding spec string ("keys@n" / "host@n")
        self._edge_sharding: Dict[Any, str] = {}
        # epoch -> {(operator_id, subtask)} completions seen; epochs far
        # behind the newest are pruned (they can never recur within one
        # run — the controller's per-epoch trackers are bounded the same
        # way), so a years-long sanitized job doesn't leak memory
        self._completed: Dict[int, set] = {}
        self.violations = 0

    # -- event ring --------------------------------------------------------

    def event(self, kind: str, task: str, detail: Any = "") -> None:
        with _ring_lock:
            _ring.append((_time.monotonic(), kind, task, detail))

    def violation(self, code: str, message: str) -> None:
        self.violations += 1
        err = SanitizerError(code, message, recent_events())
        try:
            from ..obs import tracing

            tracing.instant("sanitizer.violation", "sanitizer",
                            args={"code": code, "scope": self.scope})
        except Exception:
            pass
        raise err

    # -- invariant hooks ---------------------------------------------------

    def on_watermark(self, edge: Any, wm: Any) -> None:
        """Per-edge watermark monotonicity (event-time only: Idle
        carries no time and a later event-time watermark may follow)."""
        if getattr(wm, "is_idle", False):
            self.event("wm-idle", str(edge))
            return
        t = int(wm.time)
        prev = self._edge_wm.get(edge)
        self.event("watermark", str(edge), t)
        if prev is not None and t < prev:
            self.violation(
                "watermark-regression",
                f"edge {edge}: watermark went backwards "
                f"({prev} -> {t}, delta {t - prev}us)")
        self._edge_wm[edge] = t

    def reset_edge(self, edge: Any) -> None:
        """Forget an edge's schema tracker — called at a *declared*
        schema change point (the data plane's full KIND_DATA frame
        mid-stream), so the next batch re-seeds stability tracking
        instead of raising."""
        self.event("schema-reset", str(edge))
        self._edge_schema.pop(edge, None)

    def on_record(self, edge: Any, batch: Any) -> None:
        """Per-edge batch schema stability: column names / key layout
        must not drift mid-stream (dtypes may promote — numpy concat
        semantics — but a column appearing or vanishing is corruption)."""
        sig = (tuple(batch.columns.keys()), tuple(batch.key_cols),
               batch.key_hash is not None)
        prev = self._edge_schema.get(edge)
        if prev is None:
            self._edge_schema[edge] = sig
            self.event("schema", str(edge), list(sig[0]))
            return
        if prev != sig:
            self.event("schema", str(edge), list(sig[0]))
            self.violation(
                "schema-instability",
                f"edge {edge}: batch layout changed mid-stream "
                f"{prev} -> {sig}")

    def on_sharding(self, edge: Any, spec: str) -> None:
        """Per-edge output sharding stability: the routing decision for
        one shuffle edge (on-device ``all_to_all`` vs host partition)
        must be made once and hold for the stream's life.  The device
        path is sticky-by-construction (``DeviceShuffle`` falls back
        permanently on the first unsupported batch); a flip reaching
        here means the stickiness broke — the resharding analogue of a
        mid-stream column-layout change."""
        prev = self._edge_sharding.get(edge)
        if prev is None:
            self._edge_sharding[edge] = spec
            self.event("sharding", str(edge), spec)
            return
        if prev != spec:
            self.event("sharding", str(edge), spec)
            self.violation(
                "sharding-instability",
                f"edge {edge}: output sharding spec flipped mid-stream "
                f"({prev} -> {spec})")

    def on_record_during_alignment(self, task: str, input_idx: int,
                                   counter: Any) -> None:
        """No data batch crosses a partially-aligned barrier: if input
        ``input_idx`` already delivered its barrier for a pending epoch,
        a record from it must not reach the operator until the barrier
        aligns (the pump should have parked the channel)."""
        for epoch, seen in getattr(counter, "seen", {}).items():
            if input_idx in seen:
                self.violation(
                    "barrier-crossing",
                    f"task {task}: record from input {input_idx} "
                    f"crossed its own barrier for epoch {epoch} "
                    "(partially-aligned barrier overtaken by data)")

    def on_barrier(self, task: str, input_idx: int, epoch: int) -> None:
        self.event("barrier", task, {"input": input_idx, "epoch": epoch})

    def before_control(self, task: str, kind: str,
                       coalescer: Any = None) -> None:
        """Coalescer flush-before-control: at the moment a watermark/
        barrier/end is handled, no record fragment may still sit in the
        input coalescer (it would be reordered past the control event)."""
        self.event("control", task, kind)
        if coalescer is not None and getattr(coalescer, "pending", False):
            self.violation(
                "coalesce-unflushed",
                f"task {task}: {kind} handled while the input coalescer "
                "still buffers record fragments (flush-before-control "
                "ordering broken)")

    def on_checkpoint_completed(self, operator_id: str, subtask: int,
                                epoch: int) -> None:
        """Checkpoint completeness: one completion per distinct
        (member, subtask) per epoch."""
        key = (operator_id, subtask)
        self.event("ckpt-done", f"{operator_id}-{subtask}",
                   {"epoch": epoch})
        if key in self._completed.get(epoch, ()):
            self.violation(
                "duplicate-checkpoint",
                f"{operator_id}-{subtask} reported checkpoint epoch "
                f"{epoch} twice (two snapshots raced for one slot)")
        self._completed.setdefault(epoch, set()).add(key)
        # epochs strictly increase within one run: anything far behind
        # the newest can never legitimately complete again
        for e in [e for e in self._completed if e < epoch - 16]:
            del self._completed[e]

    # -- snapshot/upload atomicity ----------------------------------------

    @staticmethod
    def _table_fingerprint(tables: Dict[str, Any]) -> Dict[str, int]:
        """Cheap per-table size token.  Device tables are skipped — their
        snapshot is the device_get itself and sizing them would add a
        host sync to every checkpoint."""
        fp: Dict[str, int] = {}
        for name, table in tables.items():
            try:
                if hasattr(table, "n_keys"):
                    fp[name] = int(table.n_keys())
                elif hasattr(table, "__len__"):
                    fp[name] = len(table)
            except (TypeError, ValueError):
                continue
        return fp

    def checkpoint_begin(self, task: str,
                         tables: Dict[str, Any]) -> Dict[str, int]:
        self.event("ckpt-snap", task, {"tables": sorted(tables)})
        return self._table_fingerprint(tables)

    def checkpoint_end(self, task: str, tables: Dict[str, Any],
                       before: Dict[str, int]) -> None:
        after = self._table_fingerprint(tables)
        if after != before:
            changed = sorted(k for k in set(before) | set(after)
                             if before.get(k) != after.get(k))
            self.violation(
                "mutation-during-checkpoint",
                f"task {task}: state tables {changed} mutated between "
                "snapshot and upload (the persisted epoch is torn)")
