"""recompile_hazard: jit cache-key hazards in the kernel layers.

Scope: ``ops/*.py`` and ``parallel/*.py`` — every jitted step the hot
path dispatches.  The engine's throughput story rests on kernels
compiling ONCE per shape signature (`functools.lru_cache`-wrapped step
factories with `@jax.jit` inside, static args bucketed to powers of
two); a single site that rebuilds its jit per dispatch, passes a
per-batch-varying value as a cache key, or branches on a traced shape
silently turns the steady state into a compile storm that only shows
up as mysterious wall time (the profiler's `dispatch` phase inflating
was historically how these were found — this pass catches them before
they run).

Codes:

- ``jit-rebuild`` — a ``jax.jit`` / ``shard_map`` / ``pallas_call``
  created inside a function that is neither ``functools.lru_cache``/
  ``cache``-wrapped nor stores the result in a cache (subscript
  assignment, e.g. ``self._jitted[key] = f``): the closure is rebuilt
  per call, so every dispatch pays a fresh trace+compile.
- ``unhashable-static`` — a call to a same-file ``lru_cache``-wrapped
  factory passing a list/dict/set literal: TypeError at runtime, and
  even tuple-fixed it would be a per-call-varying cache key.
- ``varying-static`` — a cached factory called with a bare
  ``len(...)`` / ``x.shape[...]`` argument: per-batch-varying static
  arg, one compile per batch size.  Bucket it first
  (``_bucket``/power-of-two padding) like every existing caller.
- ``shape-branch`` — Python ``if``/``while`` on a traced parameter's
  ``.shape``/``len()`` inside a jit-compiled function: either a
  TracerBoolConversionError or a retrace per shape, depending on how
  the value flows.  Branch on closure statics instead.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from .core import Finding, call_name

PASS_ID = "recompile-hazard"

_SCOPE_RE = re.compile(r"(^|/)(ops|parallel)/[^/]+\.py$")

_JIT_MAKERS = {"jax.jit", "jit", "shard_map", "jax.experimental."
               "shard_map.shard_map", "pl.pallas_call", "pallas_call"}
_CACHE_DECOS = {"functools.lru_cache", "lru_cache", "functools.cache",
                "cache"}


def in_scope(path: str) -> bool:
    return bool(_SCOPE_RE.search(path.replace("\\", "/")))


def _deco_name(d: ast.expr) -> str:
    if isinstance(d, ast.Call):
        d = d.func
    if isinstance(d, (ast.Name, ast.Attribute)):
        parts = []
        cur = d
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if isinstance(cur, ast.Name):
            parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


def _is_cached_fn(node) -> bool:
    return any(_deco_name(d) in _CACHE_DECOS
               for d in getattr(node, "decorator_list", ()))


def _has_cache_store(fn_node) -> bool:
    """A ``cache[key] = value`` / ``self._x[key] = f`` assignment inside
    the function body — the memoized-builder pattern (CompiledExpr's
    per-schema jit cache) that makes an inline jit build legitimate."""
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Assign):
            if any(isinstance(t, ast.Subscript) for t in sub.targets):
                return True
        if isinstance(sub, ast.Call) and \
                call_name(sub).endswith(".setdefault"):
            return True
    return False


def _jit_param_names(tree: ast.AST) -> Set[str]:
    """Parameter names of every function that is jit-compiled in this
    file: decorated ``@jax.jit`` or passed (by name) to a jit maker."""
    jitted_defs: Set[str] = set()
    defs = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
            if any(_deco_name(d) in _JIT_MAKERS
                   for d in node.decorator_list):
                jitted_defs.add(node.name)
        if isinstance(node, ast.Call) and call_name(node) in _JIT_MAKERS:
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(a, ast.Name):
                    jitted_defs.add(a.id)
    params: Set[str] = set()
    for name in jitted_defs:
        fn = defs.get(name)
        if fn is None:
            continue
        for a in (fn.args.posonlyargs + fn.args.args
                  + fn.args.kwonlyargs):
            params.add((name, a.arg))
    return params


class _Scan(ast.NodeVisitor):
    def __init__(self, path: str, cached_factories: Set[str],
                 jit_params: Set):
        self.path = path
        self.cached_factories = cached_factories
        self.jit_params = jit_params
        self.findings: List[Finding] = []
        self.fn_stack: List = []

    def _flag(self, node, code: str, msg: str) -> None:
        self.findings.append(
            Finding(PASS_ID, code, self.path, node.lineno, msg))

    # ---- enclosing-function bookkeeping ------------------------------

    def _visit_fn(self, node) -> None:
        self.fn_stack.append(node)
        for d in node.decorator_list:
            if _deco_name(d) in _JIT_MAKERS:
                self._check_rebuild(node, f"@{_deco_name(d)} def "
                                          f"{node.name}")
        self.generic_visit(node)
        self.fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def _check_rebuild(self, node, what: str) -> None:
        """``node`` creates a jitted callable; the function frames it is
        nested in must include a cache (lru_cache deco or cache-store
        body) — module level is fine (built once at import)."""
        # the frame the jit build runs in is the INNERMOST enclosing
        # function that is not the jitted def itself
        frames = [f for f in self.fn_stack if f is not node]
        if not frames:
            return  # module level: built once at import
        if any(_is_cached_fn(f) for f in frames):
            return
        if any(_has_cache_store(f) for f in frames):
            return
        self._flag(node, "jit-rebuild",
                   f"{what} is built inside "
                   f"{frames[-1].name}(), which neither memoizes "
                   "(functools.lru_cache) nor stores the result in a "
                   "cache — the closure recompiles on every call; hot "
                   "paths must build jitted steps once per shape "
                   "signature")

    # ---- calls -------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        name = call_name(node)
        if name in _JIT_MAKERS:
            self._check_rebuild(node, f"{name}(...)")
        base = name.split(".")[-1]
        if base in self.cached_factories:
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(a, (ast.List, ast.Dict, ast.Set)):
                    self._flag(a, "unhashable-static",
                               f"{base}() is lru_cache-wrapped but is "
                               "passed a list/dict/set literal — "
                               "unhashable cache key (TypeError), and "
                               "mutable statics vary per call; pass a "
                               "tuple of scalars")
                elif isinstance(a, ast.Call) and call_name(a) == "len":
                    self._flag(a, "varying-static",
                               f"{base}() is keyed by a bare len(...) "
                               "— a per-batch-varying static arg "
                               "compiles one kernel per batch size; "
                               "bucket it (_bucket / power-of-two "
                               "padding) like the existing steps")
                elif isinstance(a, ast.Subscript) and \
                        isinstance(a.value, ast.Attribute) and \
                        a.value.attr == "shape":
                    self._flag(a, "varying-static",
                               f"{base}() is keyed by a raw .shape "
                               "element — per-batch-varying static "
                               "arg; bucket it first")
        self.generic_visit(node)

    # ---- shape branches inside jitted bodies -------------------------

    def _check_shape_test(self, node, test: ast.expr) -> None:
        jit_fns = [f for f in self.fn_stack
                   if any((f.name, a.arg) in self.jit_params
                          for a in (f.args.posonlyargs + f.args.args
                                    + f.args.kwonlyargs))]
        if not jit_fns:
            return
        fn = jit_fns[-1]
        pnames = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                  + fn.args.kwonlyargs)
                  if (fn.name, a.arg) in self.jit_params}
        for sub in ast.walk(test):
            hit = None
            if isinstance(sub, ast.Attribute) and sub.attr == "shape" \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id in pnames:
                hit = f"{sub.value.id}.shape"
            elif isinstance(sub, ast.Call) and call_name(sub) == "len" \
                    and sub.args and isinstance(sub.args[0], ast.Name) \
                    and sub.args[0].id in pnames:
                hit = f"len({sub.args[0].id})"
            if hit:
                self._flag(node, "shape-branch",
                           f"Python branch on {hit} inside jitted "
                           f"{fn.name}(): shape-dependent control flow "
                           "re-traces per shape (or raises under "
                           "tracing); branch on closure statics "
                           "instead")
                return

    def visit_If(self, node: ast.If) -> None:
        self._check_shape_test(node, node.test)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_shape_test(node, node.test)
        self.generic_visit(node)


def check(tree: ast.AST, lines, path: str,
          force: bool = False) -> List[Finding]:
    if not force and not in_scope(path):
        return []
    cached = {node.name for node in ast.walk(tree)
              if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
              and _is_cached_fn(node)}
    scan = _Scan(path, cached, _jit_param_names(tree))
    scan.visit(tree)
    return scan.findings
