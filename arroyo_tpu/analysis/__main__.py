"""``python -m arroyo_tpu.analysis`` — run arroyolint over the package.

Exit status: 0 when every finding is waived or baselined, 1 otherwise
(the CI gate contract; tools/lint.sh and tools/smoke.sh call this).

    python -m arroyo_tpu.analysis                 # lint arroyo_tpu/
    python -m arroyo_tpu.analysis path1 path2     # explicit paths
    python -m arroyo_tpu.analysis --format json   # machine-readable
    python -m arroyo_tpu.analysis --all           # show waived too
    python -m arroyo_tpu.analysis --pass ckpt-arity,host-sync
    python -m arroyo_tpu.analysis --write-baseline  # accept current

``--format json`` emits one object with ``findings`` entries carrying
``file``/``line``/``pass``/``code``/``severity``/``message``/
``fingerprint``/``waived``/``baselined`` — the shape CI annotations and
editor integrations consume without scraping the text renderer (the
exit status contract is identical: 0 iff the gate is clean).
"""

from __future__ import annotations

import argparse
import json
import sys

from .core import (
    DEFAULT_BASELINE,
    run_analysis,
    unwaived,
    write_baseline,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m arroyo_tpu.analysis",
        description="arroyolint: streaming-invariant static analysis")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the arroyo_tpu "
                         "package)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: "
                         "tools/arroyolint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current unwaived findings into "
                         "the baseline file")
    ap.add_argument("--pass", dest="passes",
                    help="comma-separated pass ids to run")
    ap.add_argument("--max-baseline", type=int, default=None,
                    help="fail if the baseline file holds more than N "
                         "accepted findings (the adoption ratchet: the "
                         "baseline may only shrink)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as JSON (alias of --format json)")
    ap.add_argument("--format", dest="fmt", choices=("text", "json"),
                    default="text",
                    help="output format; json is the machine-readable "
                         "shape (file/line/pass/code/fingerprint per "
                         "finding) for CI and editors")
    ap.add_argument("--all", action="store_true",
                    help="also print waived/baselined findings")
    args = ap.parse_args(argv)

    passes = ([p.strip() for p in args.passes.split(",") if p.strip()]
              if args.passes else None)
    baseline = None if (args.no_baseline or args.write_baseline) \
        else args.baseline
    findings = run_analysis(args.paths or None, baseline_path=baseline,
                            passes=passes)

    if args.write_baseline:
        n = write_baseline(findings, args.baseline)
        print(f"arroyolint: wrote {n} finding(s) to {args.baseline}")
        return 0

    if args.max_baseline is not None:
        from .core import load_baseline

        n = len(load_baseline(args.baseline))
        if n > args.max_baseline:
            print(f"arroyolint: baseline grew to {n} accepted "
                  f"finding(s) (ratchet allows {args.max_baseline}) — "
                  "fix new findings or waive them inline with a "
                  "reason; the baseline must only shrink")
            return 1

    gate = unwaived(findings)
    shown = findings if args.all else gate
    if args.json or args.fmt == "json":
        print(json.dumps({
            "version": 1,
            "findings": [f.to_json() for f in sorted(
                shown, key=lambda f: (f.rel_path(), f.line))],
            "counts": {
                "total": len(findings), "gate": len(gate),
                "waived": sum(1 for f in findings if f.waived),
                "baselined": sum(1 for f in findings if f.baselined),
            },
            "total": len(findings), "gate": len(gate),  # legacy keys
        }, indent=1))
    else:
        for f in sorted(shown, key=lambda f: (f.rel_path(), f.line)):
            print(f.render())
        n_waived = sum(1 for f in findings if f.waived)
        n_base = sum(1 for f in findings if f.baselined)
        print(f"arroyolint: {len(gate)} finding(s) "
              f"({n_waived} waived, {n_base} baselined)")
    return 1 if gate else 0


if __name__ == "__main__":
    sys.exit(main())
