"""Trace-purity pass: functions handed to ``jax.jit``/``pallas_call``
must be pure.

A jitted function runs its Python body ONCE at trace time; wall-clock
reads, ``random`` draws, and global mutation are silently frozen into
the compiled program (or worse, torn between trace and execution).
Detects jit targets by decorator (``@jax.jit``, ``@jit``,
``@partial(jax.jit, ...)``), by wrapping (``jax.jit(fn)``), and by
kernel position (``pallas_call(kernel, ...)`` / ``pl.pallas_call``),
then flags inside their bodies:

- wall clock: ``time.time/monotonic/perf_counter``, ``now_micros()``
- randomness outside jax: ``random.*``, ``np.random.*``, bound RNG
  draws are invisible statically and stay out of scope
- ``global`` / ``nonlocal`` declarations (mutation at trace time)
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from .core import Finding, call_name

PASS_ID = "trace-purity"

_WALL_CLOCK = {"time.time", "time.monotonic", "time.perf_counter",
               "_time.time", "_time.monotonic", "_time.perf_counter",
               "now_micros", "time.time_ns"}
_JIT_NAMES = {"jax.jit", "jit"}
_PALLAS_NAMES = {"pallas_call", "pl.pallas_call", "jax.experimental"
                 ".pallas.pallas_call"}


def _is_jit_decorator(dec: ast.expr) -> bool:
    if isinstance(dec, ast.Call):
        name = call_name(dec)
        if name in ("partial", "functools.partial") and dec.args:
            inner = dec.args[0]
            return (isinstance(inner, (ast.Name, ast.Attribute))
                    and _expr_name(inner) in _JIT_NAMES)
        return name in _JIT_NAMES
    return _expr_name(dec) in _JIT_NAMES


def _expr_name(node: ast.expr) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _collect_jitted(tree: ast.AST) -> Dict[str, ast.AST]:
    """name -> FunctionDef for every function that is jitted or used as
    a pallas kernel anywhere in the module."""
    defs: Dict[str, ast.AST] = {}
    jitted: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                jitted.add(node.name)
        elif isinstance(node, ast.Call):
            name = call_name(node)
            if name in _JIT_NAMES and node.args \
                    and isinstance(node.args[0], ast.Name):
                jitted.add(node.args[0].id)
            elif name in _PALLAS_NAMES and node.args \
                    and isinstance(node.args[0], ast.Name):
                jitted.add(node.args[0].id)
    return {n: defs[n] for n in jitted if n in defs}


def check(tree: ast.AST, lines, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for fn_name, fn in _collect_jitted(tree).items():
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name in _WALL_CLOCK:
                    findings.append(Finding(
                        PASS_ID, "wall-clock", path, node.lineno,
                        f"jitted {fn_name}() reads the wall clock "
                        f"({name}) — frozen at trace time"))
                elif name.startswith("random.") \
                        or name.startswith("np.random.") \
                        or name.startswith("numpy.random."):
                    findings.append(Finding(
                        PASS_ID, "impure-random", path, node.lineno,
                        f"jitted {fn_name}() draws host randomness "
                        f"({name}) — frozen at trace time; use "
                        "jax.random with an explicit key"))
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                kind = ("global" if isinstance(node, ast.Global)
                        else "nonlocal")
                findings.append(Finding(
                    PASS_ID, "global-mutation", path, node.lineno,
                    f"jitted {fn_name}() declares {kind} "
                    f"{', '.join(node.names)} — mutation happens at "
                    "trace time, not per call"))
    return findings
