"""Per-row Python loop detector for the serde steady state.

Scope: ``connectors/*.py`` and ``formats.py`` — the ingest/egress hot
paths this PR vectorized.  The decode fast path (pyarrow / bulk-array
parse into typed columns) and the vectorized JSON egress (one encoded
cell pass per column + one template substitution per row) only stay
fast if nobody quietly re-introduces a per-row Python loop next to
them; this pass is the ratchet that keeps the host path from silently
regrowing.

Flags, inside steady-state functions:

- ``for``/comprehension iteration over ``range(len(...))`` — the
  classic per-row index loop;
- iteration over a row-carrying name (``rows``, ``payloads``,
  ``lines``, ``recs``, ``records``) — per-payload Python work;
- any loop or comprehension whose body calls ``json.loads`` /
  ``json.dumps`` (or a local alias ``loads``/``dumps``) — a parser or
  encoder invocation per element.

The DESIGNATED legacy row paths are exempt by name: ``deserialize`` /
``serialize`` (the ``ARROYO_FAST_DECODE=0`` escape the parity gates
pin), Debezium envelope unwrapping, Avro's per-value binary codec, and
schema inference — plus the standard checkpoint/restore exemption.
Anything else per-row needs an inline waiver with a reason, exactly
like the host-sync pass.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from .core import Finding, call_name

PASS_ID = "row-loop"

_SCOPE_RE = re.compile(r"(^|/)(connectors/[^/]+\.py|formats\.py)$")
# designated row paths: the legacy serde escape + inherently per-record
# codecs + non-steady-state lifecycle functions
_EXEMPT_FN_RE = re.compile(
    r"(^|_)(de)?serialize$|_unwrap_debezium|_encode_value|_decode_value"
    r"|schema_for_rows|checkpoint|snapshot|restore|on_start|on_close")

_ROWY_NAMES = {"rows", "payloads", "lines", "recs", "records"}
_SERDE_CALLS = {"json.loads", "json.dumps", "loads", "dumps"}


def in_scope(path: str) -> bool:
    return bool(_SCOPE_RE.search(path.replace("\\", "/")))


def _is_range_len(it: ast.expr) -> bool:
    return (isinstance(it, ast.Call) and call_name(it) == "range"
            and len(it.args) == 1 and isinstance(it.args[0], ast.Call)
            and call_name(it.args[0]) == "len")


def _is_rowy(it: ast.expr) -> bool:
    return isinstance(it, ast.Name) and it.id in _ROWY_NAMES


def _serde_call_in(body) -> Optional[str]:
    for node in body if isinstance(body, list) else [body]:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and \
                    call_name(sub) in _SERDE_CALLS:
                return call_name(sub)
    return None


class _Scan(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: List[Finding] = []
        self.fn_stack: List[str] = []

    def _visit_fn(self, node) -> None:
        self.fn_stack.append(node.name)
        self.generic_visit(node)
        self.fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def _exempt(self) -> bool:
        return any(_EXEMPT_FN_RE.search(name) for name in self.fn_stack)

    def _flag(self, node, code: str, msg: str) -> None:
        self.findings.append(
            Finding(PASS_ID, code, self.path, node.lineno, msg))

    def _check_loop(self, node, it: ast.expr, body,
                    elementwise: bool) -> None:
        """``elementwise`` is True for comprehensions, whose body runs
        exactly once per element — a serde call there is per-row by
        construction.  ``for`` statements only flag on the iterable
        itself (a bounded retry loop AROUND one json.loads is not a
        row loop)."""
        if self._exempt():
            return
        if _is_range_len(it):
            self._flag(node, "range-len",
                       "per-row index loop over batch rows in serde "
                       "steady state — use a vectorized column pass")
            return
        if elementwise:
            serde = _serde_call_in(body)
            if serde is not None:
                self._flag(node, "per-row-serde",
                           f"{serde}() per element — parse/encode the "
                           "whole batch in one vectorized pass instead")
                return
        if _is_rowy(it):
            self._flag(node, "per-row",
                       f"per-payload Python loop over '{it.id}' in serde "
                       "steady state — batch the work into one pass")

    def visit_For(self, node: ast.For) -> None:
        self._check_loop(node, node.iter, node.body, elementwise=False)
        self.generic_visit(node)

    def _visit_comp(self, node, elt) -> None:
        for gen in node.generators:
            self._check_loop(node, gen.iter, elt, elementwise=True)
        self.generic_visit(node)

    def visit_ListComp(self, node: ast.ListComp) -> None:
        self._visit_comp(node, node.elt)

    def visit_SetComp(self, node: ast.SetComp) -> None:
        self._visit_comp(node, node.elt)

    def visit_GeneratorExp(self, node: ast.GeneratorExp) -> None:
        self._visit_comp(node, node.elt)

    def visit_DictComp(self, node: ast.DictComp) -> None:
        for gen in node.generators:
            self._check_loop(node, gen.iter, [node.key, node.value],
                             elementwise=True)
        self.generic_visit(node)


def check(tree: ast.AST, lines, path: str,
          force: bool = False) -> List[Finding]:
    if not force and not in_scope(path):
        return []
    scan = _Scan(path)
    scan.visit(tree)
    return scan.findings
