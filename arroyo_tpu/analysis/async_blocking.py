"""Blocking-call-in-async detector.

The engine is one asyncio loop per worker: a single ``time.sleep`` in a
connector's async poll loop stalls every subtask on the worker.  Flags,
inside ``async def`` bodies (nested sync ``def``s excluded — they run
on executors via ``run_in_executor``):

- ``time.sleep(...)`` (any ``<name>.sleep`` where the name binds the
  time module, e.g. ``_time.sleep``)
- ``<fut>.result()`` — blocks the loop when the future is not done
- ``open(...)`` — sync file I/O
- sync HTTP/subprocess: ``urllib.request.urlopen``, ``requests.*``,
  ``subprocess.run/check_call/check_output/call``
- ``socket.socket(...)`` construction (sync socket I/O follows)
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding, call_name

PASS_ID = "async-blocking"

_TIME_MODULE_NAMES = {"time", "_time"}
_SUBPROCESS_BLOCKING = {"subprocess.run", "subprocess.check_call",
                        "subprocess.check_output", "subprocess.call"}


def _flag_for(call: ast.Call) -> tuple:
    """(code, message) when this call blocks, else (None, None)."""
    name = call_name(call)
    if not name:
        return None, None
    parts = name.split(".")
    if len(parts) == 2 and parts[1] == "sleep" \
            and parts[0] in _TIME_MODULE_NAMES:
        return "sleep", (f"{name}() blocks the event loop; use "
                         "await asyncio.sleep()")
    if parts[-1] == "result" and not call.args and not call.keywords:
        return "future-result", (
            ".result() blocks the event loop unless the future is "
            "already done; prefer await")
    if name == "open":
        return "sync-io", ("sync open() in async function; offload "
                           "file I/O via run_in_executor")
    if name in _SUBPROCESS_BLOCKING:
        return "subprocess", (f"{name}() blocks the event loop; use "
                              "asyncio.create_subprocess_exec")
    if name == "urllib.request.urlopen" or name.startswith("requests."):
        return "sync-http", (f"{name}() is sync HTTP inside async "
                             "code; offload via run_in_executor")
    if name == "socket.socket":
        return "sync-socket", ("sync socket in async function; use "
                               "asyncio streams")
    return None, None


class _AsyncScan(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: List[Finding] = []

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._scan_async_body(node)
        # nested async defs inside this one are re-visited by the scan
        # itself; no generic_visit (sync nested defs must stay unscanned)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.generic_visit(node)  # reach async defs nested in sync ones

    def _scan_async_body(self, fn: ast.AsyncFunctionDef) -> None:
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, ast.FunctionDef):
                continue  # sync helper: runs on an executor thread
            if isinstance(node, ast.AsyncFunctionDef):
                self._scan_async_body(node)
                continue
            if isinstance(node, ast.Call):
                code, msg = _flag_for(node)
                if code:
                    self.findings.append(Finding(
                        PASS_ID, code, self.path, node.lineno,
                        f"in async {fn.name}(): {msg}"))
            stack.extend(ast.iter_child_nodes(node))


def check(tree: ast.AST, lines, path: str) -> List[Finding]:
    scan = _AsyncScan(path)
    scan.visit(tree)
    return scan.findings
