"""Plan-time validator: graph-level invariants over logical Programs.

The analog of the checks rustc + the reference's planner enforce before
a pipeline ever runs — here run at pipeline-create time (api/rest.py)
and before compilation (engine/build.py via Engine).  Error-severity
diagnostics reject the plan; warnings surface through the console's
validation endpoint but do not block.

Checks (codes):

- ``cycle``            — the operator graph must be a DAG
- ``dangling-node``    — a non-source node with no inputs computes
                         nothing (the mutated-plan class where an edge
                         was dropped entirely)
- ``dead-end``         — warning: a non-sink node whose output reaches
                         nothing (prune_dead normally removes these)
- ``keyed-not-shuffled`` — an operator with key-partitioned state fed
                         by a FORWARD edge sees only a slice of each
                         key's rows; every in-edge must be a shuffle
                         unless the operator is pinned to one subtask
                         (max_parallelism == 1, e.g. the global TopN
                         merge stage)
- ``join-sides``       — a join needs exactly one LEFT and one RIGHT
                         shuffle-join in-edge
- ``key-schema-mismatch`` — join sides must shuffle on the same key
                         arity or co-partitioning breaks silently
- ``window-no-watermark`` — window operators never fire without an
                         upstream watermark assigner
- ``window-spec``      — non-positive window width/slide/gap
- ``slide-width``      — warning: slide not dividing width falls off
                         the bin-merged fast path
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Dict, List, Optional

if TYPE_CHECKING:  # graph.logical imports networkx only — cheap, but
    from ..graph.logical import Program  # keep import-time layering clean


@dataclass
class PlanDiagnostic:
    code: str
    severity: str  # 'error' | 'warning'
    message: str
    node: Optional[str] = None

    def to_json(self) -> Dict:
        return {"code": self.code, "severity": self.severity,
                "node": self.node, "message": self.message}

    def render(self) -> str:
        where = f" [{self.node}]" if self.node else ""
        return f"{self.severity}: {self.code}{where}: {self.message}"


class PlanValidationError(ValueError):
    def __init__(self, diagnostics: List[PlanDiagnostic]):
        self.diagnostics = diagnostics
        super().__init__("; ".join(d.render() for d in diagnostics))


def _keyed_state_kinds():
    from ..graph.logical import OpKind

    return {
        OpKind.WINDOW, OpKind.SLIDING_WINDOW_AGGREGATOR,
        OpKind.TUMBLING_WINDOW_AGGREGATOR, OpKind.TUMBLING_TOP_N,
        OpKind.SLIDING_AGGREGATING_TOP_N, OpKind.WINDOW_JOIN,
        OpKind.JOIN_WITH_EXPIRATION, OpKind.NON_WINDOW_AGGREGATOR,
        OpKind.COUNT, OpKind.AGGREGATE, OpKind.WINDOW_ARGMAX,
        OpKind.MULTI_WAY_JOIN, OpKind.WINDOW_FACTOR,
        OpKind.DERIVED_WINDOW,
    }


def _key_arity(key_schema: str) -> int:
    ks = (key_schema or "").strip()
    if ks in ("", "()"):
        return 0
    return len([c for c in ks.split(",") if c.strip()])


def validate_program(program: "Program") -> List[PlanDiagnostic]:
    import networkx as nx

    from ..graph.logical import (
        EdgeType,
        OpKind,
        SessionWindow,
        SlidingAggregatingTopNSpec,
        SlidingAggregatorSpec,
        SlidingWindow,
        TumblingAggregatorSpec,
        TumblingWindow,
        WindowSpec,
    )

    diags: List[PlanDiagnostic] = []
    g = program.graph

    if not nx.is_directed_acyclic_graph(g):
        diags.append(PlanDiagnostic(
            "cycle", "error",
            "operator graph contains a cycle; streaming plans must be "
            "DAGs"))
        return diags  # downstream checks assume a DAG

    keyed_kinds = _keyed_state_kinds()
    join_kinds = {OpKind.WINDOW_JOIN, OpKind.JOIN_WITH_EXPIRATION}

    for op_id in g.nodes:
        node = program.node(op_id)
        kind = node.operator.kind
        in_edges = list(g.in_edges(op_id, data=True))

        if not in_edges and kind != OpKind.CONNECTOR_SOURCE:
            diags.append(PlanDiagnostic(
                "dangling-node", "error",
                f"{node.operator.name} ({kind.value}) has no inputs "
                "but is not a source — a dropped edge or dead subplan",
                node=op_id))
        if g.out_degree(op_id) == 0 and kind != OpKind.CONNECTOR_SINK:
            diags.append(PlanDiagnostic(
                "dead-end", "warning",
                f"{node.operator.name} ({kind.value}) output reaches "
                "no sink", node=op_id))

        if kind in keyed_kinds and in_edges:
            if node.max_parallelism != 1:
                forwards = [s for s, _, d in in_edges
                            if d["edge"].typ is EdgeType.FORWARD]
                if kind is OpKind.DERIVED_WINDOW:
                    # the factored shape: a derived window's FORWARD
                    # in-edge from its factor is co-partitioned by
                    # construction (the factor is keyed-shuffled at
                    # equal parallelism; 1:1 subtask pairing preserves
                    # key ownership) — only NON-factor forwards are
                    # unrouted
                    forwards = [
                        s for s in forwards
                        if program.node(s).operator.kind
                        is not OpKind.WINDOW_FACTOR]
                if forwards:
                    diags.append(PlanDiagnostic(
                        "keyed-not-shuffled", "error",
                        f"{node.operator.name} ({kind.value}) holds "
                        "key-partitioned state but is fed by FORWARD "
                        f"edge(s) from {forwards}; each subtask would "
                        "see only a slice of each key's rows",
                        node=op_id))

        if kind is OpKind.DERIVED_WINDOW:
            spec = node.operator.spec
            srcs = [s for s, _, _ in in_edges]
            fsrcs = [s for s in srcs if program.node(s).operator.kind
                     is OpKind.WINDOW_FACTOR]
            if len(in_edges) != 1 or len(fsrcs) != 1:
                diags.append(PlanDiagnostic(
                    "factor-shape", "error",
                    f"{node.operator.name} (derived_window) must be fed "
                    "by exactly one window_factor node "
                    f"(inputs: {srcs})", node=op_id))
            else:
                fnode = program.node(fsrcs[0])
                pane = fnode.operator.spec.pane_micros
                if (spec.pane_micros != pane
                        or spec.slide_micros % max(pane, 1) != 0
                        or spec.width_micros % max(pane, 1) != 0):
                    diags.append(PlanDiagnostic(
                        "factor-shape", "error",
                        f"{node.operator.name}: factor pane {pane}us "
                        f"must match the spec ({spec.pane_micros}us) "
                        f"and divide slide {spec.slide_micros}us / "
                        f"width {spec.width_micros}us", node=op_id))
                if fnode.parallelism != node.parallelism:
                    diags.append(PlanDiagnostic(
                        "factor-shape", "error",
                        f"{node.operator.name}: factor parallelism "
                        f"{fnode.parallelism} != derived parallelism "
                        f"{node.parallelism}; the FORWARD pane edge "
                        "would rebalance and break keyed routing",
                        node=op_id))

        if kind is OpKind.WINDOW_FACTOR:
            spec = node.operator.spec
            if spec.pane_micros <= 0:
                diags.append(PlanDiagnostic(
                    "window-spec", "error",
                    f"{node.operator.name}: factor pane must be "
                    f"positive (got {spec.pane_micros})", node=op_id))
            non_derived = [
                dst for _, dst in g.out_edges(op_id)
                if program.node(dst).operator.kind
                is not OpKind.DERIVED_WINDOW]
            if non_derived:
                diags.append(PlanDiagnostic(
                    "factor-shape", "error",
                    f"{node.operator.name} (window_factor) emits "
                    "partial-aggregate pane columns that only "
                    "derived_window consumers understand "
                    f"(non-derived consumers: {non_derived})",
                    node=op_id))

        if kind in join_kinds:
            left = [d["edge"] for _, _, d in in_edges
                    if d["edge"].typ is EdgeType.SHUFFLE_JOIN_LEFT]
            right = [d["edge"] for _, _, d in in_edges
                     if d["edge"].typ is EdgeType.SHUFFLE_JOIN_RIGHT]
            if len(left) != 1 or len(right) != 1:
                diags.append(PlanDiagnostic(
                    "join-sides", "error",
                    f"{node.operator.name} needs exactly one left and "
                    f"one right input (got {len(left)} left, "
                    f"{len(right)} right)", node=op_id))
            elif _key_arity(left[0].key_schema) \
                    != _key_arity(right[0].key_schema):
                diags.append(PlanDiagnostic(
                    "key-schema-mismatch", "error",
                    f"{node.operator.name} joins streams shuffled on "
                    f"different key arities ({left[0].key_schema!r} vs "
                    f"{right[0].key_schema!r}); rows for the same join "
                    "key would land on different subtasks", node=op_id))

        if kind == OpKind.MULTI_WAY_JOIN:
            n_sides = len(getattr(node.operator.spec, "side_cols", ()) or ())
            by_side: Dict[int, List[Any]] = {}
            for _, _, d in in_edges:
                s = d["edge"].typ.join_side
                if s is None:
                    diags.append(PlanDiagnostic(
                        "join-sides", "error",
                        f"{node.operator.name} has a non-join input edge "
                        f"({d['edge'].typ.value})", node=op_id))
                else:
                    by_side.setdefault(s, []).append(d["edge"])
            if n_sides and (sorted(by_side) != list(range(n_sides))
                            or any(len(v) != 1 for v in by_side.values())):
                diags.append(PlanDiagnostic(
                    "join-sides", "error",
                    f"{node.operator.name} declares {n_sides} sides but "
                    f"has inputs for sides {sorted(by_side)}",
                    node=op_id))
            arities = {_key_arity(es[0].key_schema)
                       for es in by_side.values()}
            if len(arities) > 1:
                diags.append(PlanDiagnostic(
                    "key-schema-mismatch", "error",
                    f"{node.operator.name} joins sides shuffled on "
                    "different key arities; rows for the same join key "
                    "would land on different subtasks", node=op_id))

        if kind in program.WINDOWED_KINDS:
            if not any(program.node(anc).operator.kind == OpKind.WATERMARK
                       for anc in nx.ancestors(g, op_id)):
                diags.append(PlanDiagnostic(
                    "window-no-watermark", "error",
                    f"{node.operator.name} ({kind.value}) requires a "
                    "watermark-assigning operator upstream; without one "
                    "its windows never fire", node=op_id))

        spec = node.operator.spec
        width = slide = None
        if kind is OpKind.DERIVED_WINDOW:
            width, slide = spec.width_micros, spec.slide_micros
        elif isinstance(spec, (SlidingAggregatorSpec,
                               SlidingAggregatingTopNSpec)):
            width, slide = spec.width_micros, spec.slide_micros
        elif isinstance(spec, TumblingAggregatorSpec):
            width = spec.width_micros
        elif isinstance(spec, WindowSpec):
            if isinstance(spec.typ, TumblingWindow):
                width = spec.typ.width_micros
            elif isinstance(spec.typ, SlidingWindow):
                width, slide = spec.typ.width_micros, spec.typ.slide_micros
            elif isinstance(spec.typ, SessionWindow):
                if spec.typ.gap_micros <= 0:
                    diags.append(PlanDiagnostic(
                        "window-spec", "error",
                        f"{node.operator.name}: session gap must be "
                        "positive", node=op_id))
        if width is not None and width <= 0:
            diags.append(PlanDiagnostic(
                "window-spec", "error",
                f"{node.operator.name}: window width must be positive "
                f"(got {width})", node=op_id))
        if slide is not None:
            if slide <= 0:
                diags.append(PlanDiagnostic(
                    "window-spec", "error",
                    f"{node.operator.name}: slide must be positive "
                    f"(got {slide})", node=op_id))
            elif width and width % slide != 0:
                diags.append(PlanDiagnostic(
                    "slide-width", "warning",
                    f"{node.operator.name}: slide {slide} does not "
                    f"divide width {width}; panes fall off the "
                    "bin-merged fast path", node=op_id))

    return diags


def errors_of(diags: List[PlanDiagnostic]) -> List[PlanDiagnostic]:
    return [d for d in diags if d.severity == "error"]


def plan_report(program: "Program", nk: Optional[int] = None
                ) -> Dict[str, Any]:
    """The combined plan report every validator consumer serves:
    graph-level diagnostics PLUS shardcheck's sharding/transfer
    verification (``analysis/shardcheck.py``) and its
    ``predicted_reshards`` total — the static analog of the runtime
    ``reshard_transfers`` counter the smoke drift gate cross-checks.
    ``ARROYO_SHARDCHECK=0`` drops the shardcheck half (triage only)."""
    diags = validate_program(program)
    from .shardcheck import analyze, shardcheck_enabled

    if not shardcheck_enabled():
        # the verifier did NOT run: report null, never a fabricated 0 —
        # a console/bench line must not display "statically proven
        # clean" for a plan nobody verified
        return {"diagnostics": diags, "predicted_reshards": None,
                "mesh_shards": None}
    rep = analyze(program, nk=nk)
    return {"diagnostics": diags + rep.diagnostics,
            "predicted_reshards": rep.predicted_reshards,
            "mesh_shards": rep.nk}


def check_program(program: "Program") -> List[PlanDiagnostic]:
    """Validate (graph invariants + shardcheck) and raise
    PlanValidationError on any error-severity diagnostic; returns the
    full diagnostic list (warnings included) otherwise."""
    diags = plan_report(program)["diagnostics"]
    errs = errors_of(diags)
    if errs:
        raise PlanValidationError(errs)
    return diags
