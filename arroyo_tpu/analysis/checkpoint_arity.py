"""Checkpoint-state arity/schema checker — the round-5 Nexmark bug class.

Round 5 shipped ``gen_next`` returning a 4-tuple while the consumer
unpacked 3 names, crashing the benchmark source on every run.  Both
halves of that bug are statically visible inside one module:

1. **State-table tuple shapes**: a table obtained from
   ``ctx.state.get_global_keyed_state("s")`` (or ``get_keyed_state``)
   whose ``insert(..., (a, b, c, d))`` writes N-tuples must only be
   unpacked/indexed on the restore path within N: an exact unpack of a
   different arity, a slice past N, or a constant index >= N is a
   latent restore crash.

2. **Producer/consumer tuple arity**: a local function whose returns
   are tuple literals of arity N, consumed by a tuple-unpack of M != N
   names — directly (``a, b = f()``), through ``await``, or through the
   ``loop.run_in_executor(None, f)`` indirection the Nexmark prefetch
   uses (``fut = loop.run_in_executor(None, gen_next)``; later
   ``a, b, c = await fut``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Finding

PASS_ID = "ckpt-arity"

_STATE_GETTERS = {"get_global_keyed_state", "get_keyed_state"}


def _const_index(sl: ast.expr) -> Optional[int]:
    if isinstance(sl, ast.Constant) and isinstance(sl.value, int):
        return sl.value
    return None


def _table_of(call: ast.Call) -> Optional[str]:
    """Table name when ``call`` is ``<...>.get_*_keyed_state("name")``."""
    if (isinstance(call.func, ast.Attribute)
            and call.func.attr in _STATE_GETTERS and call.args
            and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, str)):
        return call.args[0].value
    return None


class _ModuleScan(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: List[Finding] = []
        # state tables: var name -> table name; table -> insert arities
        self.table_vars: Dict[str, str] = {}
        self.insert_arities: Dict[str, Set[int]] = {}
        # saved-value vars: var name -> table name (from state.get(...))
        self.saved_vars: Dict[str, str] = {}
        # producer/consumer: fn name -> set of tuple-return arities
        # (None in the set marks a non-tuple return -> arity unknown)
        self.fn_returns: Dict[str, Set[Optional[int]]] = {}
        # executor futures: var name -> producer fn name
        self.future_vars: Dict[str, str] = {}
        # deferred consumer checks resolved after the full scan
        self.unpack_sites: List[tuple] = []  # (line, fn_name, n_targets)
        self.read_sites: List[tuple] = []  # (line, table, kind, value)

    # -- producers ---------------------------------------------------------

    def _scan_fn_returns(self, node) -> None:
        arities: Set[Optional[int]] = set()
        # manual walk pruning nested def SUBTREES (ast.walk would leak
        # a nested helper's returns into this function's arity set);
        # nested defs are collected on their own visit
        stack = list(ast.iter_child_nodes(node))
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(sub, ast.Return) and sub.value is not None:
                if isinstance(sub.value, ast.Tuple):
                    arities.add(len(sub.value.elts))
                else:
                    arities.add(None)
            stack.extend(ast.iter_child_nodes(sub))
        if arities:
            # same-named defs in different scopes merge their arity
            # sets: call sites can't be attributed to one def, so only
            # an arity NO definition produces may be flagged
            self.fn_returns.setdefault(node.name, set()).update(arities)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._scan_fn_returns(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._scan_fn_returns(node)
        self.generic_visit(node)

    # -- assignments -------------------------------------------------------

    def _executor_producer(self, value: ast.expr) -> Optional[str]:
        """Producer fn name when ``value`` contains
        ``<...>.run_in_executor(_, fn, ...)`` (IfExp branches included)."""
        for sub in ast.walk(value):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "run_in_executor"
                    and len(sub.args) >= 2
                    and isinstance(sub.args[1], ast.Name)):
                return sub.args[1].id
        return None

    def visit_Assign(self, node: ast.Assign) -> None:
        value = node.value
        target = node.targets[0] if len(node.targets) == 1 else None

        if isinstance(target, ast.Name):
            if isinstance(value, ast.Call):
                table = _table_of(value)
                if table is not None:
                    self.table_vars[target.id] = table
                elif (isinstance(value.func, ast.Attribute)
                        and value.func.attr == "get"
                        and isinstance(value.func.value, ast.Name)
                        and value.func.value.id in self.table_vars):
                    self.saved_vars[target.id] = \
                        self.table_vars[value.func.value.id]
            producer = self._executor_producer(value)
            if producer is not None:
                self.future_vars[target.id] = producer

        # tuple-unpack consumers:  a, b, c = <rhs>
        if isinstance(target, ast.Tuple):
            if any(isinstance(t, ast.Starred) for t in target.elts):
                self.generic_visit(node)
                return
            n = len(target.elts)
            rhs = value
            if isinstance(rhs, ast.Await):
                rhs = rhs.value
            if isinstance(rhs, ast.Call) and isinstance(rhs.func, ast.Name):
                self.unpack_sites.append(
                    ("call", node.lineno, rhs.func.id, n))
            elif isinstance(rhs, ast.Name):
                if rhs.id in self.future_vars:
                    self.unpack_sites.append(
                        ("call", node.lineno, self.future_vars[rhs.id], n))
                elif rhs.id in self.saved_vars:
                    self.read_sites.append(
                        ("unpack", node.lineno,
                         self.saved_vars[rhs.id], n))
        self.generic_visit(node)

    # -- state inserts / reads --------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "insert" and node.args:
            table = None
            base = node.func.value
            if isinstance(base, ast.Name):
                table = self.table_vars.get(base.id)
            elif isinstance(base, ast.Call):
                table = _table_of(base)
            if table is not None and isinstance(node.args[-1], ast.Tuple):
                self.insert_arities.setdefault(table, set()).add(
                    len(node.args[-1].elts))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.value, ast.Name) \
                and node.value.id in self.saved_vars:
            table = self.saved_vars[node.value.id]
            sl = node.slice
            if isinstance(sl, ast.Slice):
                upper = _const_index(sl.upper) if sl.upper else None
                if upper is not None and sl.lower is None:
                    self.read_sites.append(
                        ("slice", node.lineno, table, upper))
            else:
                idx = _const_index(sl)
                if idx is not None and idx >= 0:
                    self.read_sites.append(
                        ("index", node.lineno, table, idx))
        self.generic_visit(node)

    # -- resolution --------------------------------------------------------

    def resolve(self) -> List[Finding]:
        for kind, line, name, n in self.unpack_sites:
            arities = self.fn_returns.get(name)
            if not arities or None in arities:
                continue  # unknown/non-tuple returns: can't prove a bug
            if n not in arities:
                want = "/".join(str(a) for a in sorted(arities))
                self.findings.append(Finding(
                    PASS_ID, "tuple-unpack-mismatch", self.path, line,
                    f"unpacking {n} values from {name}() which returns "
                    f"a {want}-tuple"))
        for kind, line, table, n in self.read_sites:
            arities = self.insert_arities.get(table)
            if not arities:
                continue
            mx = max(arities)
            if kind == "unpack" and n not in arities:
                want = "/".join(str(a) for a in sorted(arities))
                self.findings.append(Finding(
                    PASS_ID, "state-unpack-mismatch", self.path, line,
                    f"restore path unpacks {n} values from state table "
                    f"{table!r} whose inserts write {want}-tuples"))
            elif kind == "slice" and n > mx:
                self.findings.append(Finding(
                    PASS_ID, "state-slice-overrun", self.path, line,
                    f"restore path slices [:{n}] of state table "
                    f"{table!r} whose inserts write {mx}-tuples"))
            elif kind == "index" and n >= mx:
                self.findings.append(Finding(
                    PASS_ID, "state-index-overrun", self.path, line,
                    f"restore path indexes [{n}] of state table "
                    f"{table!r} whose inserts write {mx}-tuples"))
        return self.findings


def check(tree: ast.AST, lines, path: str) -> List[Finding]:
    scan = _ModuleScan(path)
    scan.visit(tree)
    return scan.resolve()
