"""Proto-drift check: rpc.proto vs the hand-surgered rpc_pb2.py.

protoc is not in the image, so schema changes are made by
FileDescriptorProto surgery on the serialized blob inside
``rpc/gen/rpc_pb2.py`` while ``rpc/proto/rpc.proto`` remains the
human-readable schema.  Nothing mechanical kept them in sync — a
surgery typo (wrong field number, missed message) would ship a wire
format silently diverging from the documented schema.

This pass parses the .proto text with a minimal proto3 grammar
(messages, scalar/message/map fields, optional/repeated labels,
services) and compares it against the descriptors the generated module
actually registers: message sets, field names/numbers/types/labels,
map key/value types, and service method signatures must all match.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Tuple

from .core import Finding

PASS_ID = "proto-drift"

PROTO_REL = "arroyo_tpu/rpc/proto/rpc.proto"

# proto3 scalar type name -> FieldDescriptor.TYPE_* enum value
_SCALAR_TYPES = {
    "double": 1, "float": 2, "int64": 3, "uint64": 4, "int32": 5,
    "fixed64": 6, "fixed32": 7, "bool": 8, "string": 9, "bytes": 12,
    "uint32": 13, "sfixed32": 15, "sfixed64": 16, "sint32": 17,
    "sint64": 18,
}
_TYPE_MESSAGE = 11
_LABEL_REPEATED = 3

_FIELD_RE = re.compile(
    r"(?:(optional|repeated)\s+)?"
    r"(map\s*<\s*(\w+)\s*,\s*(\w+)\s*>|[\w.]+)\s+"
    r"(\w+)\s*=\s*(\d+)\s*;")
_RPC_RE = re.compile(
    r"rpc\s+(\w+)\s*\(\s*(stream\s+)?([\w.]+)\s*\)\s*"
    r"returns\s*\(\s*(stream\s+)?([\w.]+)\s*\)")


def _strip_comments(text: str) -> str:
    return re.sub(r"//[^\n]*", "", text)


def _blocks(text: str, kind: str) -> Dict[str, str]:
    """Top-level ``kind name { body }`` blocks (no nesting of the same
    kind in this schema)."""
    out: Dict[str, str] = {}
    for m in re.finditer(rf"\b{kind}\s+(\w+)\s*\{{", text):
        depth, i = 1, m.end()
        while i < len(text) and depth:
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
            i += 1
        out[m.group(1)] = text[m.end():i - 1]
    return out


def parse_proto(text: str) -> Tuple[Dict, Dict]:
    """-> (messages, services); messages[name][field] =
    (number, type_str, label) with type_str like 'string',
    'TaskAssignment' or 'map<string,string>'."""
    text = _strip_comments(text)
    messages: Dict[str, Dict[str, Tuple[int, str, str]]] = {}
    for name, body in _blocks(text, "message").items():
        fields: Dict[str, Tuple[int, str, str]] = {}
        for fm in _FIELD_RE.finditer(body):
            label = fm.group(1) or ""
            typ = fm.group(2)
            if typ.startswith("map"):
                typ = f"map<{fm.group(3)},{fm.group(4)}>"
                label = ""
            fields[fm.group(5)] = (int(fm.group(6)), typ, label)
        messages[name] = fields
    services: Dict[str, Dict[str, Tuple[str, str]]] = {}
    for name, body in _blocks(text, "service").items():
        services[name] = {m.group(1): (m.group(3), m.group(5))
                          for m in _RPC_RE.finditer(body)}
    return messages, services


def _is_repeated(fd) -> bool:
    try:  # newer protobuf: .label is deprecated in favor of .is_repeated
        return bool(fd.is_repeated)
    except AttributeError:
        return fd.label == _LABEL_REPEATED


def _describe_field(fd) -> Tuple[str, str]:
    """Descriptor field -> (type_str, label) in parse_proto's terms."""
    if fd.type == _TYPE_MESSAGE and fd.message_type.GetOptions().map_entry:
        kv = {f.name: f for f in fd.message_type.fields}
        inv = {v: k for k, v in _SCALAR_TYPES.items()}
        kt = inv.get(kv["key"].type, "?")
        vt = (kv["value"].message_type.name
              if kv["value"].type == _TYPE_MESSAGE
              else inv.get(kv["value"].type, "?"))
        return f"map<{kt},{vt}>", ""
    if fd.type == _TYPE_MESSAGE:
        typ = fd.message_type.name
    else:
        inv = {v: k for k, v in _SCALAR_TYPES.items()}
        typ = inv.get(fd.type, f"type#{fd.type}")
    if _is_repeated(fd):
        return typ, "repeated"
    # proto3 explicit presence on a scalar surfaces as a synthetic oneof
    if fd.containing_oneof is not None:
        return typ, "optional"
    return typ, ""


def compare(messages: Dict, services: Dict, descriptor,
            proto_path: str) -> List[Finding]:
    """Compare parsed .proto structures against a FileDescriptor."""
    findings: List[Finding] = []

    def f(code: str, msg: str) -> None:
        findings.append(Finding(PASS_ID, code, proto_path, 0, msg))

    gen_msgs = dict(descriptor.message_types_by_name)
    for name, fields in messages.items():
        md = gen_msgs.pop(name, None)
        if md is None:
            f("missing-message",
              f"message {name} is in rpc.proto but absent from the "
              "generated descriptors")
            continue
        gen_fields = {fd.name: fd for fd in md.fields}
        for fname, (number, typ, label) in fields.items():
            fd = gen_fields.pop(fname, None)
            if fd is None:
                f("missing-field",
                  f"{name}.{fname} is in rpc.proto but absent from "
                  "the generated descriptors")
                continue
            if fd.number != number:
                f("field-number",
                  f"{name}.{fname}: rpc.proto says field number "
                  f"{number}, generated descriptor says {fd.number}")
            gtyp, glabel = _describe_field(fd)
            if gtyp != typ:
                f("field-type",
                  f"{name}.{fname}: rpc.proto says {typ}, generated "
                  f"descriptor says {gtyp}")
            if glabel != label:
                f("field-label",
                  f"{name}.{fname}: rpc.proto says "
                  f"{label or 'singular'}, generated descriptor says "
                  f"{glabel or 'singular'}")
        for fname in gen_fields:
            f("extra-field",
              f"{name}.{fname} is in the generated descriptors but "
              "not in rpc.proto")
    for name in gen_msgs:
        f("extra-message",
          f"message {name} is in the generated descriptors but not "
          "in rpc.proto")

    gen_svcs = dict(descriptor.services_by_name)
    for name, methods in services.items():
        sd = gen_svcs.pop(name, None)
        if sd is None:
            f("missing-service", f"service {name} is in rpc.proto but "
              "absent from the generated descriptors")
            continue
        gen_methods = {m.name: m for m in sd.methods}
        for mname, (inp, outp) in methods.items():
            md = gen_methods.pop(mname, None)
            if md is None:
                f("missing-rpc", f"{name}.{mname} is in rpc.proto but "
                  "absent from the generated descriptors")
                continue
            if md.input_type.name != inp.split(".")[-1] \
                    or md.output_type.name != outp.split(".")[-1]:
                f("rpc-signature",
                  f"{name}.{mname}: rpc.proto says ({inp}) -> {outp}, "
                  f"generated descriptor says "
                  f"({md.input_type.name}) -> {md.output_type.name}")
        for mname in gen_methods:
            f("extra-rpc", f"{name}.{mname} is in the generated "
              "descriptors but not in rpc.proto")
    for name in gen_svcs:
        f("extra-service", f"service {name} is in the generated "
          "descriptors but not in rpc.proto")
    return findings


def check_repo(root: str, full_scan: bool = True) -> List[Finding]:
    proto_path = os.path.join(root, PROTO_REL)
    if not os.path.exists(proto_path):
        return []
    with open(proto_path) as fh:
        messages, services = parse_proto(fh.read())
    try:
        from ..rpc.gen import rpc_pb2
    except Exception as e:  # the generated module failing to import IS
        # the drift signal surgery most often produces
        return [Finding(PASS_ID, "pb2-import", proto_path, 0,
                        f"rpc_pb2.py failed to import: {e}")]
    return compare(messages, services, rpc_pb2.DESCRIPTOR, proto_path)
