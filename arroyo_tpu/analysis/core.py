"""arroyolint core: finding model, waivers, baseline, and the runner.

A *pass* is a module exposing ``PASS_ID`` and one of

- ``check(tree, lines, path) -> List[Finding]`` — an AST pass run per
  file,
- ``check_project(files) -> List[Finding]`` — an interprocedural pass
  run once over every parsed file (``files`` maps path -> (tree,
  lines)); its findings are file-anchored, so inline waivers and the
  baseline apply exactly as for AST passes (async-race), or
- ``check_repo(root, full_scan) -> List[Finding]`` — a repo-level pass
  run once (e.g. proto drift); ``full_scan`` is False when the caller
  restricted the lint below the package root, and expensive whole-repo
  work (shardcheck's plan sweep) must be skipped then.

Waivers: a finding is suppressed when its line (or the immediately
preceding comment-only line) carries::

    # arroyolint: disable=<pass>[,<pass>...] -- reason

The reason is mandatory — a waiver without one is itself reported.
``disable=all`` suppresses every pass on that line.

Baseline: tools/arroyolint_baseline.json holds fingerprints of accepted
pre-existing findings (the adoption ratchet — new findings still fail).
Fingerprints hash (relative path, pass, code, stripped line text,
occurrence index), so they survive unrelated line drift.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(PKG_ROOT)
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools",
                                "arroyolint_baseline.json")

_WAIVER_RE = re.compile(
    r"#\s*arroyolint:\s*disable=([\w,\-]+)\s*(?:--\s*(\S.*))?")


@dataclass
class Finding:
    pass_id: str
    code: str
    path: str  # absolute or repo-relative; normalized at report time
    line: int
    message: str
    severity: str = "error"
    waived: bool = False
    waive_reason: str = ""
    baselined: bool = False
    fingerprint: str = ""

    def rel_path(self) -> str:
        p = self.path
        if os.path.isabs(p):
            try:
                p = os.path.relpath(p, REPO_ROOT)
            except ValueError:
                pass
        return p.replace(os.sep, "/")

    def to_json(self) -> Dict:
        return {
            "pass": self.pass_id, "code": self.code,
            # both keys on purpose: "file" is the documented
            # machine-readable name (--format json consumers), "path"
            # the historical one older tooling may already read
            "file": self.rel_path(), "path": self.rel_path(),
            "line": self.line,
            "message": self.message, "severity": self.severity,
            "waived": self.waived, "baselined": self.baselined,
            "fingerprint": self.fingerprint,
        }

    def render(self) -> str:
        tag = ""
        if self.waived:
            tag = " [waived]"
        elif self.baselined:
            tag = " [baseline]"
        return (f"{self.rel_path()}:{self.line}: "
                f"{self.pass_id}/{self.code}: {self.message}{tag}")


@dataclass
class Waiver:
    passes: List[str]
    reason: str
    line: int


def parse_waivers(lines: Sequence[str], path: str
                  ) -> Tuple[Dict[int, Waiver], List[Finding]]:
    """Line number -> waiver in effect on that line.  A waiver on a
    comment-only line also covers the next non-blank line."""
    waivers: Dict[int, Waiver] = {}
    problems: List[Finding] = []
    for i, text in enumerate(lines, start=1):
        m = _WAIVER_RE.search(text)
        if not m:
            continue
        passes = [p.strip() for p in m.group(1).split(",") if p.strip()]
        reason = (m.group(2) or "").strip()
        if not reason:
            problems.append(Finding(
                "waiver", "missing-reason", path, i,
                "waiver without a justification: use "
                "'# arroyolint: disable=<pass> -- reason'"))
        w = Waiver(passes, reason, i)
        waivers[i] = w
        if text.split("#", 1)[0].strip() == "":
            # standalone comment line: cover the next non-blank line
            for j in range(i + 1, min(i + 3, len(lines) + 1)):
                if lines[j - 1].strip():
                    waivers.setdefault(j, w)
                    break
    return waivers, problems


def apply_waivers(findings: List[Finding], waivers: Dict[int, Waiver]
                  ) -> None:
    for f in findings:
        if f.pass_id == "waiver":
            continue  # the missing-reason enforcement finding is not
            # itself waivable — 'disable=all' must not self-waive
        w = waivers.get(f.line)
        if w and ("all" in w.passes or f.pass_id in w.passes):
            f.waived = True
            f.waive_reason = w.reason


def assign_fingerprints(findings: List[Finding],
                        lines_by_path: Dict[str, Sequence[str]]) -> None:
    seen: Dict[Tuple, int] = {}
    for f in findings:
        lines = lines_by_path.get(f.path, ())
        text = (lines[f.line - 1].strip()
                if 0 < f.line <= len(lines) else "")
        key = (f.rel_path(), f.pass_id, f.code, text)
        n = seen.get(key, 0)
        seen[key] = n + 1
        raw = "|".join((f.rel_path(), f.pass_id, f.code, text, str(n)))
        f.fingerprint = hashlib.sha1(raw.encode()).hexdigest()[:16]


# -- baseline ---------------------------------------------------------------


def load_baseline(path: str = DEFAULT_BASELINE) -> Dict[str, Dict]:
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (FileNotFoundError, json.JSONDecodeError):
        return {}
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def write_baseline(findings: Iterable[Finding],
                   path: str = DEFAULT_BASELINE,
                   reason: str = "pre-existing; accepted at baseline "
                                 "creation") -> int:
    entries = []
    for f in findings:
        if f.waived or f.pass_id == "waiver":
            # a reasonless waiver must be FIXED (given a reason), never
            # accepted into the baseline
            continue
        entries.append({
            "fingerprint": f.fingerprint, "pass": f.pass_id,
            "code": f.code, "path": f.rel_path(), "line": f.line,
            "message": f.message, "reason": reason,
        })
    entries.sort(key=lambda e: (e["path"], e["line"], e["pass"]))
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        json.dump({"version": 1, "findings": entries}, fh, indent=1)
        fh.write("\n")
    return len(entries)


def apply_baseline(findings: List[Finding],
                   baseline: Dict[str, Dict]) -> None:
    for f in findings:
        if f.pass_id == "waiver":
            continue  # unbaselineable, like unwaivable above
        if not f.waived and f.fingerprint in baseline:
            f.baselined = True


# -- runner -----------------------------------------------------------------


def _iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, files in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            out.extend(os.path.join(dirpath, fn)
                       for fn in files if fn.endswith(".py"))
    return sorted(set(out))


def _ast_passes():
    from . import (
        async_blocking,
        checkpoint_arity,
        host_sync,
        protocol,
        recompile_hazard,
        row_loop,
        trace_purity,
    )

    # recompile-hazard runs FIRST: a jit cache-key hazard in ops/ or
    # parallel/ turns the steady state into a compile storm, which
    # invalidates every number the later invariants protect
    return [recompile_hazard, checkpoint_arity, async_blocking,
            host_sync, trace_purity, protocol, row_loop]


def _project_passes():
    from . import async_race

    return [async_race]


def _repo_passes():
    from . import proto_drift, shardcheck

    # shardcheck first: the sharding contract (route-shift wiring +
    # representative-plan sweep) gates everything the data plane runs
    return [shardcheck, proto_drift]


def run_analysis(paths: Optional[Sequence[str]] = None,
                 baseline_path: Optional[str] = DEFAULT_BASELINE,
                 passes: Optional[Sequence[str]] = None,
                 repo_root: str = REPO_ROOT) -> List[Finding]:
    """Run every pass; returns ALL findings with ``waived``/``baselined``
    flags applied — callers gate on the ones with neither."""
    paths = list(paths) if paths else [PKG_ROOT]
    # repo passes with expensive whole-repo work (shardcheck's plan
    # sweep) only run it when the scan covers the package root — a
    # single-file lint must stay fast and never gate on plan findings
    pkg = os.path.abspath(PKG_ROOT)
    full_scan = any(os.path.abspath(p) == pkg
                    or pkg.startswith(os.path.abspath(p) + os.sep)
                    for p in paths)
    findings: List[Finding] = []
    lines_by_path: Dict[str, Sequence[str]] = {}
    trees_by_path: Dict[str, ast.AST] = {}
    waivers_by_path: Dict[str, Dict[int, Waiver]] = {}
    for path in _iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=path)
        except (OSError, SyntaxError) as e:
            findings.append(Finding("core", "unparsable", path,
                                    getattr(e, "lineno", 0) or 0,
                                    f"could not parse: {e}"))
            continue
        lines = src.splitlines()
        lines_by_path[path] = lines
        trees_by_path[path] = tree
        waivers, problems = parse_waivers(lines, path)
        waivers_by_path[path] = waivers
        file_findings: List[Finding] = list(problems)
        for mod in _ast_passes():
            if passes and mod.PASS_ID not in passes:
                continue
            file_findings.extend(mod.check(tree, lines, path))
        apply_waivers(file_findings, waivers)
        findings.extend(file_findings)
    # waiver lookups key on the absolute path: repo passes anchor
    # findings at REPO_ROOT-joined paths while the CLI may have been
    # given relative ones, and both must land on the same waiver set
    waivers_by_abspath = {os.path.abspath(p): w
                          for p, w in waivers_by_path.items()}
    # interprocedural passes see every parsed file at once; their
    # findings are file-anchored, so per-file waivers apply the same way
    for mod in _project_passes():
        if passes and mod.PASS_ID not in passes:
            continue
        proj = mod.check_project(
            {p: (trees_by_path[p], lines_by_path[p])
             for p in trees_by_path})
        for f in proj:
            apply_waivers(
                [f], waivers_by_abspath.get(os.path.abspath(f.path), {}))
        findings.extend(proj)
    for mod in _repo_passes():
        if passes and mod.PASS_ID not in passes:
            continue
        repo_findings = mod.check_repo(repo_root, full_scan=full_scan)
        for f in repo_findings:
            # repo-pass findings anchored to a parsed file (shardcheck's
            # wiring audit) honor that file's inline waivers exactly
            # like AST/project passes; findings anchored elsewhere
            # (rpc.proto, plan-sweep anchors) have no waiver surface
            apply_waivers(
                [f], waivers_by_abspath.get(os.path.abspath(f.path), {}))
        findings.extend(repo_findings)
    assign_fingerprints(findings, lines_by_path)
    if baseline_path:
        apply_baseline(findings, load_baseline(baseline_path))
    return findings


def unwaived(findings: Iterable[Finding]) -> List[Finding]:
    return [f for f in findings if not f.waived and not f.baselined]


# -- shared AST helpers -----------------------------------------------------


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target, '' when not a plain name/attr
    chain (e.g. ``time.sleep`` -> 'time.sleep')."""
    parts: List[str] = []
    cur = node.func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    if parts:  # method on a non-name expression: report '?.attr'
        return "?." + ".".join(reversed(parts))
    return ""
