"""shardcheck: plan-time sharding & transfer verification.

PR 9 made "no implicit reshards, no surprise host<->device hops" the
data plane's central invariant — but only *observed* it at runtime
(``reshard_transfers``, the sanitizer's sharding-instability check).  A
bad plan still shipped, ran, and paid the transfer before anyone
noticed.  Following HiFrames' stance that distribution properties of a
dataflow program are statically inferable (arXiv:1704.02341) and
Flare's whole-plan analysis (arXiv:1703.08219), this module *proves*
the invariant at plan time, before a single kernel compiles.

It is an abstract interpreter over the logical ``Program``: every node
output gets a symbolic :class:`ShardSpec` — declared key columns,
whether rows are actually key-range **aligned** across subtasks, the
top key-hash bits consumed by subtask ranges, mesh-state engagement
(``nk`` shards and the ``route_shift`` skipping the subtask bits),
join-ring placement (device ``p % nk``), and the host/device transport
pin of the producing edge (string columns force the sticky host
fallback).  Specs propagate through FORWARD edges 1:1 (chains,
factor->derived pane edges), re-partition at SHUFFLE/join edges, and
degrade to unaligned on rebalances.

Checks (diagnostic codes; errors reject plans at every plan-validator
consumer — engine build preflight, REST ``/v1/pipelines/validate``,
``bench.py`` preflight):

- ``route-bit-collision`` (error) — a mesh bin-state operator at
  parallelism P whose device route bits overlap the top
  ``ceil(log2(P))`` subtask key-range bits: the PR 9 funneling class,
  where every subtask's key slice collapses onto ~nk/P devices.  The
  expected shift is ``types.route_shift_for`` — the SAME function the
  engine wires — and the companion source audit
  (:func:`check_wiring_source`) pins that the wiring call site exists.
- ``predicted-reshard`` (error) — an edge where the producer's
  out-spec cannot unify with the consumer's pinned in-spec, so mesh-
  sharded device arrays would be re-placed at runtime (counted by
  ``ensure_sharded``).  The report's ``predicted_reshards`` total is
  the static analog of the live ``reshard_transfers`` counter; the
  smoke drift gate (:func:`drift_check`) fails when the two disagree
  in either direction, so this model can never silently rot.
- ``shard-unpinned`` (error) — a keyed-state kernel entered with an
  unaligned/unpinned spec (e.g. a FORWARD rebalance feeding keyed
  state): an implicit transfer/re-key at runtime.
- ``sticky-spec-flip`` (error) — a keyed edge behind mesh-resident
  state that a proven string column pins to the host route: the
  sharding spec flips device->host mid-chain and every batch gathers
  back to host.
- ``sticky-host-edge`` (warning) — a device-shuffle-eligible keyed
  edge that a declared string column permanently pins to the host
  route (stable, but the mesh never carries it).
- ``payload-host-gather`` (warning; escalates to the
  ``sticky-spec-flip`` error under ``ARROYO_JOIN_PAYLOAD_DEVICE=on``)
  — a string column in a join side's declared schema behind device key
  rings: the payload planes can never hold it, so every match gathers
  state from the host mirror (the sticky fallback; PR 15).
- ``sharding-instability`` (warning) — a device-eligible keyed edge
  fed by an OPEN schema (JSON ingest may grow columns mid-stream): a
  late string column would flip the edge's route mid-stream and trip
  the runtime sanitizer.
- ``session-host-aggregate`` (warning) — a string column feeding a
  session-window aggregate behind device session runs
  (state/session_state.py): interval merges ride the device union
  kernel but every fire for that aggregate replays the counted host
  segment loop (the f64 UDAF channels can never hold it), so
  ``udaf_host_rows``/``session_host_merge_rows`` carry the cost — the
  session analog of ``payload-host-gather`` (PR 19).  Suppressed
  entirely under ``ARROYO_SESSION_STATE=legacy`` (everything is host
  there by design).

``ARROYO_SHARDCHECK=0`` disables the gate at every consumer (triage
only — a plan that fails here pays real transfers).

The lint integration (``python -m arroyo_tpu.analysis``) runs this as
a repo-level pass: the wiring audit over ``engine/operators_window.py``
plus a representative-plan sweep (q5-shape hop aggregate, two-stream
join, factored correlated windows, config5-shape session windows, at
parallelism 1 and 2 on a symbolic 8-shard mesh) that must report zero
errors and zero predicted reshards.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Tuple

from .core import Finding
from .plan_validator import PlanDiagnostic

PASS_ID = "shardcheck"

_WIRING_FILE = os.path.join("engine", "operators_window.py")


def shardcheck_enabled() -> bool:
    return os.environ.get("ARROYO_SHARDCHECK", "1") not in (
        "0", "off", "false")


# ---------------------------------------------------------------------------
# the spec lattice
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ShardSpec:
    """Symbolic sharding of one node output / edge handoff.

    ``keys``     declared key columns of the rows (None = unkeyed).
    ``aligned``  rows are key-range partitioned across subtasks on
                 ``keys`` (a SHUFFLE routed them); FORWARD preserves
                 it 1:1, rebalances destroy it.
    ``part_bits`` top key-hash bits consumed by subtask ranges
                 (``ceil(log2(P))`` at parallelism P > 1).
    ``mesh_nk``  key shards of mesh-resident state backing this output
                 (1 = host/single-device state).
    ``route_shift`` first top key-hash bit the mesh routes on.
    ``device_out`` the handoff payload is mesh-sharded device arrays
                 (the factor->derived pane contract), so a repartition
                 or re-placement of this edge is a predicted reshard.
    ``sticky``   transport pin of the producing edge: 'device', 'host',
                 or 'open' (undetermined — schema may grow at runtime).
    ``mesh_behind`` mesh-resident state exists upstream of this spec
                 (drives the mid-chain device->host flip check).
    """

    keys: Optional[Tuple[str, ...]] = None
    aligned: bool = False
    part_bits: int = 0
    mesh_nk: int = 1
    route_shift: int = 0
    device_out: bool = False
    sticky: str = "host"
    mesh_behind: bool = False

    def render(self) -> str:
        k = ",".join(self.keys) if self.keys else "unkeyed"
        out = f"{k}{'|aligned' if self.aligned else ''}"
        if self.part_bits:
            out += f"|top{self.part_bits}b"
        if self.mesh_nk > 1:
            out += f"|mesh{self.mesh_nk}<<{self.route_shift}"
        if self.device_out:
            out += "|device"
        if self.sticky != "device":
            out += f"|{self.sticky}"
        return out


@dataclass
class ShardReport:
    diagnostics: List[PlanDiagnostic] = field(default_factory=list)
    predicted_reshards: int = 0
    edge_specs: Dict[Tuple[str, str], str] = field(default_factory=dict)
    node_specs: Dict[str, str] = field(default_factory=dict)
    nk: int = 1

    def errors(self) -> List[PlanDiagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    def to_json(self) -> Dict[str, Any]:
        return {
            "nk": self.nk,
            "predicted_reshards": self.predicted_reshards,
            "diagnostics": [d.to_json() for d in self.diagnostics],
            "edge_specs": {f"{s}->{d}": v
                           for (s, d), v in self.edge_specs.items()},
        }


def drift_check(predicted: int, observed: int,
                plan: str = "plan") -> Optional[str]:
    """The model-drift comparator the smoke gate runs after live
    smoke pipelines: shardcheck's ``predicted_reshards`` must equal the
    runtime ``reshard_transfers`` counter delta **in both directions** —
    a runtime reshard the model missed means the static model rotted; a
    predicted reshard the runtime never paid means the model went
    pessimistic and would start rejecting good plans.  Returns None on
    agreement, else the failure message."""
    if predicted == observed:
        return None
    if observed > predicted:
        return (f"shardcheck drift on {plan}: runtime counted {observed} "
                f"reshard(s) but the static model predicted {predicted} "
                "— the plan-time model missed a transfer class "
                "(model rot; fix analyze(), do not waive)")
    return (f"shardcheck drift on {plan}: the static model predicted "
            f"{predicted} reshard(s) but runtime counted {observed} — "
            "the model is over-pessimistic and would reject plans the "
            "data plane runs clean")


# ---------------------------------------------------------------------------
# column-kind propagation (drives the sticky string-column checks)
# ---------------------------------------------------------------------------

# connector schemas the interpreter knows cold; everything else is
# either declared (expr.output_schema) or unknown/open
_IMPULSE_COLS = {"counter": "i", "subtask_index": "i"}


def _source_cols(spec: Any) -> Tuple[Optional[Dict[str, str]], bool]:
    """(column kinds, open) for a connector source.  ``open`` means the
    schema may GROW at runtime (JSON ingest locks a schema per run but
    genuinely-new fields still appear — formats.py), so stickiness of
    downstream keyed edges cannot be pinned statically."""
    conn = getattr(spec, "connector", None)
    cfg = getattr(spec, "config", {}) or {}
    if conn == "nexmark":
        try:
            from ..sql.schema_provider import nexmark_table

            cols = dict(nexmark_table({}).schema.columns)
        except Exception:
            return None, False
        proj = cfg.get("projection")
        if proj:
            cols = {c: k for c, k in cols.items() if c in proj}
        return cols, False
    if conn == "impulse":
        return dict(_IMPULSE_COLS), False
    if conn in ("single_file", "kafka", "kinesis", "sse", "polling_http",
                "websocket", "fluvio", "filesystem", "webhook"):
        fmt = str(cfg.get("format", "json")).lower()
        # JSON schemas are inferred from data and may grow mid-stream
        return None, fmt in ("json", "debezium_json", "")
    return None, False


def _merge_cols(sides: List[Tuple[Optional[Dict[str, str]], bool]]
                ) -> Tuple[Optional[Dict[str, str]], bool]:
    is_open = any(o for _c, o in sides)
    known = [c for c, _o in sides if c is not None]
    if len(known) != len(sides):
        return None, is_open
    out: Dict[str, str] = {}
    for c in known:
        for name, kind in c.items():
            if out.get(name, kind) != kind:
                # string-wins: a column that is a string on ANY branch
                # forces the sticky host route at runtime, so the merge
                # must stay visible to _has_string; conflicting numeric
                # kinds promote on device and stay packable
                out[name] = "s" if "s" in (kind, out[name]) else "?"
            else:
                out[name] = kind
    return out, is_open


# the latency observatory's reserved ingest-stamp column name
# (obs/latency.py STAMP_COLUMN — tests pin the two in sync): an i64
# wall-clock by construction, so if it ever surfaces as a real column
# it is transportable (packs on the device shuffle) and must NEVER
# force the sticky host route the way an unknown/string column would
_LAT_STAMP_COLUMN = "__lat_ingest"


def _session_window_here(node) -> bool:
    """True when ``node`` is a session-window aggregate that will run
    on the device session-run state (state/session_state.py).  Under
    ``ARROYO_SESSION_STATE=legacy`` everything is host per-key dicts by
    design, so the session-specific findings are suppressed."""
    from ..graph.logical import OpKind, SessionWindow

    if node.operator.kind is not OpKind.WINDOW:
        return False
    if not isinstance(getattr(node.operator.spec, "typ", None),
                      SessionWindow):
        return False
    from ..state.session_state import session_state_enabled

    return session_state_enabled()


def _has_string(cols: Optional[Dict[str, str]]) -> Optional[str]:
    if not cols:
        return None
    for name, kind in cols.items():
        if name == _LAT_STAMP_COLUMN:
            continue
        if kind == "s":
            return name
    return None


# ---------------------------------------------------------------------------
# the interpreter
# ---------------------------------------------------------------------------


def _bin_state_kinds():
    from ..graph.logical import OpKind

    # operators whose state is make_bin_state (parallel/mesh_window.py):
    # mesh-sharded device bin rings when nk > 1 and the window shape is
    # short enough for key sharding (long windows ring-shard the BIN
    # axis instead and never touch the key route bits)
    return {
        OpKind.SLIDING_WINDOW_AGGREGATOR,
        OpKind.TUMBLING_WINDOW_AGGREGATOR,
        OpKind.SLIDING_AGGREGATING_TOP_N,
        OpKind.WINDOW_FACTOR,
        OpKind.DERIVED_WINDOW,
    }


def _ring_state_kinds():
    from ..graph.logical import OpKind

    # joins whose hot-partition key runs live in device rings placed
    # across the mesh at nk > 1 (PR 9: ops/join.stage_ring(device=
    # shuffle.partition_device(p)) — device p % nk).  Ring partitions
    # key on the LOW hash bits (subtask ranges own the top bits), so
    # they never participate in the route-bit funnel check — but their
    # state IS mesh-resident, so a downstream sticky host edge is the
    # same device->host mid-chain gather the flip check rejects.
    return {OpKind.WINDOW_JOIN, OpKind.JOIN_WITH_EXPIRATION,
            OpKind.MULTI_WAY_JOIN}


def _keyed_state_kinds():
    from .plan_validator import _keyed_state_kinds as kk

    return kk()


def _width_slide(node) -> Tuple[int, int]:
    spec = node.operator.spec
    w = getattr(spec, "width_micros", 0) or 0
    s = getattr(spec, "slide_micros", 0) or w
    if hasattr(spec, "pane_micros") and not w:  # WINDOW_FACTOR
        w = s = spec.pane_micros
    return w, s


def _parse_keys(key_schema: str) -> Optional[Tuple[str, ...]]:
    ks = (key_schema or "").strip()
    if ks in ("", "()"):
        return None
    return tuple(c.strip() for c in ks.split(",") if c.strip())


def _device_eligible(n: int, nk: int) -> bool:
    """Mirror of parallel/shuffle.device_shuffle_enabled's structural
    half: the fan-out a co-located keyed edge needs to ride the device
    exchange (backend/co-location are runtime facts the static model
    does not guess)."""
    return n >= 2 and not (n & (n - 1)) and nk >= n


def analyze(program: Any, nk: Optional[int] = None,
            assume_route_shift: Optional[int] = None,
            ring_min_w: Optional[int] = None) -> ShardReport:
    """Run the abstract interpreter over ``program``.

    ``nk``: mesh key-shard count to model (None resolves the live mesh
    via ``mesh_key_shards()``; falls back to 1 without a usable jax).
    ``assume_route_shift``: override the modeled route-shift wiring —
    the seeded-funnel fixtures pass 0 to re-create the PR 9 bug class
    and require the collision flagged.  Default None models the engine
    contract (``types.route_shift_for``).
    """
    import networkx as nx  # the graph layer already depends on it

    from ..graph.logical import EdgeType, OpKind
    from ..types import route_shift_for

    if nk is None:
        try:
            from ..parallel.mesh_window import mesh_key_shards

            nk = mesh_key_shards()
        except Exception:
            nk = 1
    if ring_min_w is None:
        try:
            ring_min_w = int(os.environ.get("ARROYO_RING_MIN_W", 64))
        except ValueError:
            ring_min_w = 64

    rep = ShardReport(nk=nk)
    g = program.graph
    if not nx.is_directed_acyclic_graph(g):
        return rep  # the plan validator already rejects cycles

    bin_kinds = _bin_state_kinds()
    ring_kinds = _ring_state_kinds()
    keyed_kinds = _keyed_state_kinds()
    specs: Dict[str, ShardSpec] = {}
    cols_of: Dict[str, Tuple[Optional[Dict[str, str]], bool]] = {}

    def diag(code: str, severity: str, msg: str, node: str) -> None:
        rep.diagnostics.append(PlanDiagnostic(code, severity, msg, node))

    def shift_for(p: int) -> int:
        if assume_route_shift is not None:
            return assume_route_shift
        return route_shift_for(p)

    for op_id in program.topo_order():
        node = program.node(op_id)
        kind = node.operator.kind
        P = node.parallelism
        in_edges = list(g.in_edges(op_id, data=True))

        # ---- per-edge in-specs + edge checks --------------------------
        in_specs: List[ShardSpec] = []
        in_cols: List[Tuple[Optional[Dict[str, str]], bool]] = []
        for src, _dst, data in in_edges:
            edge = data["edge"]
            p_spec = specs.get(src, ShardSpec())
            p_cols, p_open = cols_of.get(src, (None, False))
            src_p = program.node(src).parallelism
            if edge.typ is EdgeType.FORWARD:
                if src_p != P:
                    # round-robin rebalance: keyed partitioning is gone
                    spec = ShardSpec(mesh_behind=p_spec.mesh_behind)
                    if p_spec.device_out:
                        rep.predicted_reshards += 1
                        diag("predicted-reshard", "error",
                             f"{src}->{op_id}: mesh-sharded pane arrays "
                             f"cross a rebalancing FORWARD edge "
                             f"(parallelism {src_p}->{P}); every batch "
                             "would be re-placed", op_id)
                else:
                    spec = p_spec
            else:
                keys = _parse_keys(edge.key_schema)
                if p_spec.device_out:
                    # factor->derived pane arrays are a 1:1 device
                    # handoff; ANY repartition point between them means
                    # re-placing every mesh-sharded pane delta
                    rep.predicted_reshards += 1
                    diag("predicted-reshard", "error",
                         f"{src}->{op_id}: mesh-sharded pane arrays "
                         f"({p_spec.render()}) cross a "
                         f"{edge.typ.value} repartition point; the "
                         "producer's out-sharding cannot unify with "
                         "the consumer's in-sharding", op_id)
                if keys is None:
                    # "()"-keyed shuffle (union-style rebalance)
                    spec = ShardSpec(mesh_behind=p_spec.mesh_behind)
                else:
                    sticky = "device"
                    scol = _has_string(p_cols)
                    if scol is not None:
                        sticky = "host"
                    elif p_cols is None or p_open:
                        sticky = "open"
                    spec = ShardSpec(
                        keys=keys, aligned=True,
                        part_bits=route_shift_for(P),
                        sticky=sticky,
                        mesh_behind=p_spec.mesh_behind)
                    if _device_eligible(P, nk):
                        if sticky == "host":
                            if p_spec.mesh_behind:
                                diag("sticky-spec-flip", "error",
                                     f"{src}->{op_id}: sharding spec "
                                     "flips device->host mid-chain — "
                                     "state upstream is mesh-sharded "
                                     f"but string column {scol!r} pins "
                                     "this keyed edge to the sticky "
                                     "host route; every batch gathers "
                                     "back to host", op_id)
                            else:
                                diag("sticky-host-edge", "warning",
                                     f"{src}->{op_id}: string column "
                                     f"{scol!r} pins this keyed edge "
                                     "to the host route; the mesh "
                                     "never carries it", op_id)
                        elif p_open and sticky == "open":
                            diag("sharding-instability", "warning",
                                 f"{src}->{op_id}: open JSON schema "
                                 "feeds a device-eligible keyed edge; "
                                 "a late string column would flip the "
                                 "route mid-stream (the sanitizer "
                                 "would abort the pipeline)", op_id)
            in_specs.append(spec)
            in_cols.append((p_cols, p_open))
            rep.edge_specs[(src, op_id)] = spec.render()

        # ---- node checks ---------------------------------------------
        merged = in_specs[0] if len(in_specs) == 1 else ShardSpec(
            mesh_behind=any(s.mesh_behind for s in in_specs))
        if kind in keyed_kinds and in_specs and node.max_parallelism != 1:
            for (src, _d, data), spec in zip(in_edges, in_specs):
                if data["edge"].typ is EdgeType.FORWARD \
                        and not spec.aligned:
                    if program.node(src).operator.kind \
                            is OpKind.WINDOW_FACTOR:
                        continue  # 1:1 co-partitioned by construction
                    diag("shard-unpinned", "error",
                         f"{op_id} ({kind.value}): keyed-state kernel "
                         f"entered with an unpinned sharding spec from "
                         f"{src} ({spec.render()}); rows are not "
                         "key-range aligned, so the kernel would "
                         "implicitly transfer/re-key every batch",
                         op_id)

        mesh_here = False
        route_shift = 0
        if kind in bin_kinds and nk > 1:
            w, s = _width_slide(node)
            W = w // max(s, 1) if s else 0
            # mirror make_bin_state's selection exactly: long windows
            # ring-shard the BIN axis (no key route bits) only while
            # ARROYO_RING is not forced off — with it off they fall
            # back to the key-routed mesh state and every mesh check
            # applies
            ring_shape = (W and W >= ring_min_w
                          and os.environ.get("ARROYO_RING", "auto")
                          != "off")
            if ring_shape:
                pass
            else:
                mesh_here = True
                route_shift = shift_for(P)
                lg = (nk - 1).bit_length()
                # the top-bit count the INCOMING partitioning actually
                # consumed, straight off the propagated specs — the
                # lattice field is load-bearing here, not just rendered
                # (falls back to the engine contract when no in-edge
                # declared one)
                pb = max((s.part_bits for s in in_specs),
                         default=0) or route_shift_for(P)
                if P > 1 and route_shift < pb:
                    diag("route-bit-collision", "error",
                         f"{op_id} ({kind.value}): mesh route bits "
                         f"[{route_shift}, {route_shift + lg}) overlap "
                         f"the top {pb} subtask key-range bits at "
                         f"parallelism {P}; each subtask's key slice "
                         f"funnels onto ~{max(nk >> pb, 1)} of {nk} "
                         "devices (the PR 9 funneling class) — wire "
                         "set_route_shift(route_shift_for(parallelism))",
                         op_id)
                if route_shift + lg > 64:
                    diag("route-bit-overflow", "error",
                         f"{op_id}: route shift {route_shift} + "
                         f"{lg} mesh bits exceeds the 64-bit key hash",
                         op_id)

        # ---- out-spec -------------------------------------------------
        if kind is OpKind.CONNECTOR_SOURCE:
            cols, is_open = _source_cols(node.operator.spec)
            specs[op_id] = ShardSpec()
            cols_of[op_id] = (cols, is_open)
        elif kind in (OpKind.KEY_BY, OpKind.UPDATING_KEY):
            specs[op_id] = replace(
                merged, keys=node.operator.key_cols or None,
                aligned=False, part_bits=0)
            cols_of[op_id] = _merge_cols(in_cols) if in_cols else (None,
                                                                  False)
        elif kind is OpKind.GLOBAL_KEY:
            specs[op_id] = replace(merged, keys=("__global",),
                                   aligned=False, part_bits=0)
            cols_of[op_id] = _merge_cols(in_cols) if in_cols else (None,
                                                                  False)
        elif kind in (OpKind.EXPRESSION, OpKind.UDF, OpKind.FLAT_MAP,
                      OpKind.UPDATING, OpKind.FLATTEN, OpKind.WATERMARK):
            expr = node.operator.expr
            specs[op_id] = merged
            from ..graph.logical import ExprReturnType

            if expr is not None and expr.output_schema:
                cols_of[op_id] = (dict(expr.output_schema), False)
            elif (expr is None or expr.return_type
                    is ExprReturnType.PREDICATE
                    or kind is OpKind.WATERMARK):
                cols_of[op_id] = _merge_cols(in_cols) if in_cols \
                    else (None, False)
            else:
                # opaque projection: schema unknown but CLOSED (a
                # traced fn emits a fixed column set per run)
                _c, was_open = _merge_cols(in_cols) if in_cols \
                    else (None, False)
                cols_of[op_id] = (None, was_open)
        elif kind is OpKind.UNION:
            specs[op_id] = ShardSpec(
                mesh_behind=any(s.mesh_behind for s in in_specs))
            cols_of[op_id] = _merge_cols(in_cols) if in_cols else (None,
                                                                  False)
        elif kind in keyed_kinds:
            # keyed state emits per owned key: aligned on its key cols.
            # Join kinds at nk > 1 count as mesh-resident too: their
            # hot-partition rings spread device p % nk (see
            # _ring_state_kinds), so downstream sticky edges gather
            # device state back to host exactly like bin-state panes.
            ring_here = kind in ring_kinds and nk > 1
            if ring_here:
                # payload-plane placement (PR 15): a string column in a
                # join side's declared schema can never ride the device
                # payload planes, so every match of that side gathers
                # state from host while keys probe on device.  With
                # device payloads FORCED on this is the same
                # device->host flip error class as a string-pinned
                # keyed edge; under auto it is the designed sticky
                # fallback — stable, but worth a warning (the "host
                # gather share high" runbook).
                scol = _has_string(_join_out_cols(node.operator.spec))
                if scol is not None:
                    mode = os.environ.get(
                        "ARROYO_JOIN_PAYLOAD_DEVICE", "auto").lower()
                    if mode in ("on", "1", "true", "force"):
                        diag("sticky-spec-flip", "error",
                             f"{op_id} ({kind.value}): device payload "
                             "residency is forced on "
                             "(ARROYO_JOIN_PAYLOAD_DEVICE=on) but "
                             f"string column {scol!r} in a join side "
                             "schema can never ride the payload "
                             "planes; every match would gather state "
                             "host-side behind a device key ring — "
                             "the same device->host mid-chain flip as "
                             "a string-pinned keyed edge", op_id)
                    elif mode not in ("off", "0", "false"):
                        diag("payload-host-gather", "warning",
                             f"{op_id} ({kind.value}): string column "
                             f"{scol!r} pins this join's payload to "
                             "the sticky host gather; device key "
                             "rings probe on-mesh but every match "
                             "materializes from the host mirror "
                             "(join_host_gather_rows will dominate)",
                             op_id)
            # session run state (PR 19): session windows keep (key,
            # start, end) interval runs in state/session_state.py,
            # partitioned on the LOW key-hash bits (kh & (P-1)) while
            # subtask key ranges own the TOP bits — orthogonal by
            # construction, so rescale never re-partitions session runs
            # and they never enter the route-bit funnel check.  Hot
            # partitions stage (st, en) planes on mesh devices, so a
            # session node at nk > 1 is mesh-resident like a join ring.
            session_win = _session_window_here(node)
            session_here = session_win and nk > 1
            if session_win:
                # fire-time aggregation replays buffered rows through
                # ops/segment.py: a string input column can never ride
                # the f64 UDAF/partial channels, so every fire for that
                # aggregate runs the counted per-segment host loop
                # behind device interval merges — the designed sticky
                # fallback (stable, but the "config5 slow — sessions
                # riding host" runbook wants it surfaced at plan time).
                merged_in, _oin = _merge_cols(in_cols) if in_cols \
                    else (None, False)
                for a in getattr(node.operator.spec, "aggs", ()) or ():
                    ak = (merged_in or {}).get(a.column or "")
                    if ak == "s":
                        diag("session-host-aggregate", "warning",
                             f"{op_id} ({kind.value}): string column "
                             f"{a.column!r} feeds session aggregate "
                             f"{a.output!r}; interval merges ride the "
                             "device union kernel but every fire for "
                             "this aggregate replays the host segment "
                             "loop (udaf_host_rows / "
                             "session_host_merge_rows carry the cost)",
                             op_id)
                        break
            keys = next((s.keys for s in in_specs if s.keys), None)
            specs[op_id] = ShardSpec(
                keys=keys, aligned=True,
                part_bits=route_shift_for(P),
                mesh_nk=nk if mesh_here else 1,
                route_shift=route_shift,
                device_out=(kind is OpKind.WINDOW_FACTOR and mesh_here),
                sticky=merged.sticky,
                mesh_behind=(mesh_here or ring_here or session_here
                             or any(s.mesh_behind for s in in_specs)))
            cols_of[op_id] = (_agg_out_cols(node, in_cols), False)
        else:  # sinks and anything unmodeled: pass through conservatively
            specs[op_id] = merged
            cols_of[op_id] = _merge_cols(in_cols) if in_cols else (None,
                                                                  False)
        rep.node_specs[op_id] = specs[op_id].render()

    return rep


def _join_out_cols(spec) -> Optional[Dict[str, str]]:
    """Output kinds of a join from the spec's declared per-side
    ``(name, kind)`` schemas (pairwise ``left_cols``/``right_cols``,
    N-ary ``side_cols``).  Collisions mirror the engine's naming (the
    right/later side gets the ``r_`` prefix); what downstream checks
    actually consume is the KINDS — a string column selected through a
    join must stay visible to the sticky-route checks.  None when the
    planner declared nothing (unknown, never produces findings)."""
    if hasattr(spec, "left_cols") or hasattr(spec, "right_cols"):
        sides = [tuple(getattr(spec, "left_cols", ()) or ()),
                 tuple(getattr(spec, "right_cols", ()) or ())]
    elif hasattr(spec, "side_cols"):
        sides = [tuple(s) for s in (getattr(spec, "side_cols", ()) or ())]
    else:
        return None
    if not any(sides):
        return None
    out: Dict[str, str] = {}
    for i, side in enumerate(sides):
        for name, kind in side:
            if i and name in out:
                name = "r_" + name
            out.setdefault(name, kind)
    return out


def _agg_out_cols(node, in_cols) -> Optional[Dict[str, str]]:
    """Output kinds of a window aggregate: key cols (from upstream when
    known) + numeric agg outputs + window bounds.  None when a
    projection rewrites the schema opaquely."""
    spec = node.operator.spec
    aggs = getattr(spec, "aggs", None)
    if aggs is None:
        return _join_out_cols(spec)
    if getattr(spec, "projection", None) is not None:
        proj = spec.projection
        if getattr(proj, "output_schema", None):
            return dict(proj.output_schema)
        return None
    out = {a.output: "f" for a in aggs}
    out["window_start"] = "t"
    out["window_end"] = "t"
    merged, _open = _merge_cols(in_cols) if in_cols else (None, False)
    if merged:
        for name, kind in merged.items():
            out.setdefault(name, kind)
    return out


# ---------------------------------------------------------------------------
# wiring audit: the engine half of the route-shift contract
# ---------------------------------------------------------------------------


def check_wiring_source(src: str, path: str) -> List[Finding]:
    """AST audit of the BinAgg wiring file: wherever ``make_bin_state``
    is used, a guarded ``set_route_shift(route_shift_for(...))`` call
    must exist — stripping it re-creates the PR 9 funnel (at operator
    parallelism > 1 the mesh routes on the same top key-hash bits the
    subtask ranges consume).  The seeded regression test feeds this
    function the REAL source with the wiring removed and requires the
    finding back."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(PASS_ID, "unparsable", path,
                        getattr(e, "lineno", 0) or 0,
                        f"could not parse wiring file: {e}")]
    make_line = None
    shift_calls: List[ast.Call] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            if name == "make_bin_state" and make_line is None:
                make_line = node.lineno
            if name == "set_route_shift":
                shift_calls.append(node)
    findings: List[Finding] = []
    if make_line is None:
        return findings  # no bin state built here: nothing to wire
    if not shift_calls:
        findings.append(Finding(
            PASS_ID, "route-shift-unwired", path, make_line,
            "make_bin_state is used here but no set_route_shift(...) "
            "wiring exists: at parallelism > 1 the mesh routes on the "
            "top key-hash bits subtask ranges already consumed — every "
            "subtask's keys funnel onto ~nk/P devices (the PR 9 bug "
            "class shardcheck exists to catch)"))
        return findings
    for call in shift_calls:
        arg = call.args[0] if call.args else None
        ok = (isinstance(arg, ast.Call)
              and isinstance(arg.func, (ast.Name, ast.Attribute))
              and (arg.func.id if isinstance(arg.func, ast.Name)
                   else arg.func.attr) == "route_shift_for")
        if not ok:
            findings.append(Finding(
                PASS_ID, "route-shift-contract", path, call.lineno,
                "set_route_shift is wired with an ad-hoc shift "
                "expression; use types.route_shift_for so the engine "
                "and the shardcheck static model cannot drift apart"))
    return findings


# ---------------------------------------------------------------------------
# lint repo pass: wiring audit + representative-plan sweep
# ---------------------------------------------------------------------------

# the canonical shapes the acceptance bar names: q5-shape hop
# aggregate, two-stream join, factored correlated windows.  Planning
# never runs a source, so the row counts are irrelevant.
_SWEEP_SQL: Dict[str, str] = {
    "q5-shape": """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000', num_events = '1000',
  rate_limited = 'false', batch_size = '256'
);
SELECT bid.auction as auction,
       HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) as window,
       count(*) AS num
FROM nexmark WHERE bid is not null GROUP BY 1, 2
""",
    "join": """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000', num_events = '1000',
  rate_limited = 'false', batch_size = '256'
);
WITH b AS (SELECT bid.auction AS auction, bid.price AS price
           FROM nexmark WHERE bid is not null),
     a AS (SELECT auction.id AS id, auction.reserve AS reserve
           FROM nexmark WHERE auction is not null)
SELECT X.auction AS auction, X.price AS price, Y.reserve AS reserve
FROM b X JOIN a Y ON X.auction = Y.id
""",
    "factored": """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000', num_events = '1000',
  rate_limited = 'false', batch_size = '256'
);
CREATE TABLE f1 (auction BIGINT, window_end BIGINT, num BIGINT) WITH (
  connector = 'memory', name = 'fw_a', type = 'sink');
CREATE TABLE f2 (auction BIGINT, window_end BIGINT, tot BIGINT) WITH (
  connector = 'memory', name = 'fw_b', type = 'sink');
INSERT INTO f1
SELECT bid.auction as auction,
       HOP(INTERVAL '2' SECOND, INTERVAL '10' SECOND) as window,
       count(*) AS num
FROM nexmark WHERE bid is not null GROUP BY 1, 2;
INSERT INTO f2
SELECT bid.auction as auction,
       HOP(INTERVAL '2' SECOND, INTERVAL '4' SECOND) as window,
       sum(bid.price) AS tot
FROM nexmark WHERE bid is not null GROUP BY 1, 2;
""",
    # config5-shape session windows on built-in aggregates (UDAF
    # registration is a runtime act, so the sweep uses count/avg; the
    # session RUN STATE placement is what this shape pins — the plan
    # must stay aligned with zero predicted reshards whether the runs
    # live on host dicts or device partitions)
    "sessions": """
CREATE TABLE nexmark WITH (
  connector = 'nexmark', event_rate = '1000', num_events = '1000',
  rate_limited = 'false', batch_size = '256'
);
SELECT bid.auction as auction,
       session(INTERVAL '1' SECOND) as window,
       count(*) AS num, avg(bid.price) AS mean_price
FROM nexmark WHERE bid is not null GROUP BY 1, 2
""",
}

_SWEEP_NK = 8  # symbolic mesh: the checks must hold without devices


def check_repo(root: str, full_scan: bool = True) -> List[Finding]:
    findings: List[Finding] = []
    wiring = os.path.join(root, "arroyo_tpu", _WIRING_FILE)
    if os.path.exists(wiring):
        with open(wiring, encoding="utf-8") as fh:
            findings.extend(check_wiring_source(fh.read(), wiring))
    if not full_scan:
        # single-file/editor invocations skip the representative-plan
        # sweep: it imports the whole planner stack and plans each SQL
        # shape twice — seconds of wall that can gate an unrelated file
        # on plan findings; the sweep runs on every whole-package lint
        return findings
    self_path = os.path.abspath(__file__)
    try:
        from ..sql import plan_sql
    except Exception as e:  # pragma: no cover - import surface only
        findings.append(Finding(
            PASS_ID, "analysis-error", self_path, 1,
            f"plan sweep unavailable (planner import failed: {e})"))
        return findings
    for name, sql in _SWEEP_SQL.items():
        for par in (1, 2):
            try:
                prog = plan_sql(sql, parallelism=par)
            except Exception as e:
                findings.append(Finding(
                    PASS_ID, "analysis-error", self_path, 1,
                    f"plan sweep: {name}@p{par} failed to plan: {e}"))
                continue
            rep = analyze(prog, nk=_SWEEP_NK)
            for d in rep.errors():
                findings.append(Finding(
                    PASS_ID, d.code, self_path, 1,
                    f"plan sweep {name}@p{par}: {d.render()}"))
            if rep.predicted_reshards:
                findings.append(Finding(
                    PASS_ID, "predicted-reshard", self_path, 1,
                    f"plan sweep {name}@p{par}: predicted "
                    f"{rep.predicted_reshards} reshard(s); the sharded "
                    "data plane contract is 0"))
    return findings
